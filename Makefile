# Convenience targets. `lint` is the arealint gate tier-1 also runs
# via tests/test_arealint.py::TestFrameworkAndGate::test_tree_is_clean;
# run it directly for instant feedback (pure AST, no jax, < 10 s).

PY ?= python

.PHONY: lint lint-diff test tier1

lint:
	$(PY) -m tools.arealint

# incremental: only files changed vs BASE (default: main) plus any
# cross-module rule whose anchor files changed
BASE ?= main
lint-diff:
	$(PY) -m tools.arealint --diff $(BASE)

# the tier-1 suite (ROADMAP.md's verify line, minus the harness pipefail
# wrapper); JAX_PLATFORMS=cpu matches CI
tier1:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

test: lint tier1
