"""areal_tpu — a TPU-native asynchronous RL post-training framework for LLMs.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of AReaL
(reference: Bruce-rl-hw/AReaL-vllm): fully-asynchronous GRPO/PPO with verifiable
rewards, a pjit-sharded SPMD trainer, a JAX generation engine with continuous
batching and interruptible decoding, and an async workflow executor with
staleness control connecting the two.

Layer map (mirrors reference areal/README.md:82-130, re-designed TPU-first):

- ``areal_tpu.api``      — contracts: configs, allocation DSL, engine/workflow APIs
- ``areal_tpu.models``   — functional transformer stacks (Qwen2/Llama family)
- ``areal_tpu.ops``      — jnp + Pallas kernels (packed attention, GAE, ppo math)
- ``areal_tpu.parallel`` — mesh construction, sharding rules, sequence parallelism
- ``areal_tpu.engine``   — train engines (SFT, PPO actor) and inference clients
- ``areal_tpu.inference``— the generation engine + HTTP server
- ``areal_tpu.utils``    — name_resolve, stats, packing, recover, etc.
"""

__version__ = "0.1.0"

import os as _os

# Raise the TPU scoped-VMEM limit before libtpu loads: the large splash
# blocks (ops/flash.py) need 64 MiB of scoped VMEM and lose 5x throughput
# at long context without it. Appending is a no-op if the backend already
# initialized (ops/flash.probe_block_size verifies the effective limit by
# actually compiling, so a late import degrades loudly, not silently).
_VMEM_FLAG = "--xla_tpu_scoped_vmem_limit_kib=65536"
if _VMEM_FLAG.split("=")[0] not in _os.environ.get("LIBTPU_INIT_ARGS", ""):
    _os.environ["LIBTPU_INIT_ARGS"] = (
        _os.environ.get("LIBTPU_INIT_ARGS", "") + " " + _VMEM_FLAG
    ).strip()
del _os
