"""areal_tpu — a TPU-native asynchronous RL post-training framework for LLMs.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of AReaL
(reference: Bruce-rl-hw/AReaL-vllm): fully-asynchronous GRPO/PPO with verifiable
rewards, a pjit-sharded SPMD trainer, a JAX generation engine with continuous
batching and interruptible decoding, and an async workflow executor with
staleness control connecting the two.

Layer map (mirrors reference areal/README.md:82-130, re-designed TPU-first):

- ``areal_tpu.api``      — contracts: configs, allocation DSL, engine/workflow APIs
- ``areal_tpu.models``   — functional transformer stacks (Qwen2/Llama family)
- ``areal_tpu.ops``      — jnp + Pallas kernels (packed attention, GAE, ppo math)
- ``areal_tpu.parallel`` — mesh construction, sharding rules, sequence parallelism
- ``areal_tpu.engine``   — train engines (SFT, PPO actor) and inference clients
- ``areal_tpu.inference``— the generation engine + HTTP server
- ``areal_tpu.utils``    — name_resolve, stats, packing, recover, etc.
"""

__version__ = "0.1.0"
