"""Allocation-mode DSL: how devices are split between training and generation.

Role of reference areal/api/alloc_mode.py (lark grammar, :253-320): a compact
string names the parallel layout of each component, e.g.

- ``d2t2p2``                     — colocated trainer, 5-D parallel factors
- ``jaxgen.d4t2``                — generation servers only
- ``jaxgen.d4t2+d8t1``           — decoupled: gen mesh + train mesh
- ``jaxgen.d4t2+fsdp:d8``        — decoupled with an explicit train backend
- ``jaxgen.d2+(attn:d2t2|ffn:d2e2)`` — MoE hybrid train spec (attn vs ffn)

Factors (any order, default 1): ``d`` data, ``t`` tensor, ``p`` pipeline,
``c`` context(sequence), ``e`` expert. TPU mapping: these become axis sizes of
a `jax.sharding.Mesh` (areal_tpu/parallel/mesh.py); "generation servers" are
JAX generation-engine processes on their own sub-slice.

Implemented as a small recursive-descent parser rather than a lark grammar —
the language is regular enough that a hand parser is clearer and dependency-free.
"""

import dataclasses
import enum
import re
from typing import Dict, Optional

GEN_BACKENDS = ("jaxgen", "sglang", "vllm")
TRAIN_BACKENDS = ("spmd", "fsdp", "megatron")

_FACTOR_RE = re.compile(r"([dtpce])(\d+)")
_SPEC_RE = re.compile(r"^(?:[dtpce]\d+)+$")


class AllocationType(enum.Enum):
    COLOCATE = "colocate"
    DECOUPLED_TRAIN = "decoupled_train"
    LLM_SERVER_ONLY = "llm_server_only"
    DECOUPLED_EVAL = "decoupled_eval"


class AllocationValidationError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class ParallelStrategy:
    """5-D parallel factors (reference alloc_mode.py:34 `ParallelStrategy`).

    On TPU these are mesh-axis sizes: (data·fsdp, context, tensor) for dense
    models, plus expert for MoE and pipeline for cross-slice stages.
    """

    data_parallel_size: int = 1
    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    context_parallel_size: int = 1
    expert_parallel_size: int = 1

    def __post_init__(self):
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if not isinstance(v, int) or v < 1:
                raise AllocationValidationError(f"{f.name} must be a positive int, got {v}")

    @property
    def world_size(self) -> int:
        return (
            self.data_parallel_size
            * self.tensor_parallel_size
            * self.pipeline_parallel_size
            * self.context_parallel_size
        )

    # expert data parallelism: experts are replicated over the remaining
    # non-expert degrees (reference alloc_mode.py:119-124).
    @property
    def expert_data_parallel_size(self) -> int:
        dcp = self.data_parallel_size * self.context_parallel_size
        if dcp % self.expert_parallel_size != 0:
            raise AllocationValidationError(
                f"d*c={dcp} not divisible by e={self.expert_parallel_size}"
            )
        return dcp // self.expert_parallel_size

    def to_str(self) -> str:
        out = []
        for ch, v in (
            ("d", self.data_parallel_size),
            ("t", self.tensor_parallel_size),
            ("p", self.pipeline_parallel_size),
            ("c", self.context_parallel_size),
            ("e", self.expert_parallel_size),
        ):
            if v != 1:
                out.append(f"{ch}{v}")
        return "".join(out) or "d1"

    def to_tpu_parallelism(self):
        """Map the DSL factors onto the TPU mesh axes, rejecting what the
        backend doesn't implement INSTEAD of silently misbehaving
        downstream: d → fsdp (ZeRO-style), c → seq, t → tensor; e is
        carved OUT of d (DSL semantics: experts shard within the d·c
        degrees — `expert_data_parallel_size` — so total devices stay
        d·t·c). p>1 is refused (XLA SPMD over the other axes is the TPU
        answer to the scales the reference reaches with its
        instruction-interpreted pipeline engine)."""
        from areal_tpu.api.cli_args import ParallelismConfig

        if self.pipeline_parallel_size > 1:
            raise AllocationValidationError(
                "pipeline parallelism (p>1) is not implemented on the TPU "
                "backend — use fsdp/tensor/seq/expert axes instead "
                f"(got {self.to_str()!r})"
            )
        import math

        e = self.expert_parallel_size
        d, c = self.data_parallel_size, self.context_parallel_size
        # experts shard within the d·c degrees (expert_data_parallel
        # semantics): carve e out of d first, then out of c
        ed = math.gcd(e, d)
        ec = e // ed
        if c % ec != 0:
            raise AllocationValidationError(
                f"e={e} must divide d*c={d * c} factorwise on the TPU "
                f"backend (experts shard within the data/context degrees; "
                f"got d={d}, c={c})"
            )
        return ParallelismConfig(
            data_parallel_size=1,
            fsdp_parallel_size=d // ed,
            tensor_parallel_size=self.tensor_parallel_size,
            seq_parallel_size=c // ec,
            expert_parallel_size=e,
        )

    @classmethod
    def from_str(cls, s: str) -> "ParallelStrategy":
        s = s.strip()
        if not _SPEC_RE.match(s):
            raise AllocationValidationError(f"bad parallel spec: {s!r}")
        factors: Dict[str, int] = {}
        for ch, num in _FACTOR_RE.findall(s):
            if ch in factors:
                raise AllocationValidationError(f"duplicate factor {ch!r} in {s!r}")
            factors[ch] = int(num)
        return cls(
            data_parallel_size=factors.get("d", 1),
            tensor_parallel_size=factors.get("t", 1),
            pipeline_parallel_size=factors.get("p", 1),
            context_parallel_size=factors.get("c", 1),
            expert_parallel_size=factors.get("e", 1),
        )


@dataclasses.dataclass(frozen=True)
class HybridTrainStrategy:
    """MoE hybrid spec: distinct layouts for attention vs expert(ffn) blocks
    (reference ``(attn:d2t2|ffn:d2e2)`` form, alloc_mode.py:81-124)."""

    attn: ParallelStrategy
    ffn: ParallelStrategy

    def __post_init__(self):
        attn_ws = self.attn.world_size
        # on the ffn side `d` is expert-data parallelism, so experts occupy
        # d × c × t × p × e devices (reference alloc_mode.py:81-124)
        ffn_ws = self.ffn.world_size * self.ffn.expert_parallel_size
        if attn_ws != ffn_ws:
            raise AllocationValidationError(
                f"attn world size {attn_ws} != ffn world size {ffn_ws}"
            )

    @property
    def world_size(self) -> int:
        return self.attn.world_size


@dataclasses.dataclass(frozen=True)
class AllocationMode:
    """Parsed allocation string (reference alloc_mode.py:294 `from_str`)."""

    type_: AllocationType
    train: Optional[ParallelStrategy] = None
    gen: Optional[ParallelStrategy] = None
    gen_backend: Optional[str] = None
    train_backend: Optional[str] = None
    train_hybrid: Optional[HybridTrainStrategy] = None

    @property
    def train_world_size(self) -> int:
        if self.train_hybrid is not None:
            return self.train_hybrid.world_size
        return self.train.world_size if self.train else 0

    @property
    def gen_world_size(self) -> int:
        return self.gen.world_size if self.gen else 0

    @property
    def world_size(self) -> int:
        return self.train_world_size + self.gen_world_size

    @classmethod
    def from_str(cls, s: str) -> "AllocationMode":
        s = s.strip().replace(" ", "")
        if not s:
            raise AllocationValidationError("empty allocation string")
        parts = _split_top(s, "+")
        if len(parts) > 2:
            raise AllocationValidationError(f"too many '+' components in {s!r}")
        if len(parts) == 2:
            gen_backend, gen = _parse_gen(parts[0])
            train_backend, train, hybrid = _parse_train(parts[1])
            return cls(
                type_=AllocationType.DECOUPLED_TRAIN,
                train=train,
                gen=gen,
                gen_backend=gen_backend,
                train_backend=train_backend,
                train_hybrid=hybrid,
            )
        part = parts[0]
        # "backend.spec" → server only; bare spec → colocate
        prefix = _backend_prefix(part)
        if prefix in GEN_BACKENDS:
            gen_backend, gen = _parse_gen(part)
            return cls(type_=AllocationType.LLM_SERVER_ONLY, gen=gen, gen_backend=gen_backend)
        train_backend, train, hybrid = _parse_train(part)
        return cls(
            type_=AllocationType.COLOCATE,
            train=train,
            gen=train,
            train_backend=train_backend,
            train_hybrid=hybrid,
        )

    def to_str(self) -> str:
        if self.type_ == AllocationType.LLM_SERVER_ONLY:
            return f"{self.gen_backend}.{self.gen.to_str()}"
        if self.train_hybrid is not None:
            train = f"(attn:{self.train_hybrid.attn.to_str()}|ffn:{self.train_hybrid.ffn.to_str()})"
        else:
            train = self.train.to_str() if self.train else ""
        if self.train_backend:
            train = f"{self.train_backend}:{train}"
        if self.type_ == AllocationType.COLOCATE:
            return train
        return f"{self.gen_backend}.{self.gen.to_str()}+{train}"


def _split_top(s: str, sep: str):
    """Split on `sep` outside parentheses."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth < 0:
                raise AllocationValidationError(f"unbalanced parens in {s!r}")
        if ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if depth != 0:
        raise AllocationValidationError(f"unbalanced parens in {s!r}")
    parts.append("".join(cur))
    return parts


def _backend_prefix(s: str) -> Optional[str]:
    for sep in (".", ":"):
        if sep in s:
            head = s.split(sep, 1)[0]
            if head.isalpha():
                return head
    return None


def _parse_gen(s: str):
    prefix = _backend_prefix(s)
    if prefix is None:
        raise AllocationValidationError(
            f"generation spec {s!r} needs a backend prefix, e.g. 'jaxgen.{s}'"
        )
    if prefix not in GEN_BACKENDS:
        raise AllocationValidationError(
            f"unknown generation backend {prefix!r} (known: {GEN_BACKENDS})"
        )
    body = s[len(prefix) + 1 :]
    strat = ParallelStrategy.from_str(body)
    if strat.pipeline_parallel_size != 1 or strat.expert_parallel_size != 1:
        # generation engine scales by server replicas (d) × tensor (t) × context (c)
        raise AllocationValidationError(
            f"generation spec {s!r}: p/e factors are not supported on the gen side"
        )
    return prefix, strat


def _parse_train(s: str):
    backend = None
    prefix = _backend_prefix(s)
    if prefix is not None and not s.startswith("("):
        if prefix in TRAIN_BACKENDS:
            backend = prefix
            s = s[len(prefix) + 1 :]
        elif prefix in GEN_BACKENDS:
            raise AllocationValidationError(f"gen backend {prefix!r} in train position")
        elif not _SPEC_RE.match(s):
            raise AllocationValidationError(f"unknown train backend {prefix!r}")
    if s.startswith("("):
        if not s.endswith(")"):
            raise AllocationValidationError(f"bad hybrid spec {s!r}")
        inner = s[1:-1]
        sides = _split_top(inner, "|")
        if len(sides) != 2:
            raise AllocationValidationError(f"hybrid spec needs attn|ffn: {s!r}")
        spec = {}
        for side in sides:
            if ":" not in side:
                raise AllocationValidationError(f"bad hybrid component {side!r}")
            name, body = side.split(":", 1)
            if name not in ("attn", "ffn"):
                raise AllocationValidationError(f"hybrid component must be attn/ffn: {name!r}")
            spec[name] = ParallelStrategy.from_str(body)
        if set(spec) != {"attn", "ffn"}:
            raise AllocationValidationError(f"hybrid spec needs both attn and ffn: {s!r}")
        hybrid = HybridTrainStrategy(attn=spec["attn"], ffn=spec["ffn"])
        return backend, spec["attn"], hybrid
    return backend, ParallelStrategy.from_str(s), None
