"""Experiment configuration dataclasses + YAML/CLI loading.

Role of reference areal/api/cli_args.py: every experiment is a nested
dataclass tree, loaded from a YAML file (``--config path.yaml``) and
overridden by dotted CLI args (``actor.optimizer.lr=1e-5``). The reference
uses OmegaConf; here a small recursive merge over ``dataclasses.fields`` does
the same job dependency-free.
"""

import argparse
import dataclasses
import enum
import os
import sys
import typing
from typing import Any, Dict, List, Optional, Tuple, Type, TypeVar

import yaml

T = TypeVar("T")


# --------------------------------------------------------------------------
# Generation
# --------------------------------------------------------------------------
@dataclasses.dataclass
class GenerationHyperparameters:
    """Sampling options for rollout (reference cli_args.py:82)."""

    n_samples: int = 1
    max_new_tokens: int = 512
    min_new_tokens: int = 0
    greedy: bool = False
    top_p: float = 1.0
    top_k: int = 0  # 0 disables top-k
    temperature: float = 1.0
    stop_token_ids: List[int] = dataclasses.field(default_factory=list)

    def new(self, **kwargs) -> "GenerationHyperparameters":
        return dataclasses.replace(self, **kwargs)


# --------------------------------------------------------------------------
# Training
# --------------------------------------------------------------------------
@dataclasses.dataclass
class OptimizerConfig:
    """optax optimizer spec (reference cli_args.py:140)."""

    type: str = "adamw"
    lr: float = 2e-5
    weight_decay: float = 0.05
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-8
    min_lr_ratio: float = 0.0
    lr_scheduler_type: str = "constant"  # constant | linear | cosine
    warmup_steps_proportion: float = 0.001
    gradient_clipping: float = 1.0
    offload_optimizer_state: bool = False


@dataclasses.dataclass
class MicroBatchSpec:
    """Token-budget micro-batching (reference api/cli_args MicroBatchSpec)."""

    n_mbs: int = 1
    max_tokens_per_mb: int = 32768


@dataclasses.dataclass
class ParallelismConfig:
    """Trainer mesh axis sizes. On TPU these build one
    jax.sharding.Mesh with axes (data, fsdp, seq, tensor); data×fsdp shards
    the batch + optimizer state, seq is Ulysses-style sequence parallelism,
    tensor shards weights within attention/MLP blocks."""

    data_parallel_size: int = 1
    fsdp_parallel_size: int = 1
    tensor_parallel_size: int = 1
    seq_parallel_size: int = 1
    # MoE expert-parallel degree (experts shard over this mesh axis)
    expert_parallel_size: int = 1
    # cross-SLICE data parallelism over DCN: the mesh's data axis becomes
    # (dcn_data * data) with device order arranged slice-major, so only
    # the once-per-step grad psum crosses DCN while fsdp/seq/tensor/expert
    # collectives stay on each slice's ICI (how meshes larger than one ICI
    # domain scale — the reference's multi-node 32B recipes' analog)
    dcn_data_parallel_size: int = 1
    # cross-SLICE fsdp over DCN: the fsdp axis becomes (dcn_fsdp * fsdp)
    # with the OUTER fsdp positions striding across slices — parameter and
    # optimizer shards span slices, so a model too big for ONE slice's HBM
    # (the 32B recipe) still fits, at the cost of fsdp all-gathers riding
    # DCN. Prefer dcn_data when the model fits a slice.
    dcn_fsdp_parallel_size: int = 1

    @property
    def world_size(self) -> int:
        return (
            self.dcn_data_parallel_size
            * self.dcn_fsdp_parallel_size
            * self.data_parallel_size
            * self.fsdp_parallel_size
            * self.tensor_parallel_size
            * self.seq_parallel_size
            * self.expert_parallel_size
        )


@dataclasses.dataclass
class TrainEngineConfig:
    """Train-engine spec (reference cli_args.py:223)."""

    experiment_name: str = ""
    trial_name: str = ""
    path: str = ""  # HF checkpoint path or model preset name
    init_from_scratch: bool = False
    dtype: str = "bfloat16"  # compute dtype (MXU-friendly)
    param_dtype: str = "float32"  # parameter/optimizer storage (master weights)
    disable_dropout: bool = True
    gradient_checkpointing: bool = True
    # with remat on, SAVE each layer's attention output instead of
    # recomputing the flash kernel in the backward (~14ms/layer at 24k for
    # [B,T,Hq,D] bf16 of HBM); disable for memory-tight shapes
    remat_save_attn: bool = True
    # attention kernel when seq_parallel_size > 1: "auto" lets GSPMD shard
    # the XLA kernel; "ring"/"ulysses" use the explicit shard_map kernels
    attn_impl: str = "auto"
    # lazy chunked LM head: loss paths never materialize [T, vocab] logits
    # (the largest train activation — 3.2 GB for one 24k row at 32k vocab);
    # disable for custom loss fns that index the vocab axis directly
    chunked_lm_head: bool = True
    mb_spec: MicroBatchSpec = dataclasses.field(default_factory=MicroBatchSpec)
    optimizer: Optional[OptimizerConfig] = dataclasses.field(default_factory=OptimizerConfig)
    parallel: ParallelismConfig = dataclasses.field(default_factory=ParallelismConfig)
    backend: str = "spmd"


@dataclasses.dataclass
class AdvNormConfig:
    """Advantage normalization (reference ppo/actor.py:370 `AdvNorm`)."""

    mean_level: str = "batch"  # batch | group | none
    std_level: str = "batch"  # batch | group | none
    group_size: int = 1


@dataclasses.dataclass
class PPOActorConfig(TrainEngineConfig):
    """GRPO/PPO algorithm options (reference cli_args.py:274)."""

    group_size: int = 1  # answers per prompt (GRPO group)
    ppo_n_minibatches: int = 4
    eps_clip: float = 0.2
    eps_clip_higher: Optional[float] = None  # asymmetric upper clip (DAPO)
    c_clip: Optional[float] = None  # dual clip
    temperature: float = 1.0
    gamma: float = 1.0
    lam: float = 1.0
    reward_scaling: float = 1.0
    reward_bias: float = 0.0
    reward_clip: float = 20.0
    group_reward_norm: bool = False
    adv_norm: AdvNormConfig = dataclasses.field(default_factory=AdvNormConfig)
    kl_ctl: float = 0.0
    recompute_logprob: bool = True
    use_decoupled_loss: bool = True
    behav_imp_weight_cap: Optional[float] = None
    dynamic_sampling: bool = False
    # overlong reward penalty (DAPO; reference utils/functional.py:237)
    overlong_reward_penalty: bool = False
    overlong_tokens: int = 0
    overlong_penalty_factor: float = 0.0
    max_new_tokens: int = 512
    # adaptive KL controller (reference
    # realhf/impl/model/utils/ppo_functional.py:14-49): when kl_adaptive,
    # kl_ctl is the INITIAL coefficient, adapted toward kl_target over
    # kl_horizon tokens
    kl_adaptive: bool = False
    kl_target: float = 0.1
    kl_horizon: float = 10000.0


@dataclasses.dataclass
class PPOCriticConfig(TrainEngineConfig):
    """Value-model options (reference PPOCriticInterface,
    realhf/impl/model/interface/ppo_interface.py:984)."""

    is_critic: bool = True
    value_eps_clip: float = 0.2
    ppo_n_minibatches: int = 4
    temperature: float = 1.0


# --------------------------------------------------------------------------
# Inference / rollout
# --------------------------------------------------------------------------
@dataclasses.dataclass
class InferenceEngineConfig:
    """Async rollout control (reference cli_args.py:531)."""

    experiment_name: str = ""
    trial_name: str = ""
    max_concurrent_rollouts: Optional[int] = None
    queue_size: Optional[int] = None
    # Unit = episodes (prompts), NOT sequences: wait()/get_capacity() count
    # one per submitted workflow item, and each RLVR episode carries
    # gconfig.n_samples sequences. Set this to the dataloader batch size.
    consumer_batch_size: int = 1
    max_head_offpolicyness: int = 0  # staleness η: max model-version lead
    enable_rollout_tracing: bool = False
    schedule_policy: str = "round_robin"  # round_robin | least_requests
    request_timeout: float = 3600.0
    request_retries: int = 3
    setup_timeout: float = 120.0
    # bound on the pause→transfer→version-bump window of a weight update:
    # a failed upload must not hold servers paused for request_timeout
    weight_update_timeout: float = 300.0
    pause_grace_period: float = 0.0
    # chunked partial rollout (reference realhf/system/partial_rollout.py:29
    # PartialRolloutManager): each /generate asks for at most this many new
    # tokens, so weight updates interleave at chunk boundaries even without
    # server-side aborts; 0 = request everything at once
    new_tokens_per_chunk: int = 0
    # client-side request-lifecycle spans (submit→first-token→complete,
    # weight-update pause windows)
    tracing: "TracingConfig" = dataclasses.field(
        default_factory=lambda: TracingConfig()
    )
    # fleet resilience plane (inference/fleet.py): health probing, circuit
    # breaking, failover-aware generation, dynamic membership
    fleet: "FleetConfig" = dataclasses.field(
        default_factory=lambda: FleetConfig()
    )
    # trainer-side durability plane (api/workflow_api.py): episode retry
    # with poison quarantine, sliding-window failure budget → DEGRADED
    # state, prepare_batch deadline + dead-fleet health probe
    durability: "DurabilityConfig" = dataclasses.field(
        default_factory=lambda: DurabilityConfig()
    )
    # fleet telemetry hub (utils/telemetry.TelemetryCollector): when
    # enabled, the remote engine starts a collector over its fleet
    # (FleetMonitor membership + the executor's lineage ledger) and
    # serves the consolidated /metrics + /manifest hub endpoint
    telemetry: "TelemetryConfig" = dataclasses.field(
        default_factory=lambda: TelemetryConfig()
    )
    # router-scheduled mode: when set ("host:port"), agenerate asks the
    # fronting router's POST /schedule_request for a server each chunk
    # (qid affinity + global load view) instead of the client-local
    # policy, forwarding the trace context so the router lands on the
    # same stitched timeline; empty = client-local choose_server
    router_addr: str = ""
    # SLO-aware traffic plane: class weights, tenant caps, router
    # shedding thresholds, and the fleet autoscaler envelope. The client
    # reads `tenant` (default stamp) here; the router and launcher read
    # the admission/autoscale knobs
    traffic: "TrafficConfig" = dataclasses.field(
        default_factory=lambda: TrafficConfig()
    )
    # zero-pause weight plane (r13): when True, update_weights never
    # POSTs /pause_generation — the trainer streams chunks at live
    # servers, each server applies them into a shadow buffer
    # (inference/weights.WeightStore) and flips at a dispatch boundary.
    # The client records a `weight_stream` span instead of a
    # `weight_update_pause` window. False restores the r2 pause
    # protocol (the bench A/B baseline; also the right setting against
    # pre-r13 servers, whose chunk ingest stalls decode per chunk).
    streamed_weight_updates: bool = True
    # staleness admission mode (api/workflow_api.WorkflowExecutor):
    # "step" = the legacy global gate ((eta + version + 1) * batch
    # bounds accepted+running); "trajectory" = per-sample admission —
    # capacity is bounded by max_concurrent_rollouts alone and wait()
    # drops any sample whose staleness-at-consumption (trainer version
    # minus the oldest weight version that produced one of its tokens,
    # from the LineageLedger) exceeds max_head_offpolicyness, refilling
    # the batch with a fresh generation. Trajectory mode is what makes
    # streamed weight flips safe at eta=0-ish targets: the fence is
    # enforced on what the trainer CONSUMES, not on what may run.
    staleness_mode: str = "step"
    # trajectory lineage ledger (utils/telemetry.LineageLedger): consumed
    # records are appended here as JSONL when set (the in-memory ledger
    # is always on; recover checkpoints snapshot it either way)
    lineage_path: str = ""
    # bounded in-memory lineage records (oldest consumed drop first)
    lineage_max_records: int = 8192


@dataclasses.dataclass
class JaxGenConfig:
    """Generation-engine/server spec — the analog of the reference's
    SGLangConfig (cli_args.py:458), but describing the in-repo JAX engine."""

    model_path: str = ""
    dtype: str = "bfloat16"
    seed: int = 1
    max_num_seqs: int = 64  # decode slots
    max_model_len: int = 4096
    prefill_chunk: int = 512
    # --- chunked prefill (r15): bounded interactive TTFT ---
    # split a long prompt's prefill into page-aligned chunks admitted
    # across successive waves and interleaved with decode dispatches:
    # each chunk publishes its committed pages into the prefix cache
    # (publish-at-chunk-commit) and the next chunk resumes by claiming
    # them, so time-to-first-token for a request admitted behind a bulk
    # prompt is bounded by ~one chunk's latency instead of the longest
    # prefill in flight — and chunk boundaries become cheap preemption
    # points for deadline-pressed interactive traffic. Requires a
    # prefix cache (prefix_reuse_min > 0). Greedy streams are
    # bit-identical chunked on/off; off is a strict no-op (unchanged
    # programs, no new metric keys).
    chunked_prefill: bool = False
    # per-dispatch prefill token budget when chunking (floored to a
    # page multiple, min one page, must be >= prefix_reuse_min;
    # 0 = auto: 2 x prefill_chunk)
    prefill_chunk_tokens: int = 0
    # decode steps fused into one device dispatch (amortizes the host
    # round-trip; stop handling happens on device so at most one dispatch
    # of latency is added to a finished request)
    decode_chunk: int = 8
    # decode chunks kept in flight while the previous chunk's results are
    # fetched/processed on host (0 = fully synchronous). Overlapping hides
    # the host round-trip — essential over a driver tunnel, still worth a
    # dispatch latency on a local chip
    decode_pipeline: int = 1
    # --- decode tail compaction (r6) ---
    # dispatch decode over a pow2 bucket of ACTIVE slots instead of the
    # full max_num_seqs slot array: during the straggler tail of a GRPO
    # wave the fused scan, paged attention, and sampling stop paying for
    # finished rows. Per-slot state is gathered into the compact row
    # space before dispatch and scattered back after; sampling is keyed
    # by SLOT id (not row position), so token streams are identical with
    # compaction on or off. Single-device only (TP serving keeps the
    # full-slot dispatch)
    decode_compact: bool = True
    # smallest compact row bucket — floors the recompile ladder (row
    # shapes are pow2: min_rows, 2*min_rows, ..., max_num_seqs)
    decode_compact_min_rows: int = 4
    # consecutive chunks the active count must sit below the current
    # bucket's shrink target before the bucket shrinks (growth is always
    # immediate); damps recompile thrash when requests finish raggedly
    decode_compact_hysteresis: int = 4
    # unique prompts prefilled in one batched dispatch (rows are padded to
    # this wave size so the program shape is static per bucket); identical
    # prompts (GRPO siblings) share one row + a KV line copy
    admit_wave: int = 8
    # newly queued requests are held up to this long (while decode has work
    # or the queue is still filling) so admission waves arrive full — every
    # distinct wave shape is a separate XLA compile
    admit_hold_s: float = 0.05
    # decode attention reads cache lines bucketed to this quantum above the
    # longest active sequence (instead of always max_model_len)
    kv_bucket: int = 256
    # lax.top_k candidate count for truncated sampling (raised to the max
    # requested per-slot top_k); 0 would force the exact full-vocab sort
    sample_topk_bound: int = 64
    # reuse freed requests' cached KV (prefix cache) when >= this many
    # prompt tokens match (0 disables prefix reuse); matches are shared at
    # page granularity by refcount, not copied
    prefix_reuse_min: int = 16
    # prefix-cache implementation: "radix" (r9 default — refcounted radix
    # tree over the paged pool, O(prompt) descent, publish-at-prefill-
    # commit so GRPO siblings/agentic turns claim a live request's prompt
    # pages, COW claims for divergence within a partial tail page) or
    # "flat" (the r1-r8 free-time-only linear-scan registry, kept as the
    # bench A/B baseline). prefix_reuse_min=0 disables both.
    prefix_cache_mode: str = "radix"
    # --- hierarchical KV tiers (r16, inference/kv_tiers.py) ---
    # spill radix leaves to a host-RAM tier on eviction instead of
    # dropping them; claims promote spilled pages back to the device
    # pool (batched scatter) BEFORE the wave dispatches. Radix mode
    # only. Off = strict no-op (greedy streams bit-identical, no new
    # metric keys).
    kv_spill: bool = False
    # host-tier capacity in bytes (per server); LRU pages past the
    # budget drop to disk when kv_disk_path is set, else vanish
    host_kv_bytes: int = 1 << 30
    # optional third tier: directory for LRU-overflow page files
    # (empty = no disk tier)
    kv_disk_path: str = ""
    # cross-server prefix shipping: serve GET/POST /kv_export and
    # accept /kv_import + /generate kv_ship_from hints, so a router
    # affinity miss re-homes a session's committed prefix instead of
    # re-prefilling it. Radix mode only; independent of kv_spill.
    kv_ship: bool = False
    # --- paged KV pool (the radix/paged-cache analog) ---
    page_size: int = 256  # tokens per KV page
    # total pages in the pool; 0 = auto (full provisioning: every slot can
    # reach max_model_len). Set explicitly to oversubscribe — the engine
    # preempts transparently under pool pressure, which is what makes
    # 16k+ max_model_len serveable without 16k*slots of HBM
    num_pages: int = 0
    # paged-attention backend: "auto" (Pallas kernel on single-device TPU,
    # jnp gather elsewhere), "kernel", or "jnp"
    attn_impl: str = "auto"
    pages_per_compute_block: int = 4  # kernel flash-block size, in pages
    slots_per_block: int = 8  # kernel grid-step slot grouping
    # KV pool row layout: "token_packed" (row = 128//D tokens of one head)
    # or "head_merged" (row = all kv heads of 128//(Hkv*D) tokens — one
    # DMA per page moves every head; needs Hkv*D | 128). r6: "auto" now
    # resolves to head_merged whenever the geometry allows it on a
    # single-device engine (ops/paged_attention.resolve_pool_layout —
    # parity-pinned in tests/test_pool_layout.py and
    # tests/test_paged_kernel_parity.py); TP serving stays token_packed
    # (the pool's kv-head dim is the TP shard axis).
    pool_layout: str = "auto"
    # --- SLO traffic plane (server side) ---
    # bounded admission queue: with more than this many requests queued
    # (admit queue + pending), new BULK submissions are shed with a
    # typed 429 + Retry-After instead of queueing unboundedly behind
    # max_num_seqs; interactive submissions are shed only past twice
    # the bound (protected, not unbounded). 0 = unbounded (legacy).
    max_queued_requests: int = 0
    # Retry-After seconds attached to shed responses
    shed_retry_after_s: float = 1.0
    # deadline-aware preemption: a queued INTERACTIVE request carrying a
    # soft deadline that is about to miss it (inside this margin, or
    # having already waited half its deadline budget with no free slot)
    # preempts the youngest BULK request; the victim resumes via the
    # prefix-cache re-queue path (zero lost rollouts). False disables.
    deadline_preemption: bool = True
    deadline_margin_s: float = 0.25
    # persistent XLA compilation cache directory ("" = disabled). The
    # decode bucket ladder compiles O(100) programs on a cold engine
    # (378 s of warmup in the r5 bench capture); a warm cache replays
    # them from disk. Wired through the server CLI and launcher env
    # (JAX_COMPILATION_CACHE_DIR) so subprocess servers share it.
    compilation_cache_dir: str = ""
    tensor_parallel_size: int = 1
    mem_fraction: float = 0.85
    enable_metrics: bool = True
    # draft-free speculative decoding (r7): host-side n-gram proposals
    # verified by one multi-token dispatch with KV rollback
    # (inference/spec.py + model_runner.spec_verify). Off by default —
    # disabled is a strict no-op (no extra dispatches, no metric keys)
    spec: "SpecConfig" = dataclasses.field(
        default_factory=lambda: SpecConfig()
    )
    # engine-side request-lifecycle spans (queue-wait, prefill, decode,
    # preemption, weight-update windows); drained over GET /trace
    tracing: "TracingConfig" = dataclasses.field(
        default_factory=lambda: TracingConfig()
    )
    # goodput attribution (utils/goodput.py): engine wall-clock ledger
    # (prefill/decode/spec_verify/weight_pause/compile/idle), compile
    # event stream, and the warming→ready /health readiness rule
    goodput: "GoodputConfig" = dataclasses.field(
        default_factory=lambda: GoodputConfig()
    )
    # zero-pause weight plane (inference/weights.WeightStore): streamed
    # double-buffered weight ingest + atomic flip at a dispatch
    # boundary, in-flight-request version pinning, staging TTL
    weights: "WeightTransferConfig" = dataclasses.field(
        default_factory=lambda: WeightTransferConfig()
    )
    # multi-policy serving plane (inference/policies.PolicyRegistry):
    # named policy handles with independent version lines, canary
    # splits, per-(policy, version) KV namespaces, and LRU HBM→host
    # demotion of cold policy buffers
    policy: "PolicyConfig" = dataclasses.field(
        default_factory=lambda: PolicyConfig()
    )
    # cold-start elimination (inference/precompile.py): AOT-precompile
    # the exact shape ladder (or replay a prior run's compile events)
    # before/while serving, seeding the persistent compile cache
    precompile: "PrecompileConfig" = dataclasses.field(
        default_factory=lambda: PrecompileConfig()
    )
    log_level: str = "info"
    host: str = "127.0.0.1"
    port: int = 0  # 0 = auto

    @staticmethod
    def build_cmd(
        config: "JaxGenConfig",
        host: str,
        port: int,
        experiment_name: str = "",
        trial_name: str = "",
    ) -> List[str]:
        """Command line for a standalone generation server process."""
        args = [
            sys.executable,
            "-m",
            "areal_tpu.inference.server",
            f"--model-path={config.model_path}",
            f"--host={host}",
            f"--port={port}",
            f"--max-num-seqs={config.max_num_seqs}",
            f"--max-model-len={config.max_model_len}",
            f"--dtype={config.dtype}",
            f"--tensor-parallel-size={config.tensor_parallel_size}",
            f"--seed={config.seed}",
        ]
        if experiment_name:
            args.append(f"--experiment-name={experiment_name}")
        if trial_name:
            args.append(f"--trial-name={trial_name}")
        if config.tracing.enabled:
            args.append("--trace")
        if config.compilation_cache_dir:
            args.append(
                f"--compilation-cache-dir={config.compilation_cache_dir}"
            )
        # engine shape/batching knobs: forwarded unconditionally so a
        # launched server always serves exactly this config — a flag
        # missing here means subprocess servers silently run defaults
        # (the deadline_margin_s bug class; arealint ARL002 pins the
        # field ↔ flag ↔ build_cmd parity)
        if config.chunked_prefill:
            args.append("--chunked-prefill")
        args += [
            f"--prefill-chunk-tokens={config.prefill_chunk_tokens}",
            f"--prefill-chunk={config.prefill_chunk}",
            f"--decode-chunk={config.decode_chunk}",
            f"--decode-pipeline={config.decode_pipeline}",
            f"--decode-compact-min-rows={config.decode_compact_min_rows}",
            (
                "--decode-compact-hysteresis="
                f"{config.decode_compact_hysteresis}"
            ),
            f"--admit-wave={config.admit_wave}",
            f"--admit-hold={config.admit_hold_s}",
            f"--kv-bucket={config.kv_bucket}",
            f"--sample-topk-bound={config.sample_topk_bound}",
            f"--page-size={config.page_size}",
            f"--num-pages={config.num_pages}",
            f"--attn-impl={config.attn_impl}",
            f"--pages-per-compute-block={config.pages_per_compute_block}",
            f"--slots-per-block={config.slots_per_block}",
            f"--pool-layout={config.pool_layout}",
            f"--mem-fraction={config.mem_fraction}",
            f"--log-level={config.log_level}",
        ]
        if not config.decode_compact:
            args.append("--no-decode-compact")
        if not config.enable_metrics:
            args.append("--disable-metrics")
        # hierarchical KV tiers (r16): spill/ship servers must agree
        # with the client's config or affinity misses re-prefill
        if config.kv_spill:
            args += [
                "--kv-spill",
                f"--host-kv-bytes={config.host_kv_bytes}",
            ]
            if config.kv_disk_path:
                args.append(f"--kv-disk-path={config.kv_disk_path}")
        if config.kv_ship:
            args.append("--kv-ship")
        args += [
            f"--prefix-cache-mode={config.prefix_cache_mode}",
            f"--prefix-reuse-min={config.prefix_reuse_min}",
            f"--ready-quiet={config.goodput.ready_quiet_s}",
            f"--ready-min-requests={config.goodput.ready_min_requests}",
        ]
        if config.tracing.enabled:
            args.append(f"--trace-max-spans={config.tracing.max_spans}")
        if config.goodput.compile_events_path:
            args.append(
                f"--compile-events={config.goodput.compile_events_path}"
            )
        args.append(
            "--compile-events-max-bytes="
            f"{config.goodput.compile_events_max_bytes}"
        )
        # cold-start elimination (r14): launched servers warm their
        # shape ladder before/while opening for traffic
        if config.precompile.mode != "off":
            args.append(f"--precompile={config.precompile.mode}")
            if config.precompile.replay_path:
                args.append(
                    f"--precompile-replay={config.precompile.replay_path}"
                )
        if config.goodput.jsonl_path:
            args.append(f"--goodput-jsonl={config.goodput.jsonl_path}")
        if config.max_queued_requests > 0:
            args += [
                f"--max-queued-requests={config.max_queued_requests}",
                f"--shed-retry-after={config.shed_retry_after_s}",
            ]
        args.append(f"--deadline-margin={config.deadline_margin_s}")
        if not config.deadline_preemption:
            args.append("--no-deadline-preemption")
        # zero-pause weight plane (r13): streamed servers must agree
        # with the client's streamed_weight_updates setting, so the
        # whole weight config always rides the command line
        args += [
            f"--weight-flip-policy={config.weights.flip_policy}",
            f"--weight-staging-ttl={config.weights.staging_ttl_s}",
            f"--policy-max-resident={config.policy.max_resident}",
        ]
        if not config.weights.streaming:
            args.append("--no-weight-streaming")
        if config.spec.enabled:
            args += [
                "--spec",
                f"--spec-max-draft={config.spec.max_draft}",
                f"--spec-ngram-min={config.spec.ngram_min}",
                f"--spec-ngram-max={config.spec.ngram_max}",
                f"--spec-accept-floor={config.spec.accept_floor}",
                f"--spec-disable-patience={config.spec.disable_patience}",
            ]
        return args


# --------------------------------------------------------------------------
# Aux subsystems
# --------------------------------------------------------------------------
@dataclasses.dataclass
class SpecConfig:
    """Draft-free speculative decoding (inference/spec.py proposers +
    the multi-token verify dispatch in inference/model_runner.py).

    A host-side proposer (n-gram self-speculation: suffix match against
    the request's own prompt+output — no draft model) guesses up to
    ``max_draft`` continuation tokens per slot; ONE device dispatch
    scores every position causally and accepts the longest prefix the
    model itself would have produced. Greedy streams are bit-identical
    with speculation on or off (exact-match acceptance); sampled streams
    keep their exact distribution (every kept token is drawn from the
    true conditional under an independent key). Rejected positions roll
    back: their K/V never reach the paged pool and cache-length
    accounting matches a non-speculative run. Single-device dense
    serving only (TP keeps the full-slot dispatch; MoE capacity routing
    is batch-dependent)."""

    enabled: bool = False
    # draft tokens proposed per verify round; the verify window is
    # max_draft + 1 positions (current token + drafts)
    max_draft: int = 4
    # suffix n-gram lengths tried for the history match (longest first)
    ngram_min: int = 2
    ngram_max: int = 4
    # auto-disable hysteresis: speculation turns off (sticky) when the
    # accept-rate EWMA stays below this floor for ``disable_patience``
    # consecutive verify chunks; <= 0 never disables
    accept_floor: float = 0.1
    disable_patience: int = 32


@dataclasses.dataclass
class WeightTransferConfig:
    """Zero-pause weight plane, server side (inference/weights.py
    `WeightStore` + the engine flip machinery).

    With ``streaming`` on, weight updates never stop decode: chunked
    device-path pushes (and disk reloads) are staged into a shadow
    buffer on the HTTP handler thread while the engine loop keeps
    dispatching on version N, then the completed buffer flips in
    atomically BETWEEN dispatches — no ``pause_window`` span is ever
    emitted. Correctness across the flip is a version fence, not
    bit-exactness: every token records the weight version that produced
    it, and in-flight sequences either finish pinned to N
    (``flip_policy="pin"`` — the store keeps N's buffer alive until its
    last pinned request drains, and the engine dispatches each version
    cohort with its own params) or resolve with ``stop_reason="abort"``
    and resume suffix-exact on N+1 (``flip_policy="resume"`` — the
    existing interruption contract, minus the fleet-wide pause).
    ``pin`` needs the compacted decode dispatch (single-device); TP and
    compaction-off engines degrade to ``resume`` at the flip."""

    streaming: bool = True
    # "pin" | "resume" (see above). Unknown values are an init error.
    flip_policy: str = "pin"
    # abandoned-staging GC: a client that dies mid-stream must not pin
    # host/HBM staging bytes forever — staging older than this is
    # dropped (visible via the weight_staging_bytes gauge and the
    # weight_staging_aborts_total counter); <= 0 disables the sweep
    staging_ttl_s: float = 120.0


@dataclasses.dataclass
class PolicyConfig:
    """Multi-policy serving plane (inference/policies.PolicyRegistry).

    Named policy handles (``actor``, ``opponent``, ...) each carry an
    independent version line on one engine: per-line stable + canary
    buffers, deterministic canary traffic splits, per-(policy, version)
    KV namespaces in the radix cache, and per-request pins so a buffer
    serving in-flight decodes can never be dropped. Single-policy mode
    (no named push) is a strict no-op — greedy streams and the metric
    namespace are bit-identical to an engine without this plane."""

    # named policy weight buffers kept resident in HBM; colder
    # (unpinned) buffers LRU-demote to host RAM and reload on the next
    # request targeting them (<= 0 disables demotion)
    max_resident: int = 2


@dataclasses.dataclass
class GoodputConfig:
    """Goodput attribution plane (utils/goodput.py): wall-clock bucket
    ledger + recompile attribution for one owning loop. Always on — the
    ledger costs a few monotonic reads per loop iteration — but the
    JSONL streams only flow when paths are set."""

    # goodput ledger snapshots appended here (one JSON line per export)
    jsonl_path: str = ""
    # one line per XLA backend compile with its triggering phase + shape
    # signature — the input the shape-ladder AOT precompiler consumes.
    # The stream opens with a header line (ladder fingerprint + jax
    # version) and rotates to <path>.1 past compile_events_max_bytes,
    # so restarts can't grow it without bound
    compile_events_path: str = ""
    compile_events_max_bytes: int = 8_000_000
    # readiness: a server reports /health "warming" from its first XLA
    # compile until its shape ladder is covered, it goes ready_quiet_s
    # without compiling, or it has COMPLETED ready_min_requests
    # requests end-to-end (a server successfully serving is
    # serving-ready even while incremental shapes still compile —
    # without this, sustained traffic would hold a healthy server out
    # of rotation indefinitely; <= 0 disables the completion path).
    # Keeps cold servers out of fleet rotation through the compile
    # storm without deadlocking an idle fresh one.
    ready_quiet_s: float = 3.0
    ready_min_requests: int = 1


@dataclasses.dataclass
class PrecompileConfig:
    """Shape-ladder AOT precompilation (inference/precompile.py): drive
    the engine's exact compiled-program ladder ahead of traffic so a
    cold server reaches /health ``ready`` without a traffic-driven
    compile storm.

    ``mode``: "off" (default), "ladder" (AOT-compile the full
    enumerated ladder at startup — with a seeded persistent compile
    cache this is seconds of disk retrieval, not minutes of XLA), or
    "replay" (warm only the shapes a prior run's compile_events stream
    actually hit; refuses a stream whose ladder fingerprint doesn't
    match). The server CLI accepts ``--precompile replay:<path>`` as
    shorthand for mode=replay + replay_path."""

    mode: str = "off"
    # compile_events.jsonl from a prior run (mode="replay")
    replay_path: str = ""
    # seed artifact (utils/compile_cache.pack_seed tarball) the LAUNCHER
    # unpacks into compilation_cache_dir before spawning servers —
    # autoscaler scale-ups and supervisor full-constellation restarts
    # then warm from disk instead of re-paying the compile storm.
    # Launcher-side: the server process never reads it (deliberately
    # not CLI-plumbed; see arealint ARL002 exemption).
    seed_artifact: str = ""


@dataclasses.dataclass
class TracingConfig:
    """Request-lifecycle span tracing (utils/tracing.py): per-rid spans
    recorded by the inference engine / remote rollout controller, exported
    as JSONL or Chrome trace-event JSON (Perfetto / chrome://tracing).
    Disabled by default — the tracer is a strict no-op then (no per-token
    allocations on the scheduler hot loop)."""

    enabled: bool = False
    # ring-buffer bound: oldest spans are dropped past this count, so a
    # long-running server never grows without bound
    max_spans: int = 100_000
    # optional JSONL sink written by flush()/export helpers (empty = only
    # in-memory draining via GET /trace or tracer.drain())
    export_path: str = ""


@dataclasses.dataclass
class TelemetryConfig:
    """Fleet telemetry hub (utils/telemetry.TelemetryCollector): a
    background thread scrapes every generation server's ``/metrics`` and
    drains ``/trace``, computes fleet-wide rollups (queue-wait p95, KV
    utilization, accept rate, staleness distribution), runs the
    deterministic anomaly rules below (gauge flip + ERROR log, cleared
    symmetrically), and serves the consolidated ``GET /metrics`` + a
    run-manifest JSON — the inputs a queue-wait/KV-util-driven
    autoscaler consumes."""

    enabled: bool = False
    scrape_interval_s: float = 2.0
    # also drain each server's GET /trace per sweep (keeps the spans of
    # a later-killed server; feeds the stitched fleet timeline and the
    # queue-wait rollup). Off = metrics-only scraping.
    drain_traces: bool = True
    # spans kept per server for rollups/stitching (bounded ring)
    span_window: int = 4096
    # --- anomaly rules (all deterministic; each drives one 0/1 gauge) ---
    # decode stall: a server reports running_requests > 0 with
    # decode_tokens_per_sec == 0 for this many consecutive scrapes
    decode_stall_scrapes: int = 3
    # queue-wait breach: fleet queue_wait p95 over the span window
    queue_wait_p95_s: float = 30.0
    # accept-rate collapse: spec is enabled somewhere but the fleet
    # accept rate sits below this floor (after min_draft_tokens drafted)
    accept_rate_floor: float = 0.05
    min_draft_tokens: int = 256
    # staleness runaway: max staleness-at-consumption in the lineage
    # ledger exceeds this many versions
    staleness_max: int = 8
    # goodput collapse (r11): the fleet-mean pause+idle wall fraction
    # (from the engines' goodput ledgers) runs away from the run's own
    # baseline — the first `goodput_baseline_sweeps` observations set
    # the manifest baseline; the anomaly fires when the current value
    # exceeds baseline + margin AND the absolute floor
    goodput_baseline_sweeps: int = 3
    goodput_collapse_margin: float = 0.25
    goodput_collapse_floor: float = 0.5
    # consolidated hub endpoint (serve() binds here; port 0 = auto)
    host: str = "127.0.0.1"
    port: int = 0


@dataclasses.dataclass
class TrafficConfig:
    """SLO-aware traffic plane (router admission + server shedding +
    fleet autoscaling). Two request classes exist: ``interactive``
    (latency-sensitive — eval sweeps, agentic sessions driven by a live
    caller) and ``bulk`` (throughput work — GRPO training rollouts).
    Workflows stamp the class into ``ModelRequest.metadata["priority"]``;
    anything unstamped is bulk. Under contention the plane sheds or
    preempts BULK first, never interactive: the router answers
    ``429 + Retry-After`` (which utils/http honors as backoff, not
    failure), the server's bounded admission queue sheds overflow, and
    the engine preempts a bulk request when an interactive one would
    miss its soft deadline (the preempted rollout resumes via the prefix
    cache — zero lost work). The autoscaler grows/drains the fleet from
    observed queue backlog and KV utilization inside
    ``[min_servers, max_servers]`` with hysteresis."""

    # default tenant label stamped on requests from this client when the
    # workflow doesn't carry one (per-tenant fairness needs SOME key)
    tenant: str = "default"
    # weighted fairness between classes while the fleet is contended:
    # bulk may hold at most bulk_weight/(bulk_weight+interactive_weight)
    # of contended in-flight capacity when interactive traffic is
    # present (work-conserving: with no interactive in flight, bulk
    # takes everything; bulk is also never starved below ONE in-flight
    # request, since small counts round the share to zero)
    interactive_weight: int = 4
    bulk_weight: int = 1
    # per-tenant in-flight cap at the router (0 = uncapped): one tenant
    # flooding the fleet cannot starve the rest regardless of class
    max_inflight_per_tenant: int = 0
    # router-side overload shed: when the fleet's summed queued_requests
    # (from /health probes) reaches this depth, new BULK schedules are
    # shed with 429 + Retry-After until the backlog drains (0 disables)
    shed_queue_depth: int = 0
    # Retry-After seconds attached to router 429s
    retry_after_s: float = 1.0
    # router-side in-flight ledger entries expire after this long
    # without a /finish_request (crashed clients must not leak tenant
    # capacity forever)
    inflight_ttl_s: float = 600.0
    # --- FleetMonitor-driven autoscaler (inference/fleet.FleetAutoscaler) ---
    autoscale: bool = False
    min_servers: int = 1
    max_servers: int = 4
    # evaluation period of the control loop
    autoscale_interval_s: float = 5.0
    # scale up when queued-per-server exceeds this, or KV utilization
    # exceeds up_kv_util, or queue-wait p95 (when a telemetry rollup is
    # wired) exceeds up_queue_wait_s
    up_queued_per_server: float = 4.0
    up_kv_util: float = 0.9
    up_queue_wait_s: float = 10.0
    # scale down only when the fleet is quiet: zero queued and KV
    # utilization below this on every server
    down_kv_util: float = 0.3
    # hysteresis: consecutive evaluations the condition must hold
    up_consecutive: int = 2
    down_consecutive: int = 6
    # minimum seconds between scaling actions (either direction)
    cooldown_s: float = 30.0
    # cross-server prefix shipping (r16): when a qid's affine server
    # dies or is rebalanced away, attach the previous owner's address
    # to the fresh assignment (kv_ship_from) so the replacement server
    # fetches the session's committed prefix over /kv_export instead of
    # re-prefilling it. Requires --kv-ship on the target servers.
    kv_ship: bool = False
    # multi-policy canary routing (r19): per-line canary splits the
    # router resolves BEFORE scheduling, grammar
    # "name=STABLE[:CANARY:FRACTION][,name=...]" (e.g.
    # "actor=12:13:0.1,opponent=7" routes 10% of actor traffic to v13).
    # Empty = requests pass their policy handle through unresolved and
    # the server's registry split applies instead.
    policy_split: str = ""


@dataclasses.dataclass
class FleetConfig:
    """Rollout-fleet resilience plane (inference/fleet.py `FleetMonitor`):
    per-server health state machine (HEALTHY → SUSPECT → DEAD →
    RECOVERING), circuit breaker with half-open probes, graceful drain,
    and dynamic membership via the name_resolve gen_servers subtree.
    `engine/remote.py` consults it for failover-aware generation: on a
    connect failure / timeout / exhausted 5xx retries the in-flight
    request migrates to a healthy server and RESUMES from its
    accumulated tokens (token-exact, courtesy of the interruptible
    suffix-resume loop)."""

    # start the background prober/membership thread (passive failure
    # reports and failover still work when disabled)
    enabled: bool = True
    probe_interval_s: float = 2.0
    probe_timeout_s: float = 2.0
    # consecutive failures (probe or passive report) HEALTHY → SUSPECT
    suspect_threshold: int = 1
    # consecutive failures → DEAD (circuit opens; affinity evicted)
    dead_threshold: int = 3
    # consecutive half-open probe successes RECOVERING → HEALTHY
    recover_threshold: int = 2
    # DEAD servers are probed at most this often (the half-open window)
    halfopen_interval_s: float = 5.0
    # follow name_resolve gen_servers registrations live (only applies
    # when the fleet was DISCOVERED there — explicit addrs stay static)
    watch_membership: bool = True
    membership_poll_s: float = 2.0
    # per-request bound on server hops before the failure propagates
    max_failovers_per_request: int = 8


@dataclasses.dataclass
class EnvServiceConfig:
    """Environment service plane (env/service.py): sessionful env workers
    behind HTTP, health-probed/circuit-broken by the same FleetMonitor
    machinery as the generation fleet, with client-side failover. A
    ``RemoteEnv`` journals ``(reset_kwargs, action log)`` per session and,
    when a worker dies mid-episode, deterministically replays the journal
    onto a healthy worker (envs declare ``replay_safe``; non-replayable
    envs surface :class:`EnvSessionLostError` into the executor's episode
    retry/quarantine path instead of hanging the rollout thread)."""

    enabled: bool = False
    # workers the launcher spawns (python -m areal_tpu.env.service)
    n_workers: int = 1
    # env served by each worker: "module:attr" where attr is a zero-arg
    # factory (or Env subclass) producing one Env instance per session,
    # e.g. "areal_tpu.env.service:countdown_env"
    env_spec: str = ""
    host: str = "127.0.0.1"
    # concurrent sessions one worker admits before /reset answers 429
    max_sessions: int = 512
    # idle seconds before a worker expires a leaked session (crashed
    # client, failed best-effort close); <= 0 disables the sweeper
    session_ttl_s: float = 3600.0
    # --- client-side call bounds (RemoteEnv) ---
    reset_timeout_s: float = 30.0
    call_timeout_s: float = 30.0
    # transient-retry budget per worker per call (utils/http policy:
    # connect/timeout/5xx retry with jittered backoff; 4xx never retry)
    call_retries: int = 3
    # first transient-retry backoff, doubled per attempt (jittered)
    retry_delay_s: float = 0.5
    # worker hops one session may make before the failure propagates
    max_failovers: int = 4
    # compare replayed (observation, reward, done) against the journal
    # and fail the session on divergence — a worker pair that disagrees
    # is a determinism bug, not a resumable state
    verify_replay: bool = True
    # --- workflow-side tool bound (satellite: bounded in-process tools;
    # a timeout/exception becomes an error observation, not a crash) ---
    tool_timeout_s: float = 30.0
    # env workers the local launcher will respawn after a crash before
    # giving up (replacements re-register; membership finds them)
    max_worker_respawns: int = 8
    # health/circuit parameters for the env fleet monitor
    fleet: "FleetConfig" = dataclasses.field(
        default_factory=lambda: FleetConfig()
    )


@dataclasses.dataclass
class DurabilityConfig:
    """Training-loop durability plane (api/workflow_api.py
    `WorkflowExecutor`): a flaky reward/env call must not silently drop a
    sample forever, a poison sample must not burn retry budget forever,
    and a dead fleet must produce a clean error in bounded time instead
    of an infinite 1-s-timeout loop. Retry/backoff mirrors the
    utils/http.py policy shape (exponential, bounded jitter)."""

    # additional attempts after the first failure before the sample is
    # quarantined (0 = fail-fast quarantine, matching the old behavior of
    # dropping on first exception — but visibly)
    max_episode_retries: int = 2
    retry_delay: float = 0.5  # first backoff, doubled per attempt
    max_retry_delay: float = 30.0
    retry_jitter: float = 0.5  # uniform extra in [0, jitter*delay)
    # sliding window of episode-attempt outcomes driving the DEGRADED
    # state: when at least half the window is populated and the failure
    # fraction reaches `degraded_threshold`, the executor flips DEGRADED
    # (gauge + log) instead of silently shrinking throughput
    failure_window: int = 64
    degraded_threshold: float = 0.5
    # hard deadline for one prepare_batch() call; None = request_timeout
    prepare_batch_timeout: Optional[float] = None
    # with zero accepted progress for this long, prepare_batch consults
    # the engine's FleetMonitor — a fully-dead fleet raises immediately
    # rather than burning the rest of the deadline
    health_probe_after: float = 30.0


@dataclasses.dataclass
class ProfilingConfig:
    """jax-profiler trace capture for selected steps (reference
    model_worker.py:829-910 per-MFC torch profiler)."""

    enabled: bool = False
    # 0-based global step numbers to trace (empty + enabled = trace the
    # first step)
    steps: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class SaverConfig:
    experiment_name: str = ""
    trial_name: str = ""
    fileroot: str = "/tmp/areal_tpu"
    freq_epochs: Optional[int] = None
    freq_steps: Optional[int] = None
    freq_secs: Optional[int] = None


@dataclasses.dataclass
class EvaluatorConfig:
    experiment_name: str = ""
    trial_name: str = ""
    fileroot: str = "/tmp/areal_tpu"
    freq_epochs: Optional[int] = None
    freq_steps: Optional[int] = None
    freq_secs: Optional[int] = None


@dataclasses.dataclass
class RecoverConfig:
    mode: str = "disabled"  # disabled | auto | fault | resume
    retries: int = 3
    freq_epochs: Optional[int] = None
    freq_steps: Optional[int] = None
    freq_secs: Optional[int] = 600
    # committed recover checkpoints retained (recover/step_<g>/ dirs with
    # a COMMIT marker); older ones are GC'd after each successful dump
    keep_last: int = 2


@dataclasses.dataclass
class NameResolveConfig:
    type: str = "nfs"  # memory | nfs | kv
    nfs_record_root: str = "/tmp/areal_tpu/name_resolve"
    # kv backend rendezvous address (utils/kv_server.py), host:port
    kv_address: str = ""


@dataclasses.dataclass
class ClusterSpecConfig:
    name_resolve: NameResolveConfig = dataclasses.field(default_factory=NameResolveConfig)
    cluster_name: str = "local"
    fileroot: str = "/tmp/areal_tpu"
    n_devices_per_node: int = 8


@dataclasses.dataclass
class DatasetConfig:
    path: str = ""
    type: str = "gsm8k"
    batch_size: int = 8
    shuffle: bool = True
    max_length: Optional[int] = None
    drop_last: bool = True


@dataclasses.dataclass
class LauncherConfig:
    inference_server_cpus_per_task: int = 4
    inference_server_mem: int = 32768
    trainer_cpus_per_task: int = 4
    trainer_mem: int = 32768
    # >1 spawns that many trainer processes joined into one
    # jax.distributed world (multi-host SPMD; on TPU pods the per-host
    # runtime provides this instead)
    trainer_processes: int = 1


@dataclasses.dataclass
class SelfPlayConfig:
    """Self-play episode plane (workflow/selfplay.py): multi-agent
    episodes over one shared transcript, shipped as the countdown
    proposer/solver workload. Off by default — with ``enabled=False``
    the engine and workflow paths are a strict no-op. Every field here
    is machine-checked against workflow/selfplay.py by arealint ARL002
    (a field the workflow never reads is a silent default)."""

    enabled: bool = False
    # named policy handles (r19) for the two sides; "" rides the default
    # line. Different handles play different checkpoints on one engine
    # (e.g. "proposer@stable" vs "solver@canary").
    proposer_policy: str = ""
    solver_policy: str = ""
    # which sides export training rows; an untrained side is a frozen
    # opponent contributing only loss-masked context tokens
    train_proposer: bool = True
    train_solver: bool = True
    # traffic class for UNTRAINED (opponent) sides: interactive gives
    # opponent turns the bounded TTFT of PR 10/15 inside bulk rollouts
    # (the opponent is on the episode's critical path); trained sides
    # always ride bulk
    opponent_priority: str = "interactive"
    # proposer reward mapping: "banded" (difficulty band of the accepted
    # instance) or "zero_sum" (1 - solver reward)
    reward_mode: str = "banded"
    # reward discount across an agent's own turns (export_completions)
    turn_discount: float = 0.9
    # per-side turn budgets within one episode
    max_propose_rounds: int = 3
    max_solver_rounds: int = 4
    # proposer instance-schema bounds (env/selfplay.py grader families)
    min_numbers: int = 3
    max_numbers: int = 4
    max_target: int = 1000


# --------------------------------------------------------------------------
# Experiments
# --------------------------------------------------------------------------
@dataclasses.dataclass
class BaseExperimentConfig:
    experiment_name: str = "experiment"
    trial_name: str = "trial"
    cluster: ClusterSpecConfig = dataclasses.field(default_factory=ClusterSpecConfig)
    allocation_mode: str = ""
    seed: int = 1
    total_train_epochs: int = 1
    total_train_steps: Optional[int] = None
    tokenizer_path: str = ""
    train_dataset: DatasetConfig = dataclasses.field(default_factory=DatasetConfig)
    valid_dataset: Optional[DatasetConfig] = None
    saver: SaverConfig = dataclasses.field(default_factory=SaverConfig)
    checkpointer: SaverConfig = dataclasses.field(default_factory=SaverConfig)
    evaluator: EvaluatorConfig = dataclasses.field(default_factory=EvaluatorConfig)
    recover: RecoverConfig = dataclasses.field(default_factory=RecoverConfig)
    launcher: LauncherConfig = dataclasses.field(default_factory=LauncherConfig)
    # trainer → generation-server weight path: "disk" (HF checkpoint +
    # reload) or "device" (host-staged chunked transfer, no disk —
    # reference NCCL-broadcast analog). Colocated runs always use the
    # in-memory device path regardless.
    weight_update_mode: str = "disk"
    profiling: ProfilingConfig = dataclasses.field(
        default_factory=ProfilingConfig
    )


@dataclasses.dataclass
class SFTConfig(BaseExperimentConfig):
    model: TrainEngineConfig = dataclasses.field(default_factory=TrainEngineConfig)


@dataclasses.dataclass
class GRPOConfig(BaseExperimentConfig):
    async_training: bool = True
    gconfig: GenerationHyperparameters = dataclasses.field(
        default_factory=GenerationHyperparameters
    )
    rollout: InferenceEngineConfig = dataclasses.field(default_factory=InferenceEngineConfig)
    server: JaxGenConfig = dataclasses.field(default_factory=JaxGenConfig)
    # environment service plane (env/service.py): remote sessionful env
    # workers with replay-based failover for agentic rollouts
    env_service: EnvServiceConfig = dataclasses.field(
        default_factory=EnvServiceConfig
    )
    actor: PPOActorConfig = dataclasses.field(default_factory=PPOActorConfig)
    ref: Optional[PPOActorConfig] = None
    # self-play episode plane (workflow/selfplay.py): off = strict no-op
    selfplay: SelfPlayConfig = dataclasses.field(
        default_factory=SelfPlayConfig
    )


# --------------------------------------------------------------------------
# Loading / merging
# --------------------------------------------------------------------------
def _is_optional(tp) -> Tuple[bool, Any]:
    if typing.get_origin(tp) is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return True, args[0]
    return False, tp


def from_dict(cls: Type[T], data: Dict[str, Any]) -> T:
    """Recursively build a dataclass from a nested dict."""
    if data is None:
        data = {}
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"{cls} is not a dataclass")
    kwargs = {}
    fields = {f.name: f for f in dataclasses.fields(cls)}
    for key, value in data.items():
        if key not in fields:
            raise ValueError(f"unknown config key {key!r} for {cls.__name__}")
        ftype = fields[key].type
        if isinstance(ftype, str):
            ftype = typing.get_type_hints(cls)[key]
        _, inner = _is_optional(ftype)
        if dataclasses.is_dataclass(inner) and isinstance(value, dict):
            kwargs[key] = from_dict(inner, value)
        else:
            kwargs[key] = value
    return cls(**kwargs)


def to_dict(obj) -> Dict[str, Any]:
    return dataclasses.asdict(obj)


def _coerce(existing: Any, raw: str) -> Any:
    s = raw.strip()
    low = s.lower()
    if low in ("null", "none"):
        return None
    if low in ("true", "false"):
        return low == "true"
    if isinstance(existing, bool):
        return low in ("true", "1", "yes")
    for caster in (int, float):
        try:
            return caster(s)
        except ValueError:
            pass
    if s.startswith("[") or s.startswith("{"):
        return yaml.safe_load(s)
    return s


def apply_override(obj: Any, dotted: str, raw_value: str) -> None:
    """Apply ``a.b.c=value`` onto a dataclass tree in place-ish (rebuilds
    leaves as needed; dataclasses here are mutable so set directly)."""
    parts = dotted.split(".")
    target = obj
    for p in parts[:-1]:
        if not hasattr(target, p):
            raise ValueError(f"unknown config key {dotted!r}")
        nxt = getattr(target, p)
        if nxt is None:
            # instantiate Optional[dataclass] nodes on demand
            hints = typing.get_type_hints(type(target))
            _, inner = _is_optional(hints[p])
            if dataclasses.is_dataclass(inner):
                nxt = inner()
                setattr(target, p, nxt)
            else:
                raise ValueError(f"cannot descend into None field {p!r}")
        target = nxt
    leaf = parts[-1]
    if not hasattr(target, leaf):
        raise ValueError(f"unknown config key {dotted!r}")
    setattr(target, leaf, _coerce(getattr(target, leaf), raw_value))


def load_expr_config(argv: List[str], config_cls: Type[T]) -> Tuple[T, str]:
    """Parse ``--config file.yaml key=value ...`` into `config_cls`
    (reference cli_args.py:922 `load_expr_config`). Returns (config, path)."""
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=str, default=None)
    args, overrides = parser.parse_known_args(argv)
    data = {}
    if args.config:
        with open(args.config) as f:
            data = yaml.safe_load(f) or {}
    cfg = from_dict(config_cls, data)
    for ov in overrides:
        if "=" not in ov:
            raise ValueError(f"override {ov!r} must look like key=value")
        key, value = ov.split("=", 1)
        apply_override(cfg, key, value)
    _propagate_names(cfg)
    return cfg, args.config or ""


def _propagate_names(cfg) -> None:
    """Copy experiment/trial names into sub-configs that carry them
    (the reference does this in each entry point)."""
    exp = getattr(cfg, "experiment_name", None)
    trial = getattr(cfg, "trial_name", None)
    fileroot = None
    cluster = getattr(cfg, "cluster", None)
    if cluster is not None:
        fileroot = cluster.fileroot
    if not exp:
        return
    for f in dataclasses.fields(cfg):
        sub = getattr(cfg, f.name)
        if dataclasses.is_dataclass(sub) and not isinstance(sub, type):
            if hasattr(sub, "experiment_name") and not sub.experiment_name:
                sub.experiment_name = exp
            if hasattr(sub, "trial_name") and not sub.trial_name:
                sub.trial_name = trial
            if fileroot and hasattr(sub, "fileroot"):
                sub.fileroot = fileroot
