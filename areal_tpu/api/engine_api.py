"""Abstract train/inference engine contracts.

Role of reference areal/api/engine_api.py:39-227: algorithms talk to these
interfaces, never to device code directly, so FSDP↔Megatron (reference) or
single-host↔pod SPMD (here) swaps are config changes.
"""

import abc
from typing import Any, Callable, Dict, List, Optional

from areal_tpu.api.io_struct import (
    FinetuneSpec,
    ModelRequest,
    ModelResponse,
    SaveLoadMeta,
    WeightUpdateMeta,
)


class TrainEngine(abc.ABC):
    """A sharded train state + jitted update functions on a device mesh
    (reference engine_api.py:39 `TrainEngine`)."""

    def initialize(self, ft_spec: Optional[FinetuneSpec] = None):
        raise NotImplementedError()

    def destroy(self):
        pass

    def train(self, mode: bool = True):
        return self

    @property
    def data_parallel_rank(self) -> int:
        raise NotImplementedError()

    @property
    def data_parallel_world_size(self) -> int:
        raise NotImplementedError()

    def is_data_parallel_head(self) -> bool:
        return self.data_parallel_rank == 0

    def current_data_parallel_head(self) -> int:
        return 0

    def get_version(self) -> int:
        raise NotImplementedError()

    def set_version(self, version: int):
        raise NotImplementedError()

    def save(self, meta: SaveLoadMeta):
        raise NotImplementedError()

    def load(self, meta: SaveLoadMeta):
        raise NotImplementedError()

    def upload_weights(self, meta: WeightUpdateMeta):
        """Push current weights to inference engines."""
        raise NotImplementedError()

    def train_batch(
        self,
        input_: Dict[str, Any],
        loss_fn: Callable,
        loss_weight_fn: Callable,
    ) -> Dict[str, float]:
        raise NotImplementedError()

    def eval_batch(
        self,
        input_: Dict[str, Any],
        loss_fn: Callable,
        loss_weight_fn: Callable,
    ) -> Dict[str, float]:
        raise NotImplementedError()

    def forward(
        self,
        input_: Dict[str, Any],
        post_hook: Optional[Callable] = None,
    ):
        raise NotImplementedError()


class InferenceEngine(abc.ABC):
    """Rollout-side contract (reference engine_api.py:158)."""

    def initialize(self, *args, **kwargs):
        raise NotImplementedError()

    def destroy(self):
        pass

    async def agenerate(self, req: ModelRequest) -> ModelResponse:
        raise NotImplementedError()

    def update_weights(self, meta: WeightUpdateMeta):
        raise NotImplementedError()

    def get_version(self) -> int:
        raise NotImplementedError()

    def set_version(self, version: int):
        raise NotImplementedError()

    def submit(self, data: Dict[str, Any], workflow) -> bool:
        """Queue one episode; False when refused (quarantined sample)."""
        raise NotImplementedError()

    def wait(self, count: int, timeout: Optional[float] = None):
        raise NotImplementedError()

    def rollout_batch(self, data: List[Dict[str, Any]], workflow):
        raise NotImplementedError()

    def prepare_batch(self, dataloader, workflow):
        raise NotImplementedError()

    def pause(self):
        """Pause issuing new requests (weight update window)."""
        raise NotImplementedError()

    def resume(self):
        raise NotImplementedError()
