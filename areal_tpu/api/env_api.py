"""Gym-like async environment contract (reference areal/api/env_api.py)."""

import abc
from typing import Any, Dict, Tuple


class Env(abc.ABC):
    """Async environment for agentic workflows."""

    async def areset(self, **kwargs) -> Any:
        """Start an episode; returns the initial observation."""
        raise NotImplementedError()

    async def astep(
        self, action: Any
    ) -> Tuple[Any, float, bool, Dict[str, Any]]:
        """Apply an action; returns (observation, reward, done, info)."""
        raise NotImplementedError()

    async def aclose(self):
        pass
