"""Gym-like async environment contract (reference areal/api/env_api.py)."""

import abc
from typing import Any, Dict, Tuple


class EnvServiceError(RuntimeError):
    """Base class for environment-service-plane failures (the episode
    itself is lost: worker death, fleet down). Lives next to the Env
    contract so workflow code can type-match without importing the
    service implementation (env/service.py and its HTTP stack)."""


class EnvWorkerUnavailableError(EnvServiceError):
    """No env worker could serve the call (whole pool unreachable or the
    failover budget is spent). Typed so the executor's episode
    retry/quarantine machinery owns it instead of a bare stack trace."""


class EnvSessionLostError(EnvServiceError):
    """A session's worker died and the env is not replay-safe (or the
    replay diverged): the episode cannot be resumed. Routes the episode
    into retry/quarantine — never silently resumed on divergent state."""


class EnvActionError(RuntimeError):
    """The ENV raised while executing an action (worker answered 422) —
    the infrastructure is fine, the action was poison. Deliberately NOT
    an EnvServiceError: workflows convert it into an error observation
    (exactly like a local ``env.call`` raising), never a failover."""


class Env(abc.ABC):
    """Async environment for agentic workflows.

    ``replay_safe`` is the env's determinism declaration for the
    environment service plane (env/service.py): a replay-safe env
    guarantees that re-running ``areset(**kwargs)`` followed by the same
    action sequence reproduces the same observations, rewards, and done
    flags — so when a remote env worker dies mid-episode, the client may
    reconstruct the session on a healthy worker by replaying its journal.
    Envs with hidden nondeterminism (wall-clock state, external mutation,
    unseeded randomness) must leave it False; their in-flight episodes
    route into the executor's episode-retry/quarantine path on worker
    death instead of being silently resumed against divergent state.
    """

    #: deterministic (reset_kwargs, actions) -> trajectory; see class doc
    replay_safe: bool = False

    async def areset(self, **kwargs) -> Any:
        """Start an episode; returns the initial observation."""
        raise NotImplementedError()

    async def astep(
        self, action: Any
    ) -> Tuple[Any, float, bool, Dict[str, Any]]:
        """Apply an action; returns (observation, reward, done, info)."""
        raise NotImplementedError()

    async def aclose(self):
        pass
