"""Request/response and metadata structs shared across engines.

Role of reference areal/api/io_struct.py: the wire-level contracts between
workflows, inference engines, and train engines.
"""

import dataclasses
import enum
import itertools
import time
import uuid
from typing import Any, Dict, List, Optional

from areal_tpu.api.cli_args import GenerationHyperparameters


@dataclasses.dataclass
class ModelRequest:
    """One generation request (reference io_struct.py:22)."""

    rid: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex)
    input_ids: List[int] = dataclasses.field(default_factory=list)
    gconfig: GenerationHyperparameters = dataclasses.field(
        default_factory=GenerationHyperparameters
    )
    # VLM inputs: base64-encoded images interleaved with image tokens in
    # input_ids (reference io_struct.py ModelRequest.image_data)
    image_data: List[str] = dataclasses.field(default_factory=list)
    # processed multimodal payload for the in-repo engine's mm prefill:
    # pixel_values / vis_seg / vis_pos_h / vis_pos_w / mm_index /
    # mrope_pos (+ optional rope_delta); see inference/engine._Request.mm
    mm: Optional[Dict[str, Any]] = None
    metadata: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModelResponse:
    """Generation result (reference io_struct.py:38). Token-in/token-out;
    logprobs are the behavior policy's sampled-token logprobs and `versions`
    records the weight version that produced each output token (for
    staleness-aware decoupled PPO)."""

    input_tokens: List[int] = dataclasses.field(default_factory=list)
    output_tokens: List[int] = dataclasses.field(default_factory=list)
    output_logprobs: List[float] = dataclasses.field(default_factory=list)
    output_versions: List[int] = dataclasses.field(default_factory=list)
    stop_reason: str = "stop"  # stop | length | abort
    latency: float = 0.0
    ttft: float = 0.0

    @property
    def input_len(self) -> int:
        return len(self.input_tokens)

    @property
    def output_len(self) -> int:
        return len(self.output_tokens)


class WeightUpdateMethod(enum.Enum):
    DISK = "disk"
    DEVICE = "device"  # cross-mesh device transfer (ICI/DCN), NCCL-bcast analog


@dataclasses.dataclass
class ParamSpec:
    """Flat description of one parameter for chunked transfer
    (reference io_struct.py ParamSpec)."""

    name: str
    shape: List[int]
    dtype: str

    @property
    def size_bytes(self) -> int:
        import numpy as np

        n = 1
        for s in self.shape:
            n *= s
        return n * np.dtype(self.dtype).itemsize


@dataclasses.dataclass
class WeightUpdateMeta:
    """How fresh weights travel trainer → generation engine
    (reference io_struct.py:126)."""

    type: WeightUpdateMethod = WeightUpdateMethod.DISK
    path: Optional[str] = None  # disk: checkpoint dir
    model_version: int = 0
    chunk_bytes: int = 1 << 30  # device path: FFD chunking budget
    param_specs: List[ParamSpec] = dataclasses.field(default_factory=list)
    # device path: generation-server addresses (host:port); empty = read
    # AREAL_LLM_SERVER_ADDRS
    addrs: List[str] = dataclasses.field(default_factory=list)

    @classmethod
    def from_disk(cls, experiment_name: str, trial_name: str, fileroot: str,
                  model_version: int = 0) -> "WeightUpdateMeta":
        import os

        path = os.path.join(
            fileroot, experiment_name, trial_name, "weight_update", f"v{model_version}"
        )
        return cls(type=WeightUpdateMethod.DISK, path=path, model_version=model_version)


@dataclasses.dataclass
class SaveLoadMeta:
    """Checkpoint save/load request (reference io_struct.py:144)."""

    path: str
    weight_format: str = "orbax"  # orbax | hf
    with_optim: bool = False
    tokenizer_path: Optional[str] = None
    base_model_path: Optional[str] = None


@dataclasses.dataclass
class FinetuneSpec:
    """Dataset-epoch accounting (reference io_struct.py FinetuneSpec)."""

    total_train_epochs: int
    dataset_size: int
    train_batch_size: int

    @property
    def steps_per_epoch(self) -> int:
        return max(1, self.dataset_size // self.train_batch_size)

    @property
    def total_train_steps(self) -> int:
        return self.total_train_epochs * self.steps_per_epoch


@dataclasses.dataclass
class StepInfo:
    """Global/epoch step bookkeeping (reference io_struct.py:169)."""

    epoch: int = 0
    epoch_step: int = 0
    global_step: int = 0
    steps_per_epoch: int = 1

    def next(self) -> "StepInfo":
        ep_step = self.epoch_step + 1
        epoch = self.epoch
        if ep_step >= self.steps_per_epoch:
            ep_step = 0
            epoch += 1
        return StepInfo(
            epoch=epoch,
            epoch_step=ep_step,
            global_step=self.global_step + 1,
            steps_per_epoch=self.steps_per_epoch,
        )


@dataclasses.dataclass
class RolloutStat:
    """Rollout lifecycle counters (reference io_struct.py RolloutStat)."""

    submitted: int = 0
    accepted: int = 0
    running: int = 0
    rejected: int = 0
    # groups dropped by a consumer-side group_filter (DAPO dynamic
    # sampling); dropped groups release staleness-gate budget so the
    # pipeline backfills them with fresh generations
    filtered: int = 0
    # durability plane (workflow_api episode retry + quarantine):
    # re-attempts performed after an episode failure
    retried: int = 0
    # samples that exhausted max_episode_retries and are barred from
    # re-admission (persisted across restarts via RecoverInfo)
    quarantined: int = 0
    # submissions refused because the sample is already quarantined
    quarantine_skipped: int = 0
    # samples dropped at consumption by the trajectory-level staleness
    # fence (staleness_mode="trajectory": the sample's oldest token
    # lagged the trainer by more than max_head_offpolicyness versions)
    stale_dropped: int = 0


_COUNTER = itertools.count()


def unique_rid(prefix: str = "req") -> str:
    return f"{prefix}-{int(time.time()*1000)}-{next(_COUNTER)}"
