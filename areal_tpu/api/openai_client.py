"""OpenAI-compatible async client over the InferenceEngine.

Role of reference areal/experimental/openai/client.py (`ArealOpenAI`, an
AsyncOpenAI subclass whose chat.completions.create routes through the
in-repo engine, caches `CompletionWithTokenLogpReward`, and exports cached
completions as RL training rows): agentic code written against the OpenAI
chat API runs unchanged on top of this framework's generation engines,
while every completion's token ids / behavior logprobs / model versions
are captured for the trainer.

The `openai` package is not a dependency here — the response objects are
lightweight dataclasses with the same attribute shape
(`resp.choices[0].message.content`, `resp.usage`, `resp.id`), which is
what agent code actually touches.

Tool calling (reference areal/experimental/openai/client.py `tool_call_parser`
+ tool_choice plumbing): pass OpenAI function schemas via ``tools=``; they are
rendered into the prompt through the tokenizer's chat template when it
supports a ``tools`` kwarg, else as a Hermes-style system block. Completions
are scanned for ``<tool_call>{json}</tool_call>`` blocks (the qwen2/Hermes
convention) and surface as ``message.tool_calls`` with
``finish_reason == "tool_calls"``. The parser is pluggable
(``tool_parser=``) because the convention is model-specific string surgery —
exactly how the reference treats it.
"""

import dataclasses
import json
import re
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelRequest


@dataclasses.dataclass
class ToolCallFunction:
    name: str
    arguments: str  # JSON-encoded argument object, as in the OpenAI API


@dataclasses.dataclass
class ToolCall:
    id: str
    function: ToolCallFunction
    type: str = "function"


_TOOL_CALL_RE = re.compile(r"<tool_call>\s*(.*?)\s*</tool_call>", re.DOTALL)


def hermes_tool_parser(text: str) -> List[ToolCall]:
    """Parse ``<tool_call>{"name": ..., "arguments": {...}}</tool_call>``
    blocks (qwen2/Hermes convention). Malformed JSON inside a block is
    skipped — an agent loop must see either a valid call or plain text."""
    calls = []
    for m in _TOOL_CALL_RE.finditer(text):
        try:
            obj = json.loads(m.group(1))
            name = obj["name"]
        except (ValueError, KeyError, TypeError):
            continue
        args = obj.get("arguments", {})
        calls.append(
            ToolCall(
                id=f"call_{uuid.uuid4().hex[:12]}",
                function=ToolCallFunction(
                    name=str(name),
                    arguments=(
                        args if isinstance(args, str) else json.dumps(args)
                    ),
                ),
            )
        )
    return calls


@dataclasses.dataclass
class ChatMessage:
    role: str
    content: str
    tool_calls: Optional[List[ToolCall]] = None


@dataclasses.dataclass
class Choice:
    index: int
    message: ChatMessage
    finish_reason: str


@dataclasses.dataclass
class CompletionUsage:
    prompt_tokens: int
    completion_tokens: int

    @property
    def total_tokens(self) -> int:
        return self.prompt_tokens + self.completion_tokens


@dataclasses.dataclass
class ChatCompletion:
    id: str
    choices: List[Choice]
    created: int
    model: str
    usage: CompletionUsage


@dataclasses.dataclass
class CompletionWithTokenLogpReward:
    """Cached RL view of one completion (reference
    experimental/openai/types.py:38)."""

    completion: ChatCompletion
    messages: List[Dict[str, str]]
    input_tokens: List[int]
    output_tokens: List[int]
    output_logprobs: List[float]
    output_versions: List[int]
    reward: Optional[float] = None

    def to_training_row(self) -> Dict[str, np.ndarray]:
        """Padded [1, L] tensors in the workflow batch schema."""
        ids = list(self.input_tokens) + list(self.output_tokens)
        plen, olen = len(self.input_tokens), len(self.output_tokens)
        row = {
            "input_ids": np.asarray([ids], np.int32),
            "attention_mask": np.ones((1, plen + olen), np.bool_),
            "loss_mask": np.asarray([[0] * plen + [1] * olen], np.int32),
            "logprobs": np.asarray(
                [[0.0] * plen + list(self.output_logprobs)], np.float32
            ),
            "versions": np.asarray(
                [[-1] * plen + list(self.output_versions)], np.int32
            ),
            "rewards": np.asarray([self.reward or 0.0], np.float32),
        }
        return row


class _ChatCompletions:
    def __init__(self, client: "ArealOpenAI"):
        self._client = client

    async def create(
        self,
        *,
        messages: List[Dict[str, str]],
        max_tokens: Optional[int] = None,
        max_completion_tokens: Optional[int] = None,
        temperature: Optional[float] = None,
        top_p: Optional[float] = None,
        stop: Optional[List[str]] = None,
        tools: Optional[List[Dict[str, Any]]] = None,
        tool_choice: Optional[str] = None,
        **unsupported: Any,
    ) -> ChatCompletion:
        # silently ignoring OpenAI params we don't implement would corrupt
        # agent loops written against the real API (n>1 returning one
        # choice, stream=True returning a non-stream)
        hard = {
            k: v
            for k, v in unsupported.items()
            if k in ("n", "stream", "functions")
            and v not in (None, False, 1, [])
        }
        if hard:
            raise NotImplementedError(
                f"unsupported OpenAI parameters: {sorted(hard)} "
                "(this client returns a single non-streamed completion)"
            )
        if tool_choice not in (None, "auto", "none"):
            # "required"/forced-function would need constrained decoding
            raise NotImplementedError(
                f"tool_choice={tool_choice!r} unsupported (only 'auto'; "
                "forced tool calls need constrained decoding)"
            )
        c = self._client
        base = c.gconfig
        gconfig = base.new(
            n_samples=1,
            max_new_tokens=(
                max_completion_tokens or max_tokens or base.max_new_tokens
            ),
            temperature=(
                base.temperature if temperature is None else temperature
            ),
            top_p=base.top_p if top_p is None else top_p,
        )
        # the real OpenAI API renders tool schemas whenever `tools` is
        # non-empty and uses tool_choice only to steer calling — so a
        # multi-turn conversation that toggles tool_choice sees the SAME
        # prompt prefix every turn (prompt-consistency + prefix-cache
        # hits). Only the parser / finish_reason are gated on 'none'.
        render_tools = bool(tools)
        parse_tools = bool(tools) and tool_choice != "none"
        rendered = list(messages)
        input_ids = c.tokenizer.apply_chat_template(
            rendered, tokenize=True, add_generation_prompt=True
        )
        if render_tools:
            # HF chat templates for tool-capable models take tools= directly.
            # A template that IGNORES the kwarg returns the same ids — the
            # schemas would silently never reach the model — so verify the
            # render changed, and otherwise inject a Hermes-style system
            # block (the convention the default parser expects).
            try:
                with_tools = c.tokenizer.apply_chat_template(
                    rendered,
                    tokenize=True,
                    add_generation_prompt=True,
                    tools=list(tools),
                )
            except TypeError:
                with_tools = input_ids
            if list(with_tools) != list(input_ids):
                input_ids = with_tools
            else:
                sys_block = (
                    "You may call tools. Available tools (JSON schemas):\n"
                    f"<tools>{json.dumps(list(tools))}</tools>\n"
                    "To call one, emit exactly:\n"
                    '<tool_call>{"name": <tool-name>, "arguments": '
                    "<args-object>}</tool_call>"
                )
                if rendered and rendered[0].get("role") == "system":
                    rendered = [
                        {
                            "role": "system",
                            "content": rendered[0]["content"]
                            + "\n\n"
                            + sys_block,
                        }
                    ] + rendered[1:]
                else:
                    rendered = [
                        {"role": "system", "content": sys_block}
                    ] + rendered
                input_ids = c.tokenizer.apply_chat_template(
                    rendered, tokenize=True, add_generation_prompt=True
                )
        if stop:
            stop_ids = []
            for s in stop if isinstance(stop, list) else [stop]:
                t = c.tokenizer.encode(s, add_special_tokens=False)
                if len(t) != 1:
                    # truncating to a sub-token would halt generation on
                    # ordinary prose — refuse loudly instead
                    raise ValueError(
                        f"stop string {s!r} is not a single token "
                        f"({len(t)} ids); multi-token stop strings are "
                        "not supported yet"
                    )
                stop_ids.append(t[0])
            gconfig = gconfig.new(
                stop_token_ids=list(gconfig.stop_token_ids) + stop_ids
            )
        req = ModelRequest(
            input_ids=list(input_ids),
            gconfig=gconfig,
            rid=f"chatcmpl-{uuid.uuid4().hex}",
            # the client-lifetime dict, not a fresh one: router-resolved
            # canary handles written back into it stick for the session
            metadata=c._metadata,
        )
        resp = await c.engine.agenerate(req)
        text = c.tokenizer.decode(resp.output_tokens)
        tool_calls = c.tool_parser(text) if parse_tools else []
        completion = ChatCompletion(
            id=req.rid,
            choices=[
                Choice(
                    index=0,
                    message=ChatMessage(
                        role="assistant",
                        content=text,
                        tool_calls=tool_calls or None,
                    ),
                    finish_reason=(
                        "tool_calls"
                        if tool_calls
                        else (
                            "stop" if resp.stop_reason == "stop" else "length"
                        )
                    ),
                )
            ],
            created=int(time.time()),
            model="areal-tpu",
            usage=CompletionUsage(
                prompt_tokens=len(req.input_ids),
                completion_tokens=len(resp.output_tokens),
            ),
        )
        c._cache[req.rid] = CompletionWithTokenLogpReward(
            completion=completion,
            messages=list(messages),
            input_tokens=list(req.input_ids),
            output_tokens=list(resp.output_tokens),
            output_logprobs=list(resp.output_logprobs),
            output_versions=list(resp.output_versions),
        )
        return completion


class _Chat:
    def __init__(self, client: "ArealOpenAI"):
        self.completions = _ChatCompletions(client)


class ArealOpenAI:
    """OpenAI-shaped client bound to an InferenceEngine
    (reference experimental/openai/client.py:194)."""

    def __init__(
        self,
        engine,
        tokenizer,
        gconfig: Optional[GenerationHyperparameters] = None,
        tool_parser: Callable[[str], List[ToolCall]] = hermes_tool_parser,
        session_id: Optional[str] = None,
        priority: str = "interactive",
        policy: str = "",
        agent: str = "",
        role: str = "",
    ):
        self.engine = engine
        self.tokenizer = tokenizer
        self.gconfig = gconfig or GenerationHyperparameters()
        self.tool_parser = tool_parser
        # traffic-plane class: a live OpenAI-shaped session is
        # INTERACTIVE by default (agentic TRAINING loops driving this
        # client should pass priority="bulk" so their rollouts stay
        # shed-able under load)
        self.priority = priority
        # multi-policy serving plane (r19): named policy handle stamped
        # into every request ("actor", "actor@v13", "opponent", ...);
        # "" keeps the single-policy default path. Self-play clients
        # bind one ArealOpenAI per side ("actor" vs "opponent") against
        # the SAME engine/fleet.
        self.policy = policy
        # session/affinity key stamped into every request's metadata
        # ("qid"): all of an agentic episode's turns steer to one
        # server, where each turn's growing history rides the previous
        # turn's radix-cached pages
        from areal_tpu.api.io_struct import unique_rid

        self.session_id = session_id or unique_rid("sess")
        # self-play episode plane: which agent of a multi-agent episode
        # this client speaks for, and that agent's role — stamped into
        # request metadata so lineage records split per side
        self.agent = agent
        self.role = role
        # ONE metadata dict for the client's lifetime (the rlvr/
        # multi_turn stamping contract, r19): the router writes a
        # canary-resolved policy handle back into it, so every later
        # turn of the session stays on the version that served turn 0
        self._metadata: Dict[str, Any] = {
            "qid": self.session_id,
            "priority": self.priority,
            **({"policy": self.policy} if self.policy else {}),
            **({"agent": self.agent} if self.agent else {}),
            **({"role": self.role} if self.role else {}),
        }
        self._cache: Dict[str, CompletionWithTokenLogpReward] = {}
        self.chat = _Chat(self)

    def get_completions(
        self, completion_id: str
    ) -> Optional[CompletionWithTokenLogpReward]:
        return self._cache.get(completion_id)

    def set_reward(self, completion_id: str, reward: float) -> None:
        if completion_id not in self._cache:
            raise KeyError(f"unknown completion id {completion_id}")
        self._cache[completion_id].reward = float(reward)

    def export_completions(
        self, turn_discount: float = 1.0
    ) -> Dict[str, CompletionWithTokenLogpReward]:
        """All cached completions; rewards propagate backwards through an
        agent's conversation turns with `turn_discount` (reference
        export_completions semantics: later turns' rewards discount back
        to the earlier turns that produced them)."""
        import copy as _copy

        items = sorted(
            self._cache.items(), key=lambda kv: kv[1].completion.created
        )
        # propagate into COPIES: writing discounted rewards back into the
        # cache would make a second export (or a different turn_discount)
        # compound them as if they were explicit (round-2 advisor finding)
        out = [(k, _copy.copy(c)) for k, c in items]
        running: Optional[float] = None
        for _, c in reversed(out):
            if c.reward is not None:
                running = (
                    c.reward
                    if running is None
                    else c.reward + turn_discount * running
                )
            elif running is not None:
                running = turn_discount * running
                c.reward = running
        return dict(out)
