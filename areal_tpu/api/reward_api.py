"""Reward API: sync verifier functions made awaitable.

Role of reference areal/api/reward_api.py (`AsyncRewardWrapper`): reward
functions (math verification, code execution) are blocking CPU work; the
async rollout loop must not stall on them, so they run in a thread pool.
"""

import asyncio
import concurrent.futures
import contextvars
import functools
from typing import Callable, Optional

_DEFAULT_POOL: Optional[concurrent.futures.ThreadPoolExecutor] = None


class RewardTimeoutError(RuntimeError):
    """A reward call exceeded its time budget. Typed (rather than a bare
    asyncio.TimeoutError) so the executor's episode retry/quarantine
    machinery can tell a sick reward backend from a cancelled task."""


def _pool() -> concurrent.futures.ThreadPoolExecutor:
    global _DEFAULT_POOL
    if _DEFAULT_POOL is None:
        _DEFAULT_POOL = concurrent.futures.ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="reward"
        )
    return _DEFAULT_POOL


class AsyncRewardWrapper:
    """Wrap a sync ``reward_fn(prompt, completion, prompt_ids,
    completion_ids, **data) -> float`` for use inside ``arun_episode``.

    ``timeout_s`` bounds each call: a reward backend that hangs (remote
    verifier wedged, sandbox deadlock) raises :class:`RewardTimeoutError`
    after the budget instead of pinning the episode task forever. The
    worker thread itself cannot be interrupted — the bound is on the
    episode's wait, which is what keeps the rollout pipeline live."""

    def __init__(
        self,
        reward_fn: Callable[..., float],
        timeout_s: Optional[float] = None,
    ):
        self.reward_fn = reward_fn
        self.timeout_s = timeout_s

    async def __call__(self, *args, **kwargs) -> float:
        loop = asyncio.get_running_loop()
        # propagate the episode-lineage contextvar into the worker thread
        # (trace headers on remote verifier calls depend on it)
        ctx = contextvars.copy_context()
        fut = loop.run_in_executor(
            _pool(),
            ctx.run,
            functools.partial(self.reward_fn, *args, **kwargs),
        )
        if self.timeout_s:
            try:
                return float(await asyncio.wait_for(fut, self.timeout_s))
            except asyncio.TimeoutError:
                raise RewardTimeoutError(
                    f"reward_fn did not return within {self.timeout_s}s"
                ) from None
        return float(await fut)
