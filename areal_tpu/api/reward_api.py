"""Reward API: sync verifier functions made awaitable.

Role of reference areal/api/reward_api.py (`AsyncRewardWrapper`): reward
functions (math verification, code execution) are blocking CPU work; the
async rollout loop must not stall on them, so they run in a thread pool.
"""

import asyncio
import concurrent.futures
import functools
from typing import Callable, Optional

_DEFAULT_POOL: Optional[concurrent.futures.ThreadPoolExecutor] = None


def _pool() -> concurrent.futures.ThreadPoolExecutor:
    global _DEFAULT_POOL
    if _DEFAULT_POOL is None:
        _DEFAULT_POOL = concurrent.futures.ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="reward"
        )
    return _DEFAULT_POOL


class AsyncRewardWrapper:
    """Wrap a sync ``reward_fn(prompt, completion, prompt_ids,
    completion_ids, **data) -> float`` for use inside ``arun_episode``."""

    def __init__(self, reward_fn: Callable[..., float]):
        self.reward_fn = reward_fn

    async def __call__(self, *args, **kwargs) -> float:
        loop = asyncio.get_running_loop()
        return float(
            await loop.run_in_executor(
                _pool(), functools.partial(self.reward_fn, *args, **kwargs)
            )
        )
