"""Async rollout runtime: RolloutWorkflow + WorkflowExecutor.

Role of reference areal/api/workflow_api.py:31-323 — the heart of async RL.
A background thread runs an asyncio loop that drains an input queue into
``workflow.arun_episode`` tasks against the inference engine. Capacity
control enforces both a concurrency cap and the staleness gate

    capacity = min(max_concurrent_rollouts,
                   (max_head_offpolicyness + trainer_version + 1) ·
                   consumer_batch_size − (accepted + running))

so rollouts never run more than ``max_head_offpolicyness`` weight versions
ahead of what the trainer has consumed (reference workflow_api.py:101-113).

TPU adaptation: batches are plain dict[str, np.ndarray] (padded layout)
instead of TensorDicts; the asyncio loop is stock (uvloop is CUDA-image
baggage the reference carries — not needed here).
"""

import abc
import asyncio
import queue
import random
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from areal_tpu.api.cli_args import InferenceEngineConfig
from areal_tpu.api.io_struct import RolloutStat
from areal_tpu.utils import data as data_utils
from areal_tpu.utils import logging as logging_util

logger = logging_util.getLogger("WorkflowExecutor")


class RolloutWorkflow(abc.ABC):
    """One episode of data collection (reference workflow_api.py:31)."""

    @abc.abstractmethod
    async def arun_episode(
        self, engine, data: Dict[str, Any]
    ) -> Optional[Dict[str, np.ndarray]]:
        """Run one episode and return a padded batch (or None to reject)."""
        raise NotImplementedError()


class _WorkItem:
    __slots__ = ("data", "workflow", "create_time", "uid")

    def __init__(self, data, workflow):
        self.data = data
        self.workflow = workflow
        self.create_time = time.monotonic_ns()
        self.uid = data_utils.sample_uid(data)


class _ResultItem:
    __slots__ = ("batch", "create_time", "uid")

    def __init__(self, batch, create_time, uid=""):
        self.batch = batch
        self.create_time = create_time
        self.uid = uid


class WorkflowExecutor:
    """Background async rollout driver (reference workflow_api.py:51)."""

    def __init__(self, config: InferenceEngineConfig, inference_engine):
        self.config = config
        self.engine = inference_engine
        qsize = config.queue_size or (config.consumer_batch_size * 16 or 128)
        self.input_queue: "queue.Queue[_WorkItem]" = queue.Queue(maxsize=qsize)
        # unbounded: total outstanding results are already bounded by the
        # staleness gate (accepted counts feed get_capacity), and a bounded
        # queue would let put() block the asyncio loop thread
        self.output_queue: "queue.Queue[_ResultItem]" = queue.Queue()
        self.rollout_stat = RolloutStat()
        # uids of dataset items whose episode results were CONSUMED (pulled
        # into a returned batch) — recover persists these so a resumed run
        # never trains one twice (reference master_worker.py:121-128);
        # submitted-but-unconsumed items are deliberately NOT here: their
        # rollouts are lost on crash and must be re-generated
        self.consumed_uids: List[str] = []
        self._lock = threading.Lock()
        self._exiting = threading.Event()
        self._paused = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def initialize(self):
        self._thread = threading.Thread(
            target=self._thread_main, daemon=True
        )
        self._thread.start()
        return self

    def destroy(self):
        self._exiting.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def pause(self):
        """Stop launching new episodes (weight-update window; reference
        workflow_api pause/resume gate)."""
        self._paused.set()

    def resume(self):
        self._paused.clear()

    # ------------------------------------------------------------------
    def get_capacity(self) -> int:
        """Staleness-aware admission budget (reference workflow_api.py:101)."""
        cfg = self.config
        with self._lock:
            version = self.engine.get_version()
            consumer_bs = max(cfg.consumer_batch_size, 1)
            max_concurrent = cfg.max_concurrent_rollouts or consumer_bs
            capacity = max_concurrent - self.rollout_stat.running
            if cfg.max_head_offpolicyness is not None:
                ofp = cfg.max_head_offpolicyness
                sample_cnt = self.rollout_stat.accepted + self.rollout_stat.running
                budget = (ofp + version + 1) * consumer_bs - sample_cnt
                capacity = min(capacity, budget)
            return capacity

    # ------------------------------------------------------------------
    def submit(self, data: Dict[str, Any], workflow: RolloutWorkflow) -> None:
        self.input_queue.put_nowait(_WorkItem(data, workflow))
        with self._lock:
            self.rollout_stat.submitted += 1

    def wait(
        self,
        count: int,
        timeout: Optional[float] = None,
        group_filter: Optional[Callable[[Dict[str, np.ndarray]], bool]] = None,
        refill_fn: Optional[Callable[[int], None]] = None,
    ) -> Dict[str, np.ndarray]:
        """Block until `count` accepted results; returns one concatenated
        padded batch sorted by creation time then shuffled (reference
        workflow_api.py:225-274).

        ``group_filter(batch) -> keep?`` implements DAPO dynamic sampling
        (reference areal/engine/ppo/actor.py dynamic_sampling, done here at
        the SOURCE): a dropped episode is un-counted from ``accepted`` so
        the staleness gate reopens and the pipeline generates a replacement
        — the batch is backfilled with useful groups instead of silently
        shrinking."""
        start = time.monotonic()
        timeout = timeout or self.config.request_timeout
        results: List[_ResultItem] = []
        while len(results) < count:
            if self._exiting.is_set():
                raise RuntimeError("executor is shutting down")
            remain = timeout - (time.monotonic() - start)
            if remain <= 0:
                # put back what we took so nothing is lost
                for r in results:
                    self.output_queue.put_nowait(r)
                raise TimeoutError(
                    f"rollout wait timed out: {len(results)}/{count}"
                )
            try:
                item = self.output_queue.get(timeout=min(0.05, remain))
            except queue.Empty:
                continue
            if group_filter is not None and not group_filter(item.batch):
                with self._lock:
                    self.rollout_stat.accepted -= 1
                    self.rollout_stat.filtered += 1
                if refill_fn is not None:
                    # synchronous callers have no pipeline topping them up
                    # — ask for a replacement episode per dropped group
                    refill_fn(1)
                continue
            results.append(item)
        results.sort(key=lambda r: r.create_time)
        random.shuffle(results)
        with self._lock:
            self.consumed_uids.extend(r.uid for r in results if r.uid)
        return data_utils.concat_padded_tensors([r.batch for r in results])

    def drain_consumed_uids(self) -> List[str]:
        """Consumed-sample uids since the last drain (recover bookkeeping)."""
        with self._lock:
            out, self.consumed_uids = self.consumed_uids, []
            return out

    def rollout_batch(
        self,
        data: List[Dict[str, Any]],
        workflow: RolloutWorkflow,
        group_filter: Optional[Callable] = None,
    ) -> Dict[str, np.ndarray]:
        """Synchronous batch rollout: submit all, wait all. With a
        ``group_filter``, dropped groups are backfilled by resubmitting
        prompts (round-robin over ``data``) until ``len(data)`` useful
        groups exist — the synchronous caller has no prepare_batch
        pipeline to top it up."""
        import itertools

        for item in data:
            self.submit(item, workflow)
        refill = None
        if group_filter is not None and data:
            cyc = itertools.cycle(data)

            def refill(n: int):
                for _ in range(n):
                    self.submit(next(cyc), workflow)

        return self.wait(
            count=len(data), group_filter=group_filter, refill_fn=refill
        )

    def prepare_batch(
        self,
        dataloader,
        workflow: RolloutWorkflow,
        group_filter: Optional[Callable] = None,
    ) -> Dict[str, np.ndarray]:
        """Overlap submission with waiting: keep the pipeline full under the
        capacity gate, return as soon as one consumer batch is ready
        (reference workflow_api.py:288-317)."""
        if not hasattr(self, "_data_generator"):
            self._data_generator = cycle_dataloader(dataloader)
        bs = getattr(dataloader, "batch_size", 1) or 1
        assert self.config.consumer_batch_size % bs == 0
        while True:
            # top the pipeline up whenever the staleness gate has room for
            # at least one more dataloader batch (reference :300-308)
            if (
                self.get_capacity() + bs > 0
                and not self.input_queue.full()
            ):
                items = next(self._data_generator)
                for item in items:
                    self.submit(item, workflow)
            try:
                return self.wait(
                    count=self.config.consumer_batch_size, timeout=1,
                    group_filter=group_filter,
                )
            except TimeoutError:
                continue

    # ------------------------------------------------------------------
    def _thread_main(self):
        try:
            asyncio.run(self._run_async())
        except Exception:
            logger.error(
                "rollout thread crashed:\n" + traceback.format_exc()
            )
            raise

    async def _run_async(self):
        pending: set = set()
        trace = self.config.enable_rollout_tracing
        while not self._exiting.is_set():
            # launch as many episodes as capacity allows
            capacity = self.get_capacity()
            launched = 0
            while capacity > 0 and not self._paused.is_set():
                try:
                    item = self.input_queue.get_nowait()
                except queue.Empty:
                    break
                task = asyncio.create_task(
                    self._run_episode(item)
                )
                pending.add(task)
                task.add_done_callback(pending.discard)
                capacity -= 1
                launched += 1
                with self._lock:
                    self.rollout_stat.running += 1
                if trace:
                    logger.info(
                        f"launched episode (running={self.rollout_stat.running})"
                    )
            if pending:
                await asyncio.wait(
                    pending, timeout=0.02,
                    return_when=asyncio.FIRST_COMPLETED,
                )
            else:
                await asyncio.sleep(0.005)
        for t in pending:
            t.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)

    async def _run_episode(self, item: _WorkItem):
        try:
            batch = await item.workflow.arun_episode(self.engine, item.data)
        except Exception:
            logger.error("episode failed:\n" + traceback.format_exc())
            batch = None
        with self._lock:
            self.rollout_stat.running -= 1
            if batch is None:
                self.rollout_stat.rejected += 1
                return
            self.rollout_stat.accepted += 1
        self.output_queue.put_nowait(
            _ResultItem(batch, item.create_time, uid=item.uid)
        )
        if self.config.enable_rollout_tracing:
            logger.info(
                f"episode done (accepted={self.rollout_stat.accepted})"
            )


def zero_signal_filter(batch: Dict[str, np.ndarray]) -> bool:
    """The canonical DAPO group filter: keep an episode's group only if
    its rewards are not all identical (all-same rewards normalize to zero
    advantage — pure gradient noise). Pass as ``group_filter=`` to
    prepare_batch/rollout_batch/wait."""
    r = np.asarray(batch.get("rewards", ())).reshape(-1)
    return bool(r.size <= 1 or (r != r.flat[0]).any())


def cycle_dataloader(dataloader):
    """Endless epoch-wrapping iterator over a dataloader."""
    while True:
        for batch in dataloader:
            yield batch
