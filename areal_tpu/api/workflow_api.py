"""Async rollout runtime: RolloutWorkflow + WorkflowExecutor.

Role of reference areal/api/workflow_api.py:31-323 — the heart of async RL.
A background thread runs an asyncio loop that drains an input queue into
``workflow.arun_episode`` tasks against the inference engine. Capacity
control enforces both a concurrency cap and the staleness gate

    capacity = min(max_concurrent_rollouts,
                   (max_head_offpolicyness + trainer_version + 1) ·
                   consumer_batch_size − (accepted + running))

so rollouts never run more than ``max_head_offpolicyness`` weight versions
ahead of what the trainer has consumed (reference workflow_api.py:101-113).

Staleness admission modes (``InferenceEngineConfig.staleness_mode``, r13):
``"step"`` keeps the global gate above. ``"trajectory"`` — built for the
zero-pause weight plane, where versions advance mid-decode instead of at
fleet-wide pause barriers — bounds in-flight work by
``max_concurrent_rollouts`` alone and enforces η per SAMPLE at
consumption: ``wait()`` reads each sample's staleness-at-consumption
(trainer version minus the oldest weight version that produced one of
its tokens, from the LineageLedger) and DROPS samples beyond η,
un-counting them from ``accepted`` so the pipeline backfills with fresh
generations — the fence moves from "what may run" to "what the trainer
eats".

TPU adaptation: batches are plain dict[str, np.ndarray] (padded layout)
instead of TensorDicts; the asyncio loop is stock (uvloop is CUDA-image
baggage the reference carries — not needed here).
"""

import abc
import asyncio
import collections
import queue
import random
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Set

import numpy as np

from areal_tpu.api.cli_args import DurabilityConfig, InferenceEngineConfig
from areal_tpu.api.io_struct import RolloutStat
from areal_tpu.utils import chaos
from areal_tpu.utils import data as data_utils
from areal_tpu.utils import logging as logging_util
from areal_tpu.utils import stats_tracker, telemetry
from areal_tpu.utils.http import backoff_delay

logger = logging_util.getLogger("WorkflowExecutor")


class RolloutThreadError(RuntimeError):
    """The background rollout thread died; the captured terminal
    exception is chained as ``__cause__``. Raised promptly from
    wait()/prepare_batch() instead of letting callers block out the full
    request_timeout against a loop nobody is running."""


class FleetUnavailableError(RuntimeError):
    """Every generation server is unhealthy: prepare_batch cannot make
    progress no matter how long it waits, so it fails fast with the
    fleet gauges instead of burning its deadline 1 s at a time."""


class EpisodeQuarantinedError(RuntimeError):
    """An episode wait() was counting on got quarantined: the expected
    result will never arrive, so the caller learns NOW instead of
    timing out after request_timeout."""


class RolloutWorkflow(abc.ABC):
    """One episode of data collection (reference workflow_api.py:31)."""

    @abc.abstractmethod
    async def arun_episode(
        self, engine, data: Dict[str, Any]
    ) -> Optional[Dict[str, np.ndarray]]:
        """Run one episode and return a padded batch (or None to reject)."""
        raise NotImplementedError()


class _WorkItem:
    __slots__ = ("data", "workflow", "create_time", "uid")

    def __init__(self, data, workflow):
        self.data = data
        self.workflow = workflow
        self.create_time = time.monotonic_ns()
        self.uid = data_utils.sample_uid(data)


class _ResultItem:
    __slots__ = ("batch", "create_time", "uid")

    def __init__(self, batch, create_time, uid=""):
        self.batch = batch
        self.create_time = create_time
        self.uid = uid


class WorkflowExecutor:
    """Background async rollout driver (reference workflow_api.py:51)."""

    def __init__(self, config: InferenceEngineConfig, inference_engine):
        self.config = config
        self.engine = inference_engine
        qsize = config.queue_size or (config.consumer_batch_size * 16 or 128)
        self.input_queue: "queue.Queue[_WorkItem]" = queue.Queue(maxsize=qsize)
        # unbounded: total outstanding results are already bounded by the
        # staleness gate (accepted counts feed get_capacity), and a bounded
        # queue would let put() block the asyncio loop thread
        self.output_queue: "queue.Queue[_ResultItem]" = queue.Queue()
        self.rollout_stat = RolloutStat()
        # uids of dataset items whose episode results were CONSUMED (pulled
        # into a returned batch) — recover persists these so a resumed run
        # never trains one twice (reference master_worker.py:121-128);
        # submitted-but-unconsumed items are deliberately NOT here: their
        # rollouts are lost on crash and must be re-generated
        self.consumed_uids: List[str] = []
        # poison quarantine: uids that exhausted max_episode_retries —
        # barred from re-admission (persisted via RecoverInfo so a
        # supervised restart doesn't grant them a fresh retry budget)
        self.quarantined: Set[str] = set()
        self.durability: DurabilityConfig = (
            getattr(config, "durability", None) or DurabilityConfig()
        )
        # trajectory lineage ledger: per-sample records (attempts,
        # servers, per-segment weight versions, reward, staleness at
        # consumption, consuming step) assembled from the episode
        # contexts agenerate fills in; always on in memory, appended to
        # config.lineage_path as JSONL when one is set
        self.lineage = telemetry.LineageLedger(
            path=getattr(config, "lineage_path", "") or "",
            max_records=getattr(config, "lineage_max_records", 8192),
        )
        # staleness admission mode (r13): "step" = the legacy global
        # version gate in get_capacity; "trajectory" = per-sample
        # staleness-at-consumption filtering in wait()
        self.staleness_mode = str(
            getattr(config, "staleness_mode", "step") or "step"
        )
        if self.staleness_mode not in ("step", "trajectory"):
            raise ValueError(
                f"staleness_mode={self.staleness_mode!r}: expected "
                "step | trajectory"
            )
        # consuming-step attribution: the trainer announces its global
        # step via set_train_step; otherwise consumption is numbered by
        # wait() returns
        self._train_step = -1
        self._consume_seq = 0
        # sliding window of episode-attempt outcomes (True = failure)
        # driving the DEGRADED state
        self._outcomes: "collections.deque[bool]" = collections.deque(
            maxlen=max(1, self.durability.failure_window)
        )
        self._degraded = False
        self._lock = threading.Lock()
        self._exiting = threading.Event()
        self._paused = threading.Event()
        # watchdog: the rollout thread's terminal exception, re-raised
        # from wait()/prepare_batch() within one poll interval
        self._failed = threading.Event()
        self._thread_exc: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def initialize(self):
        self._thread = threading.Thread(
            target=self._thread_main, daemon=True
        )
        self._thread.start()
        return self

    def destroy(self):
        self._exiting.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def set_train_step(self, step: int) -> None:
        """Announce the trainer's global step so lineage records carry
        the TRUE consuming step g (train loops call this once per step;
        without it, consumption is numbered by wait() returns)."""
        self._train_step = int(step)

    def pause(self):
        """Stop launching new episodes (weight-update window; reference
        workflow_api pause/resume gate)."""
        self._paused.set()

    def resume(self):
        self._paused.clear()

    # ------------------------------------------------------------------
    # Durability plane: degraded state, quarantine, thread watchdog
    # ------------------------------------------------------------------
    @property
    def degraded(self) -> bool:
        """True while the sliding-window failure budget is blown — the
        pipeline is still up but visibly losing a large fraction of its
        episodes (flaky reward backend, sick env service)."""
        return self._degraded

    def _tracer(self):
        t = getattr(self.engine, "tracer", None)
        return t if t is not None and getattr(t, "enabled", False) else None

    def _record_outcome(self, failure: bool) -> None:
        """Feed the failure-budget window; flip/clear DEGRADED with a log
        line on each transition (never silently)."""
        dur = self.durability
        with self._lock:
            self._outcomes.append(failure)
            window = self._outcomes
            # require a half-full window (min 1, so tiny windows can
            # still flip) before judging: one early failure must not
            # flip a freshly started executor
            populated = len(window) >= max(1, window.maxlen // 2)
            frac = (sum(window) / len(window)) if window else 0.0
            now_degraded = populated and frac >= dur.degraded_threshold
            changed = now_degraded != self._degraded
            self._degraded = now_degraded
        # gauge on EVERY outcome, not just transitions: stats exports
        # reset each window, so a transition-only emit would make an
        # ongoing DEGRADED state invisible after one logging step
        stats_tracker.scalar(**{"rollout/degraded": float(now_degraded)})
        if changed:
            if now_degraded:
                logger.error(
                    f"executor DEGRADED: {frac:.0%} of the last "
                    f"{len(window)} episode attempts failed (threshold "
                    f"{dur.degraded_threshold:.0%}) — throughput is being "
                    f"propped up by retries, check reward/env backends"
                )
            else:
                logger.info(
                    f"executor recovered from DEGRADED "
                    f"(failure fraction now {frac:.0%})"
                )

    def quarantine_snapshot(self) -> List[str]:
        """Current quarantine set (recover.dump persists it)."""
        with self._lock:
            return sorted(self.quarantined)

    def restore_quarantine(self, uids) -> None:
        """Re-arm the quarantine after a supervised restart."""
        with self._lock:
            fresh = {u for u in uids if u} - self.quarantined
            self.quarantined.update(fresh)
            # the stat is also wait()'s fast-fail gate: restored poison
            # must arm it, or the post-restart path re-grows the silent
            # request_timeout hang this plane exists to fix
            self.rollout_stat.quarantined += len(fresh)

    def _check_thread(self) -> None:
        if self._failed.is_set():
            raise RolloutThreadError(
                "rollout thread died; no episodes are running"
            ) from self._thread_exc

    # ------------------------------------------------------------------
    def get_capacity(self) -> int:
        """Staleness-aware admission budget (reference workflow_api.py:101)."""
        cfg = self.config
        with self._lock:
            version = self.engine.get_version()
            consumer_bs = max(cfg.consumer_batch_size, 1)
            max_concurrent = cfg.max_concurrent_rollouts or consumer_bs
            capacity = max_concurrent - self.rollout_stat.running
            if (
                cfg.max_head_offpolicyness is not None
                and self.staleness_mode != "trajectory"
            ):
                # step mode: the global version-arithmetic gate.
                # Trajectory mode deliberately skips it — admission is
                # bounded by concurrency alone and η is enforced on
                # each CONSUMED sample's recorded staleness in wait()
                ofp = cfg.max_head_offpolicyness
                sample_cnt = self.rollout_stat.accepted + self.rollout_stat.running
                budget = (ofp + version + 1) * consumer_bs - sample_cnt
                capacity = min(capacity, budget)
            return capacity

    # ------------------------------------------------------------------
    def submit(self, data: Dict[str, Any], workflow: RolloutWorkflow) -> bool:
        """Queue one episode; returns False (not queued) for quarantined
        samples — a poison item must not re-enter the pipeline after a
        resume or at an epoch wrap."""
        item = _WorkItem(data, workflow)
        with self._lock:
            if item.uid and item.uid in self.quarantined:
                self.rollout_stat.quarantine_skipped += 1
                logger.info(f"skipping quarantined sample {item.uid}")
                return False
        self.input_queue.put_nowait(item)
        with self._lock:
            self.rollout_stat.submitted += 1
        return True

    def wait(
        self,
        count: int,
        timeout: Optional[float] = None,
        group_filter: Optional[Callable[[Dict[str, np.ndarray]], bool]] = None,
        refill_fn: Optional[Callable[[int], None]] = None,
        ignore_quarantine: bool = False,
    ) -> Dict[str, np.ndarray]:
        """Block until `count` accepted results; returns one concatenated
        padded batch sorted by creation time then shuffled (reference
        workflow_api.py:225-274).

        ``group_filter(batch) -> keep?`` implements DAPO dynamic sampling
        (reference areal/engine/ppo/actor.py dynamic_sampling, done here at
        the SOURCE): a dropped episode is un-counted from ``accepted`` so
        the staleness gate reopens and the pipeline generates a replacement
        — the batch is backfilled with useful groups instead of silently
        shrinking.

        Quarantine fast-fail: once any sample has ever been quarantined,
        each iteration compares ``count`` against the episodes that can
        still deliver (collected + running + queued, all read under one
        lock so the launch/finish windows can't undercount). A deficit
        asks ``refill_fn`` for replacements when one is provided; if the
        deficit persists (nothing healthy left to refill, or a bare
        submit-N/wait-N caller whose N-th episode was quarantined) the
        wait raises :class:`EpisodeQuarantinedError` instead of blocking
        out ``request_timeout`` on results that can never come.
        ``ignore_quarantine`` disables the check for callers whose outer
        loop backfills (prepare_batch: admission is capacity-gated, so a
        transient deficit there is normal, not terminal)."""
        start = time.monotonic()
        timeout = timeout or self.config.request_timeout
        results: List[_ResultItem] = []

        def _put_back():
            for r in results:
                self.output_queue.put_nowait(r)

        def _deliverable() -> int:
            with self._lock:
                return (
                    len(results) + self.rollout_stat.running
                    + self.input_queue.qsize()
                    + self.output_queue.qsize()
                )

        while len(results) < count:
            if self._failed.is_set():
                # completed results survive the thread's death — put back
                # what we took (the timeout path below does the same)
                _put_back()
                self._check_thread()
            if self._exiting.is_set():
                raise RuntimeError("executor is shutting down")
            if not ignore_quarantine and (
                self.rollout_stat.quarantined or self.quarantined
            ):
                deficit = count - _deliverable()
                if deficit > 0 and refill_fn is not None:
                    # replace lost episodes; refill submits synchronously
                    # so a successful top-up closes the deficit here
                    refill_fn(deficit)
                    deficit = count - _deliverable()
                if deficit > 0:
                    st = self.rollout_stat
                    _put_back()
                    raise EpisodeQuarantinedError(
                        f"rollout wait can never complete: "
                        f"{len(results)}/{count} results collected and "
                        f"only {count - deficit} deliverable "
                        f"(quarantined={st.quarantined} "
                        f"rejected={st.rejected}, e.g. "
                        f"{self.quarantine_snapshot()[:4]}); poison "
                        f"samples exhausted their retry budget"
                    )
            remain = timeout - (time.monotonic() - start)
            if remain <= 0:
                # put back what we took so nothing is lost
                _put_back()
                raise TimeoutError(
                    f"rollout wait timed out: {len(results)}/{count}"
                )
            try:
                item = self.output_queue.get(timeout=min(0.05, remain))
            except queue.Empty:
                continue
            if group_filter is not None and not group_filter(item.batch):
                with self._lock:
                    self.rollout_stat.accepted -= 1
                    self.rollout_stat.filtered += 1
                if refill_fn is not None:
                    # synchronous callers have no pipeline topping them up
                    # — ask for a replacement episode per dropped group
                    refill_fn(1)
                continue
            if (
                self.staleness_mode == "trajectory"
                and self.config.max_head_offpolicyness is not None
            ):
                lag = self._staleness_at_consumption(item)
                if (
                    lag is not None
                    and lag > self.config.max_head_offpolicyness
                ):
                    # trajectory-level η enforcement: this sample's
                    # oldest token lags the trainer too far — drop it
                    # and let the reopened capacity (or refill_fn)
                    # generate a fresher replacement
                    with self._lock:
                        self.rollout_stat.accepted -= 1
                        self.rollout_stat.stale_dropped += 1
                    stats_tracker.counter(**{
                        "rollout/stale_dropped_total": 1.0,
                    })
                    tracer = self._tracer()
                    if tracer is not None:
                        tracer.instant(
                            "stale_drop", item.uid or "?",
                            staleness=lag,
                            eta=self.config.max_head_offpolicyness,
                        )
                    logger.info(
                        f"dropped stale sample {item.uid or '?'}: "
                        f"staleness-at-consumption {lag} > eta="
                        f"{self.config.max_head_offpolicyness}"
                    )
                    if refill_fn is not None:
                        refill_fn(1)
                    continue
            results.append(item)
        results.sort(key=lambda r: r.create_time)
        random.shuffle(results)
        with self._lock:
            self.consumed_uids.extend(r.uid for r in results if r.uid)
            step = self._train_step
            if step < 0:
                step = self._consume_seq
            self._consume_seq += 1
        # lineage: stamp the consuming step + staleness-at-consumption
        # on every sample handed to the trainer (appends to the JSONL
        # sink when one is configured)
        self.lineage.mark_consumed(
            [r.uid for r in results if r.uid],
            step=step,
            trainer_version=self.engine.get_version(),
        )
        return data_utils.concat_padded_tensors([r.batch for r in results])

    def _staleness_at_consumption(self, item: _ResultItem) -> Optional[int]:
        """Trainer version minus the OLDEST weight version that produced
        one of this sample's CONSUMED tokens. The batch's per-token
        ``versions`` array is the primary source — it reflects exactly
        the tokens the trainer would train on (prompt positions are
        stamped -1 and skipped). The LineageLedger record is only the
        fallback: it unions every retry attempt's segments, so after a
        failed-and-retried episode it still carries the DISCARDED
        attempt's old versions and would spuriously drop a fresh
        sample. None when neither source knows, in which case the
        sample passes — an unattributable sample is a
        missing-instrumentation bug, not a staleness verdict."""
        trainer_v = self.engine.get_version()
        versions: List[int] = []
        if hasattr(item.batch, "get"):
            v = item.batch.get("versions")
            if v is not None:
                arr = np.asarray(v).reshape(-1)
                versions = [int(x) for x in arr[arr >= 0]]
        if not versions and item.uid:
            versions = self.lineage.versions_of(item.uid)
        if not versions:
            return None
        return trainer_v - min(versions)

    def drain_consumed_uids(self) -> List[str]:
        """Consumed-sample uids since the last drain (recover bookkeeping)."""
        with self._lock:
            out, self.consumed_uids = self.consumed_uids, []
            return out

    def rollout_batch(
        self,
        data: List[Dict[str, Any]],
        workflow: RolloutWorkflow,
        group_filter: Optional[Callable] = None,
    ) -> Dict[str, np.ndarray]:
        """Synchronous batch rollout: submit all, wait all. With a
        ``group_filter``, dropped groups are backfilled by resubmitting
        prompts (round-robin over ``data``) until ``len(data)`` useful
        groups exist — the synchronous caller has no prepare_batch
        pipeline to top it up."""
        import itertools

        submitted = sum(1 for item in data if self.submit(item, workflow))
        if data and not submitted:
            # every item refused: returning a silently empty batch would
            # crash the trainer far downstream with no cause attached
            raise RuntimeError(
                f"rollout_batch: all {len(data)} samples are quarantined "
                f"({self.quarantine_snapshot()[:8]}...); nothing to "
                f"roll out"
            )
        refill = None
        if group_filter is not None and data:
            cyc = itertools.cycle(data)

            def refill(n: int):
                for _ in range(n):
                    # skip quarantined prompts, bounded by one lap over
                    # the data so an all-quarantined cycle can't spin
                    for _attempt in range(len(data)):
                        if self.submit(next(cyc), workflow):
                            break

        if refill is not None:
            # the refill machinery can top quarantine-refused slots back
            # up with healthy prompts, so the full len(data) groups the
            # docstring promises are deliverable
            count = len(data)
        else:
            count = submitted
            if submitted < len(data):
                # no refill source: the batch is short and the trainer
                # must hear about it, not discover it downstream
                logger.warning(
                    f"rollout_batch: {len(data) - submitted} of "
                    f"{len(data)} samples are quarantined; returning a "
                    f"{submitted}-group batch"
                )
        return self.wait(
            count=count, group_filter=group_filter, refill_fn=refill
        )

    def prepare_batch(
        self,
        dataloader,
        workflow: RolloutWorkflow,
        group_filter: Optional[Callable] = None,
    ) -> Dict[str, np.ndarray]:
        """Overlap submission with waiting: keep the pipeline full under the
        capacity gate, return as soon as one consumer batch is ready
        (reference workflow_api.py:288-317).

        Bounded-time degradation: the call carries a real deadline
        (``durability.prepare_batch_timeout``, default request_timeout)
        and, after ``health_probe_after`` seconds with zero accepted
        progress, consults the engine's FleetMonitor — a fully-dead fleet
        raises :class:`FleetUnavailableError` immediately with the fleet
        gauges in the message instead of looping on 1-s wait timeouts
        until the heat death of the job."""
        # the cached endless iterator is keyed on the dataloader identity:
        # passing a different dataloader must not silently keep iterating
        # the first one
        if getattr(self, "_data_generator_key", None) != id(dataloader):
            self._data_generator = cycle_dataloader(dataloader)
            self._data_generator_key = id(dataloader)
        bs = getattr(dataloader, "batch_size", 1) or 1
        if self.config.consumer_batch_size % bs != 0:
            # user-config error, not an invariant: asserts vanish under -O
            raise ValueError(
                f"consumer_batch_size ({self.config.consumer_batch_size}) "
                f"must be divisible by the dataloader batch_size ({bs})"
            )
        dur = self.durability
        deadline_s = dur.prepare_batch_timeout or self.config.request_timeout
        start = time.monotonic()
        last_progress = start
        last_accepted = self.rollout_stat.accepted
        while True:
            self._check_thread()
            # top the pipeline up whenever the staleness gate has room for
            # at least one more dataloader batch (reference :300-308)
            if (
                self.get_capacity() + bs > 0
                and not self.input_queue.full()
            ):
                items = next(self._data_generator)
                for item in items:
                    self.submit(item, workflow)
            try:
                return self.wait(
                    count=self.config.consumer_batch_size, timeout=1,
                    group_filter=group_filter, ignore_quarantine=True,
                )
            except TimeoutError:
                now = time.monotonic()
                accepted = self.rollout_stat.accepted
                if accepted != last_accepted:
                    last_accepted = accepted
                    last_progress = now
                if now - start > deadline_s:
                    st = self.rollout_stat
                    raise TimeoutError(
                        f"prepare_batch exceeded its {deadline_s:.0f}s "
                        f"deadline: {self.output_queue.qsize()}"
                        f"/{self.config.consumer_batch_size} "
                        f"results ready (submitted={st.submitted} "
                        f"accepted={st.accepted} running={st.running} "
                        f"rejected={st.rejected} "
                        f"quarantined={st.quarantined} "
                        f"degraded={self._degraded})"
                    )
                if now - last_progress >= max(0.0, dur.health_probe_after):
                    self._probe_fleet_health(now - last_progress)
                continue

    def _probe_fleet_health(self, stalled_s: float) -> None:
        """Fail fast when the whole fleet is gone: zero schedulable
        servers means no episode can ever complete, so waiting out the
        deadline would only delay the same error."""
        fleet = getattr(self.engine, "fleet", None)
        if fleet is None:
            return
        try:
            schedulable = fleet.schedulable_addresses()
            total = len(fleet.addresses())
        except Exception:
            return  # a half-built monitor must not mask the real wait
        if total > 0 and not schedulable:
            raise FleetUnavailableError(
                f"no rollout progress for {stalled_s:.0f}s and 0/{total} "
                f"generation servers are schedulable (all DEAD/DRAINING) "
                f"— fleet is down; check server logs / the launcher"
            )

    # ------------------------------------------------------------------
    def _thread_main(self):
        try:
            asyncio.run(self._run_async())
        except BaseException as e:
            # capture the terminal exception for the watchdog: wait()/
            # prepare_batch() re-raise it within one poll interval — a
            # dead loop must not leave the trainer blocking out the full
            # request_timeout (3600 s) against a queue nobody fills
            self._thread_exc = e
            self._failed.set()
            logger.error(
                "rollout thread crashed:\n" + traceback.format_exc()
            )

    async def _run_async(self):
        pending: set = set()
        trace = self.config.enable_rollout_tracing
        while not self._exiting.is_set():
            # counted chaos fault point: tests kill the loop thread on an
            # exact iteration and assert the watchdog re-raises promptly
            chaos.trainer_fault("rollout_loop")
            # launch as many episodes as capacity allows
            capacity = self.get_capacity()
            launched = 0
            while capacity > 0 and not self._paused.is_set():
                # pop + running increment are one atomic step as seen by
                # wait()'s quarantine unsatisfiability check (which reads
                # running and the queue sizes under the same lock): an
                # in-launch item must never be invisible to both counts
                with self._lock:
                    try:
                        item = self.input_queue.get_nowait()
                    except queue.Empty:
                        break
                    self.rollout_stat.running += 1
                task = asyncio.create_task(
                    self._run_episode(item)
                )
                pending.add(task)
                task.add_done_callback(pending.discard)
                capacity -= 1
                launched += 1
                if trace:
                    logger.info(
                        f"launched episode (running={self.rollout_stat.running})"
                    )
            if pending:
                await asyncio.wait(
                    pending, timeout=0.02,
                    return_when=asyncio.FIRST_COMPLETED,
                )
            else:
                await asyncio.sleep(0.005)
        for t in pending:
            t.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)

    async def _run_episode(self, item: _WorkItem):
        """One episode with bounded retry: a flaky reward/env call gets
        ``max_episode_retries`` re-attempts under jittered exponential
        backoff (the utils/http.py policy shape); a sample that fails
        every attempt is quarantined — visible in stats and persisted
        via recover — instead of silently dropped forever."""
        dur = self.durability
        uid = item.uid or "?"
        batch = None
        failed = False
        # lineage/trace context: one trace id for the WHOLE episode —
        # retries and suffix-resume migrations stay on one timeline.
        # agenerate (running in this task's context, or child tasks that
        # inherit it) appends each request's server/version path here.
        episode = telemetry.EpisodeLineage(uid=item.uid or uid)
        ctx_token = telemetry.set_episode(episode)
        try:
            for attempt in range(dur.max_episode_retries + 1):
                episode.attempt = attempt
                try:
                    batch = await item.workflow.arun_episode(
                        self.engine, item.data
                    )
                    failed = False
                    break
                except Exception:
                    failed = True
                    self._record_outcome(failure=True)
                    logger.warning(
                        f"episode {uid} attempt "
                        f"{attempt + 1}/{dur.max_episode_retries + 1} "
                        f"failed:\n" + traceback.format_exc()
                    )
                    if attempt >= dur.max_episode_retries:
                        break
                    with self._lock:
                        self.rollout_stat.retried += 1
                    stats_tracker.counter(**{
                        "rollout/episode_retries_total": 1.0,
                    })
                    tracer = self._tracer()
                    if tracer is not None:
                        tracer.instant(
                            "episode_retry", uid, attempt=attempt,
                            trace=episode.trace_id,
                        )
                    await asyncio.sleep(backoff_delay(
                        attempt, dur.retry_delay, dur.max_retry_delay,
                        dur.retry_jitter,
                    ))
        finally:
            telemetry.reset_episode(ctx_token)
        if failed:
            with self._lock:
                self.rollout_stat.running -= 1
                self.rollout_stat.quarantined += 1
                if item.uid:
                    self.quarantined.add(item.uid)
                quarantined_total = self.rollout_stat.quarantined
            stats_tracker.counter(**{
                "rollout/quarantined_total": 1.0,
            })
            self.lineage.record_episode(episode, status="quarantined")
            tracer = self._tracer()
            if tracer is not None:
                tracer.instant(
                    "quarantine", uid,
                    attempts=dur.max_episode_retries + 1,
                    trace=episode.trace_id,
                )
            logger.error(
                f"episode {uid} QUARANTINED after "
                f"{dur.max_episode_retries + 1} attempts "
                f"(quarantined={quarantined_total})"
            )
            # no result is queued: wait()'s deliverable check (armed by
            # rollout_stat.quarantined) sees this episode vanish from
            # `running` and fails fast instead of blocking out its
            # timeout on a result that can never come
            return
        self._record_outcome(failure=False)
        with self._lock:
            if batch is None:
                self.rollout_stat.rejected += 1
                self.rollout_stat.running -= 1
                self.lineage.record_episode(episode, status="rejected")
                return
            self.rollout_stat.accepted += 1
        rewards = None
        r = batch.get("rewards") if hasattr(batch, "get") else None
        if r is not None:
            rewards = [float(x) for x in np.asarray(r).reshape(-1)]
        self.lineage.record_episode(
            episode, status="collected", rewards=rewards
        )
        # the result enters the queue BEFORE `running` drops so wait()'s
        # quarantine unsatisfiability check never misses an episode that
        # is between "finished" and "delivered"
        self.output_queue.put_nowait(
            _ResultItem(batch, item.create_time, uid=item.uid)
        )
        with self._lock:
            self.rollout_stat.running -= 1
        if self.config.enable_rollout_tracing:
            logger.info(
                f"episode done (accepted={self.rollout_stat.accepted})"
            )


def zero_signal_filter(batch: Dict[str, np.ndarray]) -> bool:
    """The canonical DAPO group filter: keep an episode's group only if
    its rewards are not all identical (all-same rewards normalize to zero
    advantage — pure gradient noise). Pass as ``group_filter=`` to
    prepare_batch/rollout_batch/wait."""
    r = np.asarray(batch.get("rewards", ())).reshape(-1)
    return bool(r.size <= 1 or (r != r.flat[0]).any())


def cycle_dataloader(dataloader):
    """Endless epoch-wrapping iterator over a dataloader."""
    while True:
        for batch in dataloader:
            yield batch
