"""ctypes loader for the native helpers in interval_ops.cpp.

Builds the shared library on first import (g++ is in the image; no pybind11
needed — plain C ABI + ctypes). All entry points degrade gracefully: callers
fall back to numpy implementations when the toolchain is unavailable.
"""

import ctypes
import os
import subprocess
import threading
from typing import List, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "interval_ops.cpp")
_LIB_PATH = os.path.join(_DIR, "libinterval_ops.so")
_lock = threading.Lock()
_lib = None


def _build() -> None:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _LIB_PATH]
    subprocess.run(cmd, check=True, capture_output=True)


# Build eagerly so a missing toolchain surfaces as ImportError here and
# callers (utils/datapack.py) fall back to their numpy paths, instead of
# crashing at first call.
def _ensure_available() -> None:
    try:
        _load()
    except Exception as e:  # pragma: no cover - toolchain-dependent
        raise ImportError(f"areal_tpu.csrc native build unavailable: {e}") from e


def _load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC):
            _build()
        lib = ctypes.CDLL(_LIB_PATH)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        u16p = ctypes.POINTER(ctypes.c_uint16)
        lib.merge_intervals.restype = ctypes.c_int64
        lib.merge_intervals.argtypes = [i64p, i64p, ctypes.c_int64]
        lib.slice_intervals_f32.restype = ctypes.c_int64
        lib.slice_intervals_f32.argtypes = [f32p, i64p, i64p, ctypes.c_int64, f32p]
        lib.set_intervals_f32.restype = ctypes.c_int64
        lib.set_intervals_f32.argtypes = [f32p, i64p, i64p, ctypes.c_int64, f32p]
        lib.slice_intervals_u16.restype = ctypes.c_int64
        lib.slice_intervals_u16.argtypes = [u16p, i64p, i64p, ctypes.c_int64, u16p]
        lib.set_intervals_u16.restype = ctypes.c_int64
        lib.set_intervals_u16.argtypes = [u16p, i64p, i64p, ctypes.c_int64, u16p]
        lib.ffd_allocate.restype = ctypes.c_int64
        lib.ffd_allocate.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, i64p]
        _lib = lib
        return lib


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def merge_intervals(intervals: Sequence) -> List:
    """Coalesce sorted [start, end) pairs (reference csrc/interval_op.cpp:4-29)."""
    arr = np.asarray(intervals, dtype=np.int64)
    if arr.size == 0:
        return []
    starts = np.ascontiguousarray(arr[:, 0])
    ends = np.ascontiguousarray(arr[:, 1])
    lib = _load()
    n = lib.merge_intervals(_i64(starts), _i64(ends), len(starts))
    return list(zip(starts[:n].tolist(), ends[:n].tolist()))


def slice_intervals(src: np.ndarray, intervals: Sequence) -> np.ndarray:
    """Gather many (start, end) slices of a flat array into one contiguous
    array (reference csrc/interval_op.cu slice_intervals)."""
    arr = np.asarray(intervals, dtype=np.int64).reshape(-1, 2)
    starts = np.ascontiguousarray(arr[:, 0])
    ends = np.ascontiguousarray(arr[:, 1])
    total = int((ends - starts).sum())
    src = np.ascontiguousarray(src)
    lib = _load()
    if src.dtype == np.float32:
        out = np.empty(total, np.float32)
        lib.slice_intervals_f32(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            _i64(starts), _i64(ends), len(starts),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out
    if src.dtype.itemsize == 2:
        view = src.view(np.uint16)
        out = np.empty(total, np.uint16)
        lib.slice_intervals_u16(
            view.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            _i64(starts), _i64(ends), len(starts),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)))
        return out.view(src.dtype)
    return np.concatenate([src[s:e] for s, e in zip(starts, ends)])


def set_intervals(src: np.ndarray, dst: np.ndarray, intervals: Sequence) -> None:
    """Scatter a contiguous array into many (start, end) slices of `dst`
    (reference csrc/interval_op.cu set_intervals)."""
    arr = np.asarray(intervals, dtype=np.int64).reshape(-1, 2)
    starts = np.ascontiguousarray(arr[:, 0])
    ends = np.ascontiguousarray(arr[:, 1])
    src = np.ascontiguousarray(src)
    assert dst.flags["C_CONTIGUOUS"]
    lib = _load()
    if dst.dtype == np.float32:
        lib.set_intervals_f32(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            _i64(starts), _i64(ends), len(starts),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    elif dst.dtype.itemsize == 2:
        lib.set_intervals_u16(
            src.view(np.uint16).ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            _i64(starts), _i64(ends), len(starts),
            dst.view(np.uint16).ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)))
    else:
        off = 0
        for s, e in zip(starts, ends):
            dst[s:e] = src[off : off + (e - s)]
            off += e - s


def ffd_allocate(sizes: Sequence[int], capacity: int, min_groups: int = 1) -> List[List[int]]:
    """First-fit-decreasing bin packing; returns index groups."""
    sizes_arr = np.ascontiguousarray(np.asarray(sizes, dtype=np.int64))
    n = len(sizes_arr)
    if n == 0:
        return []
    bin_of = np.empty(n, np.int64)
    lib = _load()
    n_bins = lib.ffd_allocate(_i64(sizes_arr), n, capacity, min_groups, _i64(bin_of))
    groups: List[List[int]] = [[] for _ in range(n_bins)]
    for idx, b in enumerate(bin_of.tolist()):
        groups[b].append(idx)
    return groups


_ensure_available()
