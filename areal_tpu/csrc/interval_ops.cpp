// Native helpers for host-side batching and parameter repacking.
//
// TPU-native equivalent of the reference's csrc/ extensions
// (csrc/interval_op/interval_op.cpp merge_intervals; interval_op.cu
// slice/set_intervals; plus an FFD bin-packing fast path used by
// areal_tpu/utils/datapack.py). On TPU the *device-side* scatter/gather of
// param slices is obviated by jax.Array resharding, but the host staging
// path (weight export to generation servers) still slices many
// (offset, len) intervals out of flat buffers — done here in C++.
//
// C ABI only; loaded from Python via ctypes (see __init__.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// Coalesce sorted [start, end) intervals in-place.
// Returns the number of merged intervals written back to `starts`/`ends`.
int64_t merge_intervals(int64_t* starts, int64_t* ends, int64_t n) {
  if (n <= 0) return 0;
  int64_t w = 0;
  for (int64_t i = 1; i < n; ++i) {
    if (starts[i] == ends[w]) {
      ends[w] = ends[i];
    } else {
      ++w;
      starts[w] = starts[i];
      ends[w] = ends[i];
    }
  }
  return w + 1;
}

// Gather many [start, end) intervals of a flat float32 buffer into `out`
// (contiguous). Returns total elements copied.
int64_t slice_intervals_f32(const float* src, const int64_t* starts,
                            const int64_t* ends, int64_t n, float* out) {
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t len = ends[i] - starts[i];
    std::memcpy(out + off, src + starts[i], sizeof(float) * len);
    off += len;
  }
  return off;
}

// Scatter a contiguous float32 buffer back into many [start, end) intervals.
int64_t set_intervals_f32(const float* src, const int64_t* starts,
                          const int64_t* ends, int64_t n, float* dst) {
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t len = ends[i] - starts[i];
    std::memcpy(dst + starts[i], src + off, sizeof(float) * len);
    off += len;
  }
  return off;
}

// 16-bit variants (bf16/fp16 move as opaque uint16).
int64_t slice_intervals_u16(const uint16_t* src, const int64_t* starts,
                            const int64_t* ends, int64_t n, uint16_t* out) {
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t len = ends[i] - starts[i];
    std::memcpy(out + off, src + starts[i], sizeof(uint16_t) * len);
    off += len;
  }
  return off;
}

int64_t set_intervals_u16(const uint16_t* src, const int64_t* starts,
                          const int64_t* ends, int64_t n, uint16_t* dst) {
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t len = ends[i] - starts[i];
    std::memcpy(dst + starts[i], src + off, sizeof(uint16_t) * len);
    off += len;
  }
  return off;
}

// First-fit-decreasing bin packing. Writes the bin id of each item into
// `bin_of` and returns the number of bins used (>= min_groups).
int64_t ffd_allocate(const int64_t* sizes, int64_t n, int64_t capacity,
                     int64_t min_groups, int64_t* bin_of) {
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) { return sizes[a] > sizes[b]; });
  std::vector<int64_t> loads;
  std::vector<bool> empty_flag;
  loads.assign(std::max<int64_t>(min_groups, 1), 0);
  empty_flag.assign(loads.size(), true);
  for (int64_t k = 0; k < n; ++k) {
    int64_t idx = order[k];
    int64_t size = sizes[idx];
    int64_t placed = -1;
    for (size_t b = 0; b < loads.size(); ++b) {
      if (loads[b] + size <= capacity || (empty_flag[b] && size > capacity)) {
        placed = static_cast<int64_t>(b);
        break;
      }
    }
    if (placed < 0) {
      loads.push_back(0);
      empty_flag.push_back(true);
      placed = static_cast<int64_t>(loads.size()) - 1;
    }
    loads[placed] += size;
    empty_flag[placed] = false;
    bin_of[idx] = placed;
  }
  return static_cast<int64_t>(loads.size());
}

}  // extern "C"
