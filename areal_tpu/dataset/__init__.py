"""Dataset loading + a stateful dataloader for RL/SFT training.

Role of reference areal/dataset/__init__.py (`get_custom_dataset`) and the
torchdata StatefulDataLoader the trainer checkpoints: datasets load from
local jsonl files (the training environment is egress-free; the reference
pulls from the HF hub) and the dataloader exposes state_dict/
load_state_dict so recover resumes mid-epoch without repeating samples.
"""

import json
import os
import random
from typing import Any, Callable, Dict, Iterator, List, Optional

from areal_tpu.api.cli_args import DatasetConfig


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def _gsm8k_to_rl(row: Dict[str, Any], tokenizer=None) -> Dict[str, Any]:
    """GSM8K schema {question, answer} → workflow item. The answer keeps its
    '#### <final>' tail; math_parser extracts it for the reward."""
    out = {"answer": row["answer"]}
    if tokenizer is not None:
        out["messages"] = [{"role": "user", "content": row["question"]}]
    else:
        out["question"] = row["question"]
    return out


def _code_to_rl(row: Dict[str, Any], tokenizer=None) -> Dict[str, Any]:
    """Code-RLVR schema → workflow item: {question/prompt, test_cases |
    test_code} (reference code datasets feed functioncall verification;
    realhf/impl/dataset/ math_code jsonl)."""
    out: Dict[str, Any] = {}
    if "test_cases" in row:
        out["test_cases"] = row["test_cases"]
    if "test_code" in row:
        out["test_code"] = row["test_code"]
    q = row.get("question") or row.get("prompt") or ""
    if tokenizer is not None:
        out["messages"] = [{"role": "user", "content": q}]
    else:
        out["question"] = q
    return out


def _math_to_rl(row: Dict[str, Any], tokenizer=None) -> Dict[str, Any]:
    """Generic math schema {question/problem, answer/solution} (MATH,
    AIME-style jsonl; reference areal/dataset math loaders)."""
    # explicit key checks: `or` would drop falsy-but-valid answers (0, 0.0)
    if "answer" in row:
        answer = row["answer"]
    else:
        answer = row.get("solution", "")
    out = {"answer": str(answer)}
    q = row.get("question") or row.get("problem") or ""
    if tokenizer is not None:
        out["messages"] = [{"role": "user", "content": q}]
    else:
        out["question"] = q
    return out


def _vision_to_rl(row: Dict[str, Any], tokenizer=None) -> Dict[str, Any]:
    """VLM schema {images: [paths], question, answer} (clevr_count /
    geometry3k-style; reference areal/dataset/__init__.py VLM loaders).
    Image PATHS stay lazy — the vision workflow decodes them per episode,
    so a 70k-row dataset never materializes every image in RAM."""
    out: Dict[str, Any] = {"answer": str(row.get("answer", ""))}
    paths = row.get("images") or row.get("image") or []
    if isinstance(paths, str):
        paths = [paths]
    out["images"] = list(paths)
    q = row.get("question") or row.get("prompt") or ""
    out["messages"] = [{"role": "user", "content": q}]
    return out


_PROCESSORS: Dict[str, Callable] = {
    "gsm8k": _gsm8k_to_rl,
    "math": _math_to_rl,
    "code": _code_to_rl,
    "clevr_count": _vision_to_rl,
    "geometry3k": _vision_to_rl,
    "vision": _vision_to_rl,
    "raw": lambda row, tokenizer=None: row,
}


def get_custom_dataset(
    config: DatasetConfig,
    tokenizer=None,
    split: str = "train",
) -> List[Dict[str, Any]]:
    """Load + convert a dataset (reference areal/dataset/__init__.py:1-99).

    ``config.path`` may be a .jsonl file or a directory containing
    ``{split}.jsonl``.
    """
    path = config.path
    if os.path.isdir(path):
        path = os.path.join(path, f"{split}.jsonl")
    rows = load_jsonl(path)
    proc = _PROCESSORS.get(config.type, _PROCESSORS["raw"])
    out = [proc(r, tokenizer=tokenizer) for r in rows]
    if config.max_length is not None and tokenizer is not None:
        out = [
            r
            for r in out
            if "messages" not in r
            or len(tokenizer.apply_chat_template(r["messages"], tokenize=True))
            <= config.max_length
        ]
    return out


class StatefulDataLoader:
    """Shuffling epoch dataloader with resumable state (role of torchdata's
    StatefulDataLoader in the reference recover path).

    One ``__iter__`` pass yields the REMAINDER of the current epoch (so a
    resumed run continues where it left off); callers loop epochs.
    """

    def __init__(
        self,
        dataset: List[Any],
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        collate_fn: Optional[Callable] = None,
    ):
        assert batch_size >= 1
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or (lambda x: x)
        self._epoch = 0
        self._batch_idx = 0  # batches already yielded in the current epoch

    def __len__(self) -> int:
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    @property
    def steps_per_epoch(self) -> int:
        return len(self)

    @property
    def epoch(self) -> int:
        return self._epoch

    def _order(self) -> List[int]:
        order = list(range(len(self.dataset)))
        if self.shuffle:
            random.Random(self.seed + self._epoch).shuffle(order)
        return order

    def __iter__(self) -> Iterator[Any]:
        order = self._order()
        nb = len(self)
        for b in range(self._batch_idx, nb):
            idx = order[b * self.batch_size : (b + 1) * self.batch_size]
            if not idx:
                continue
            self._batch_idx = b + 1
            yield self.collate_fn([self.dataset[i] for i in idx])
        self._epoch += 1
        self._batch_idx = 0

    def state_dict(self) -> Dict[str, int]:
        return {"epoch": self._epoch, "batch_idx": self._batch_idx}

    def load_state_dict(self, state: Dict[str, int]):
        self._epoch = int(state["epoch"])
        self._batch_idx = int(state["batch_idx"])
