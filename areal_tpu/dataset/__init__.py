"""Dataset loading + a stateful dataloader for RL/SFT training.

Role of reference areal/dataset/__init__.py (`get_custom_dataset`) and the
torchdata StatefulDataLoader the trainer checkpoints: datasets load from
local jsonl files (the training environment is egress-free; the reference
pulls from the HF hub) and the dataloader exposes state_dict/
load_state_dict so recover resumes mid-epoch without repeating samples.
"""

import json
import os
import random
from typing import Any, Callable, Dict, Iterator, List, Optional

from areal_tpu.api.cli_args import DatasetConfig


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def _gsm8k_to_rl(row: Dict[str, Any], tokenizer=None) -> Dict[str, Any]:
    """GSM8K schema {question, answer} → workflow item. The answer keeps its
    '#### <final>' tail; math_parser extracts it for the reward."""
    out = {"answer": row["answer"]}
    if tokenizer is not None:
        out["messages"] = [{"role": "user", "content": row["question"]}]
    else:
        out["question"] = row["question"]
    return out


def _code_to_rl(row: Dict[str, Any], tokenizer=None) -> Dict[str, Any]:
    """Code-RLVR schema → workflow item: {question/prompt, test_cases |
    test_code} (reference code datasets feed functioncall verification;
    realhf/impl/dataset/ math_code jsonl)."""
    out: Dict[str, Any] = {}
    if "test_cases" in row:
        out["test_cases"] = row["test_cases"]
    if "test_code" in row:
        out["test_code"] = row["test_code"]
    q = row.get("question") or row.get("prompt") or ""
    if tokenizer is not None:
        out["messages"] = [{"role": "user", "content": q}]
    else:
        out["question"] = q
    return out


def _math_to_rl(row: Dict[str, Any], tokenizer=None) -> Dict[str, Any]:
    """Generic math schema {question/problem, answer/solution} (MATH,
    AIME-style jsonl; reference areal/dataset math loaders)."""
    # explicit key checks: `or` would drop falsy-but-valid answers (0, 0.0)
    if "answer" in row:
        answer = row["answer"]
    else:
        answer = row.get("solution", "")
    out = {"answer": str(answer)}
    q = row.get("question") or row.get("problem") or ""
    if tokenizer is not None:
        out["messages"] = [{"role": "user", "content": q}]
    else:
        out["question"] = q
    return out


def _vision_to_rl(row: Dict[str, Any], tokenizer=None) -> Dict[str, Any]:
    """VLM schema {images: [paths], question, answer} (clevr_count /
    geometry3k-style; reference areal/dataset/__init__.py VLM loaders).
    Image PATHS stay lazy — the vision workflow decodes them per episode,
    so a 70k-row dataset never materializes every image in RAM."""
    out: Dict[str, Any] = {"answer": str(row.get("answer", ""))}
    paths = row.get("images") or row.get("image") or []
    if isinstance(paths, str):
        paths = [paths]
    out["images"] = list(paths)
    q = row.get("question") or row.get("prompt") or ""
    out["messages"] = [{"role": "user", "content": q}]
    return out


_PROCESSORS: Dict[str, Callable] = {
    "gsm8k": _gsm8k_to_rl,
    "math": _math_to_rl,
    "code": _code_to_rl,
    "clevr_count": _vision_to_rl,
    "geometry3k": _vision_to_rl,
    "vision": _vision_to_rl,
    "raw": lambda row, tokenizer=None: row,
}


def get_custom_dataset(
    config: DatasetConfig,
    tokenizer=None,
    split: str = "train",
) -> List[Dict[str, Any]]:
    """Load + convert a dataset (reference areal/dataset/__init__.py:1-99).

    ``config.path`` may be a .jsonl file or a directory containing
    ``{split}.jsonl``.
    """
    path = config.path
    if os.path.isdir(path):
        path = os.path.join(path, f"{split}.jsonl")
    rows = load_jsonl(path)
    proc = _PROCESSORS.get(config.type, _PROCESSORS["raw"])
    out = [proc(r, tokenizer=tokenizer) for r in rows]
    if config.max_length is not None and tokenizer is not None:
        out = [
            r
            for r in out
            if "messages" not in r
            or len(tokenizer.apply_chat_template(r["messages"], tokenize=True))
            <= config.max_length
        ]
    return out


class StatefulDataLoader:
    """Shuffling epoch dataloader with resumable state (role of torchdata's
    StatefulDataLoader in the reference recover path).

    One ``__iter__`` pass yields the REMAINDER of the current epoch (so a
    resumed run continues where it left off); callers loop epochs.

    Used-data exclusion (reference realhf/base/recover.py +
    master_worker.py:121-128): ``mark_used(uids)`` records CONSUMED
    samples; after a resume that restored a non-empty used set, iteration
    restarts the epoch from the top and skips exactly those samples — so
    nothing is trained twice AND submitted-but-unconsumed items (whose
    in-flight rollouts died with the crash) are re-yielded rather than
    silently dropped by a submit-cursor restore. The set clears at each
    epoch boundary.
    """

    def __init__(
        self,
        dataset: List[Any],
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        collate_fn: Optional[Callable] = None,
    ):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or (lambda x: x)
        self._epoch = 0
        self._batch_idx = 0  # batches already yielded in the current epoch
        self._used: set = set()  # consumed-sample uids (current epoch)
        self._yielded_epoch: set = set()  # uids yielded this epoch
        self._scan_from_start = False  # resume mode: re-scan + skip used

    def __len__(self) -> int:
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    @property
    def steps_per_epoch(self) -> int:
        return len(self)

    @property
    def epoch(self) -> int:
        return self._epoch

    def _order(self) -> List[int]:
        order = list(range(len(self.dataset)))
        if self.shuffle:
            random.Random(self.seed + self._epoch).shuffle(order)
        return order

    def mark_used(self, uids) -> None:
        """Record consumed samples. Only uids yielded in the CURRENT epoch
        count: a straggler consumed from a previous epoch refers to that
        epoch's visit — marking it here would wrongly block its legitimate
        re-visit this epoch (each epoch trains every sample once)."""
        self._used.update(u for u in uids if u in self._yielded_epoch)

    def _uid(self, item) -> str:
        from areal_tpu.utils.data import sample_uid

        return sample_uid(item)

    def __iter__(self) -> Iterator[Any]:
        order = self._order()
        nb = len(self)
        start = 0 if self._scan_from_start else self._batch_idx
        self._scan_from_start = False
        for b in range(start, nb):
            idx = order[b * self.batch_size : (b + 1) * self.batch_size]
            if not idx:
                continue
            self._batch_idx = max(self._batch_idx, b + 1)
            items = [self.dataset[i] for i in idx]
            uids = [self._uid(it) for it in items]
            if self._used:
                keep = [u not in self._used for u in uids]
                items = [it for it, k in zip(items, keep) if k]
                uids = [u for u, k in zip(uids, keep) if k]
                if not items:
                    continue
            self._yielded_epoch.update(uids)
            yield self.collate_fn(items)
        self._epoch += 1
        self._batch_idx = 0
        self._used.clear()
        self._yielded_epoch.clear()

    def state_dict(self) -> Dict[str, Any]:
        return {
            "epoch": self._epoch,
            "batch_idx": self._batch_idx,
            "used": sorted(self._used),
            "yielded": sorted(self._yielded_epoch),
        }

    def load_state_dict(self, state: Dict[str, Any]):
        self._epoch = int(state["epoch"])
        self._batch_idx = int(state["batch_idx"])
        self._used = set(state.get("used", ()))
        self._yielded_epoch = set(state.get("yielded", ()))
        # a restored used set means async items past the consume point may
        # be unconsumed: re-scan the epoch and skip exactly the used ones
        self._scan_from_start = bool(self._used)
