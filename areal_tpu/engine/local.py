"""Colocated inference engine: trainer + generator in one process/runtime.

Role of reference areal/experimental/sglang_engine.py (in-process
`SGLangEngine` for colocated mode) — but on TPU this is the PRIMARY
single-slice deployment, not an experiment: a TPU chip is owned by exactly
one process, so trainer and generator colocate by sharing the jax runtime.
The payoff is the fast weight path — ``update_weights`` hands the trainer's
device params straight to the generation engine (an HBM-to-HBM cast/copy,
role of the reference's custom NCCL broadcast group fsdp_engine.py:399-433)
with no disk or network hop.
"""

import concurrent.futures
import threading
import time
from typing import Any, Dict, List, Optional

from areal_tpu.api.cli_args import InferenceEngineConfig, JaxGenConfig
from areal_tpu.api.engine_api import InferenceEngine
from areal_tpu.api.io_struct import (
    ModelRequest,
    ModelResponse,
    WeightUpdateMeta,
    WeightUpdateMethod,
)
from areal_tpu.api.workflow_api import RolloutWorkflow, WorkflowExecutor
from areal_tpu.inference.engine import GenerationEngine
from areal_tpu.utils import goodput
from areal_tpu.utils import logging as logging_util
from areal_tpu.utils import stats_tracker

logger = logging_util.getLogger("LocalSyncInferenceEngine")


class LocalSyncInferenceEngine(InferenceEngine):
    """InferenceEngine over an in-process GenerationEngine."""

    def __init__(
        self,
        config: InferenceEngineConfig,
        gen_config: JaxGenConfig,
        model_config=None,
        params=None,
    ):
        self.config = config
        self.engine = GenerationEngine(
            gen_config, model_config=model_config, params=params
        )
        self._version = 0
        self._lock = threading.Lock()
        self.executor = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        self.workflow_executor: Optional[WorkflowExecutor] = None
        self._train_engine = None  # set for the device weight path

    # ------------------------------------------------------------------
    def initialize(self, train_engine=None):
        self._train_engine = train_engine
        self.engine.start()
        self.workflow_executor = WorkflowExecutor(self.config, self)
        self.workflow_executor.initialize()
        return self

    def destroy(self):
        if self.workflow_executor is not None:
            self.workflow_executor.destroy()
        self.engine.stop()
        self.executor.shutdown(wait=False)

    # ------------------------------------------------------------------
    def get_version(self) -> int:
        with self._lock:
            return self._version

    def set_version(self, version: int):
        with self._lock:
            self._version = version
        self.engine.model_version = version

    # ------------------------------------------------------------------
    async def agenerate(self, req: ModelRequest) -> ModelResponse:
        """Submit to the in-process engine; the abort/resume loop still
        applies (pause aborts in-flight slots exactly like the server)."""
        import asyncio

        gconfig = req.gconfig
        if gconfig.n_samples != 1:
            raise ValueError(
                "agenerate expects n_samples=1; workflows fan out samples"
            )
        start = time.monotonic()
        accumulated: List[int] = []
        logprobs: List[float] = []
        versions: List[int] = []
        stop_reason = None
        ttft = None
        # lineage + trace context, same shape as the remote engine so
        # ledgers/dashboards don't care about deployment mode (the one
        # "server" is the in-process engine)
        from areal_tpu.utils import telemetry as _telemetry

        episode = _telemetry.current_episode()
        lineage = _telemetry.RequestLineage(
            rid=req.rid,
            attempt=episode.attempt if episode is not None else 0,
            # same stamps as the remote engine so lineage records read
            # identically across deployment modes: the policy handle is
            # recorded as stamped (a single local engine has no router
            # to resolve canaries), plus the self-play agent/role
            policy=str(req.metadata.get("policy") or ""),
            agent=str(req.metadata.get("agent") or ""),
            role=str(req.metadata.get("role") or ""),
        )
        if episode is not None:
            self.engine.tracer.bind_trace(req.rid, episode.trace_id)
        try:
            while (
                stop_reason not in ("stop", "length")
                and len(accumulated) < gconfig.max_new_tokens
            ):
                payload_extra = (
                    {"mm": req.mm}
                    if getattr(req, "mm", None) is not None else {}
                )
                # traffic-plane class rides into the in-process engine
                # too: self-play opponent turns stamp "interactive" and
                # get the bounded-TTFT scheduling the remote path has.
                # The policy handle is NOT forwarded — a single local
                # engine has no policy registry, and an unregistered
                # name would 400 at submit (the remote path resolves
                # handles in the router instead).
                priority = str(req.metadata.get("priority") or "bulk")
                if priority not in ("interactive", "bulk"):
                    priority = "bulk"
                fut = self.engine.submit(
                    {
                        "rid": req.rid,
                        "input_ids": list(req.input_ids) + accumulated,
                        "priority": priority,
                        **payload_extra,
                        "sampling_params": {
                            "max_new_tokens": gconfig.max_new_tokens
                            - len(accumulated),
                            "min_new_tokens": max(
                                0, gconfig.min_new_tokens - len(accumulated)
                            ),
                            "temperature": gconfig.temperature,
                            "top_p": gconfig.top_p,
                            "top_k": gconfig.top_k,
                            "greedy": gconfig.greedy,
                            "stop_token_ids": gconfig.stop_token_ids,
                        },
                    }
                )
                result = await asyncio.wrap_future(fut)
                if ttft is None and result["output_ids"]:
                    # engine-side ttft, re-based onto this call's clock
                    meta = result["meta_info"]
                    ttft = (
                        (time.monotonic() - start)
                        - meta["latency"] + meta["ttft"]
                    )
                if result["output_ids"]:
                    lineage.add_segment(
                        "local", len(result["output_ids"]),
                        result["output_versions"],
                    )
                accumulated.extend(result["output_ids"])
                logprobs.extend(result["output_logprobs"])
                versions.extend(result["output_versions"])
                stop_reason = result["meta_info"]["finish_reason"]["type"]
                if stop_reason == "abort":
                    await asyncio.sleep(
                        self.config.pause_grace_period or 0.05
                    )
        finally:
            # a mid-generation exception must still unbind the rid and
            # hand the partial path to the episode record (same contract
            # as the remote engine's finally block)
            if episode is not None:
                self.engine.tracer.unbind_trace(req.rid)
                episode.add_request(lineage)
        if versions:
            # generation-time staleness vs the trainer (same keys as the
            # remote engine so dashboards don't care about deployment mode)
            trainer_v = self.get_version()
            lags = [trainer_v - v for v in versions]
            now = time.monotonic()
            stats_tracker.scalar(**{
                "rollout/staleness_lag_mean": sum(lags) / len(lags),
                "rollout/staleness_lag_max": float(max(lags)),
                "rollout/ttft_s": ttft if ttft is not None else now - start,
                "rollout/latency_s": now - start,
                "rollout/output_tokens": float(len(accumulated)),
            })
        return ModelResponse(
            input_tokens=list(req.input_ids),
            output_tokens=accumulated,
            output_logprobs=logprobs,
            output_versions=versions,
            stop_reason=stop_reason or "length",
            latency=time.monotonic() - start,
            ttft=ttft if ttft is not None else time.monotonic() - start,
        )

    # ------------------------------------------------------------------
    def update_weights(self, meta: WeightUpdateMeta) -> concurrent.futures.Future:
        """DEVICE path: hand the trainer's live params to the generator —
        the ICI/HBM analog of the reference's NCCL broadcast. With the
        zero-pause weight plane on (both this client's
        ``streamed_weight_updates`` and the engine's
        ``weights.streaming``), the copy happens off the engine loop and
        the new buffer flips in at a dispatch boundary — in-flight slots
        keep decoding (version-fenced) instead of aborting into a pause
        window."""
        t_pause = time.monotonic()
        method = (
            "tensors" if meta.type == WeightUpdateMethod.DEVICE else "disk"
        )
        streamed = bool(
            getattr(self.config, "streamed_weight_updates", True)
        ) and self.engine.streams_weight_updates(method)
        if not streamed:
            self.engine.pause()

        def _do():
            try:
                if meta.type == WeightUpdateMethod.DEVICE:
                    if self._train_engine is None:
                        raise RuntimeError(
                            "device weight path needs "
                            "initialize(train_engine=...)"
                        )
                    self.engine.update_weights_from_tensors(
                        self._train_engine.params, version=meta.model_version
                    )
                else:
                    self.engine.update_weights_from_disk(
                        meta.path, version=meta.model_version
                    )
                self.set_version(meta.model_version)
            finally:
                if streamed:
                    stats_tracker.scalar(**{
                        "rollout/weight_stream_s":
                            time.monotonic() - t_pause
                    })
                else:
                    self.engine.continue_generation()
                    stats_tracker.scalar(**{
                        "rollout/pause_window_s":
                            time.monotonic() - t_pause
                    })

        return self.executor.submit(_do)

    # ------------------------------------------------------------------
    def submit(self, data: Dict[str, Any], workflow: RolloutWorkflow) -> bool:
        """False when the sample is quarantined (not queued) — submit-N/
        wait-N callers must not count it or wait() starves."""
        return self.workflow_executor.submit(data, workflow)

    def wait(self, count: int, timeout: Optional[float] = None,
             group_filter=None):
        # rollout_wait bucket mirrors engine/remote.py: trainer wall
        # time blocked on generation (reentrant no-op under an outer
        # bucket)
        with goodput.trainer_bucket("rollout_wait"):
            return self.workflow_executor.wait(
                count, timeout=timeout, group_filter=group_filter
            )

    def rollout_batch(self, data: List[Dict[str, Any]], workflow,
                      group_filter=None):
        with goodput.trainer_bucket("rollout_wait"):
            return self.workflow_executor.rollout_batch(
                data, workflow, group_filter=group_filter
            )

    def prepare_batch(self, dataloader, workflow, group_filter=None):
        with goodput.trainer_bucket("rollout_wait"):
            return self.workflow_executor.prepare_batch(
                dataloader, workflow, group_filter=group_filter
            )

    def pause(self):
        self.workflow_executor.pause()

    def resume(self):
        self.workflow_executor.resume()
