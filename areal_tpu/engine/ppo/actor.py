"""PPO/GRPO actor: the algorithm layer over any TrainEngine.

Role of reference areal/engine/ppo/actor.py (`PPOActor`, `FSDPPPOActor`,
`grpo_loss_fn`): reward shaping → advantage estimation → minibatched
decoupled-PPO updates. Device math (GAE, whitening, the loss) is jnp and
jit-traced inside the engine; host-side orchestration (minibatch splitting,
dynamic sampling) is numpy on padded batches.

Data layout (padded Batch, all aligned to TARGET token position t =
"token t given prefix < t"):
- input_ids [B, L], attention_mask [B, L]
- loss_mask [B, L]: 1 on completion tokens (positions to train)
- logprobs  [B, L]: behavior-policy logprobs of token t (0 on prompt)
- versions  [B, L]: weight version that generated token t (-1 prompt)
- rewards   [B]: scalar episode rewards
"""

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.cli_args import PPOActorConfig
from areal_tpu.api.engine_api import TrainEngine
from areal_tpu.engine.spmd_engine import target_aligned_logprobs
from areal_tpu.ops import functional as F
from areal_tpu.utils import stats_tracker
from areal_tpu.utils.data import Batch, batch_select, batch_size


class PPOActor:
    """Algorithm wrapper (reference ppo/actor.py:24)."""

    def __init__(self, config: PPOActorConfig, engine: TrainEngine):
        self.config = config
        self.engine = engine
        self.reward_bias = config.reward_bias
        self.reward_scaling = config.reward_scaling
        self.reward_clip = config.reward_clip
        self.group_size = config.group_size
        # KL coefficient: fixed, or adapted toward kl_target (reference
        # ppo_functional.py:14-49 controllers)
        if getattr(config, "kl_adaptive", False):
            self.kl_controller = F.AdaptiveKLController(
                config.kl_ctl, config.kl_target, config.kl_horizon
            )
        else:
            self.kl_controller = F.FixedKLController(config.kl_ctl)

    # ------------------------------------------------------------------
    def compute_logp(self, data: Batch, temperature: Optional[float] = None) -> np.ndarray:
        """Recompute logprobs of the batch tokens under current weights
        (reference ppo/actor.py:48 `compute_logp`)."""
        temp = temperature if temperature is not None else self.config.temperature
        # cache the hook per temperature: the engine keys its jitted program
        # on hook identity, so a fresh closure per call would recompile
        if not hasattr(self, "_logp_hooks"):
            self._logp_hooks = {}
        if temp not in self._logp_hooks:

            def hook(logits, arrays, _temp=temp):
                return target_aligned_logprobs(logits, arrays, temperature=_temp)

            self._logp_hooks[temp] = hook
        return self.engine.forward(data, post_hook=self._logp_hooks[temp])

    # ------------------------------------------------------------------
    def compute_advantages(self, data: Batch) -> Batch:
        """Reward shaping + GAE + advantage normalization (reference
        ppo/actor.py:67-159). Returns `data` with added keys: advantages,
        kl_rewards, tot_rewards (all [B, L] target-aligned)."""
        cfg = self.config
        mask = np.asarray(data["attention_mask"]).astype(bool)
        loss_mask = np.asarray(data["loss_mask"]).astype(np.float32)
        bsz, L = mask.shape
        reward_score = np.asarray(data["rewards"]).astype(np.float32)
        reward_score = (reward_score + self.reward_bias) * self.reward_scaling
        reward_score = np.clip(
            reward_score, -self.reward_clip, self.reward_clip
        )
        if cfg.group_reward_norm and self.group_size > 1:
            reward_score = np.asarray(
                F.grpo_group_norm_rewards(
                    jnp.asarray(reward_score), self.group_size
                )
            )
        if cfg.overlong_reward_penalty:
            gen_lens = loss_mask.sum(1)
            reward_score = np.asarray(
                F.reward_overlong_penalty(
                    jnp.asarray(gen_lens), jnp.asarray(reward_score),
                    cfg.overlong_tokens, cfg.overlong_penalty_factor,
                    cfg.max_new_tokens,
                )
            )

        logprobs = np.asarray(
            data.get("prox_logp", data["logprobs"])
        ).astype(np.float32)
        ref_logp = data.get("ref_logp")
        # dense KL reward on completion positions
        kl_ctl = self.kl_controller.value
        if ref_logp is not None and kl_ctl != 0.0:
            kl_est = (
                logprobs - np.asarray(ref_logp, np.float32)
            ) * loss_mask
            kl_rewards = -kl_ctl * kl_est
            n_tok = max(1.0, float(loss_mask.sum()))
            # n_steps is the SEQUENCE count (reference
            # realhf/impl/model/interface/ppo_interface.py:176), not the
            # token count — with kl_horizon ~1e4 a token count would swing
            # the adaptive coefficient by 5x+ per update
            self.kl_controller.update(float(kl_est.sum() / n_tok), bsz)
        else:
            kl_rewards = np.zeros_like(loss_mask)
        tok_rewards = kl_rewards.copy()
        # terminal scalar reward at the last completion token
        lens = mask.sum(1).astype(np.int64)
        last_idx = np.maximum(lens - 1, 0)
        tok_rewards[np.arange(bsz), last_idx] += reward_score

        values = np.asarray(
            data.get("values", np.zeros_like(loss_mask))
        ).astype(np.float32)
        adv, returns = _gae_jit(
            jnp.asarray(tok_rewards), jnp.asarray(values),
            jnp.asarray(mask.astype(np.float32)), cfg.gamma, cfg.lam,
        )
        adv = np.asarray(adv)
        # returns feed the critic's clipped value loss (PPOCritic)
        data["returns"] = np.asarray(returns)
        data["values"] = values
        an = cfg.adv_norm
        if an is not None and (an.mean_level != "none" or an.std_level != "none"):
            adv = _adv_normalize(adv, loss_mask, an, self.group_size)
        data["advantages"] = adv
        data["kl_rewards"] = kl_rewards
        data["tot_rewards"] = tok_rewards
        stats_tracker.scalar(
            task_reward=float(reward_score.mean()),
            kl_reward=float(kl_rewards.sum(1).mean()),
            advantage=float((adv * loss_mask).sum() / max(loss_mask.sum(), 1)),
        )
        self._record_staleness(data, loss_mask)
        return data

    def _record_staleness(self, data: Batch, loss_mask: np.ndarray):
        """Consumed-batch staleness histogram: how many weight versions
        behind the trainer each token being trained on was generated
        (the paper's η in practice — the distribution async rollout
        actually delivered, not just the configured bound). Exported via
        stats_tracker so StatsLogger.commit persists it per step."""
        if "versions" not in data:
            return
        versions = np.asarray(data["versions"])
        on = (loss_mask > 0) & (versions >= 0)
        if not on.any():
            return
        lag = self.engine.get_version() - versions[on]
        hist = {
            f"staleness/lag{b}_frac": float((lag == b).mean())
            for b in range(4)
        }
        hist["staleness/lag_ge4_frac"] = float((lag >= 4).mean())
        stats_tracker.scalar(
            **hist,
            **{
                "staleness/lag_mean": float(lag.mean()),
                "staleness/lag_max": float(lag.max()),
                "staleness/lag_min": float(lag.min()),
                "staleness/n_tokens": float(lag.size),
            },
        )

    # ------------------------------------------------------------------
    def ppo_update(self, data: Batch) -> List[Dict[str, float]]:
        """Minibatched decoupled-PPO update (reference ppo/actor.py:161)."""
        cfg = self.config
        if cfg.recompute_logprob and "prox_logp" not in data:
            # proximal policy = current weights before this update
            data["prox_logp"] = self.compute_logp(data) * np.asarray(
                data["loss_mask"], np.float32
            )
        if cfg.dynamic_sampling and self.group_size > 1:
            keep = np.asarray(
                F.dynamic_sampling_mask(
                    jnp.asarray(np.asarray(data["rewards"], np.float32)),
                    self.group_size,
                )
            )
            if keep.any() and not keep.all():
                data = batch_select(data, np.nonzero(keep)[0])
        bsz = batch_size(data)
        n_mbs = min(cfg.ppo_n_minibatches, max(bsz, 1))
        perm = np.random.permutation(bsz)
        groups = np.array_split(perm, n_mbs)
        all_stats = []
        for g in groups:
            if len(g) == 0:
                continue
            mb = batch_select(data, g)
            stats = self.engine.train_batch(
                mb, self._loss_fn, _ppo_loss_weight_fn
            )
            all_stats.append(stats)
        return all_stats

    @property
    def _loss_fn(self):
        if not hasattr(self, "_cached_loss_fn"):
            cfg = self.config

            def grpo_loss_fn(logits, arrays):
                """reference ppo/actor.py:292 `grpo_loss_fn`."""
                newlogp = target_aligned_logprobs(
                    logits, arrays, temperature=cfg.temperature
                )
                old_logp = arrays["t_logprobs"].astype(jnp.float32)
                prox_logp = (
                    arrays["t_prox_logp"].astype(jnp.float32)
                    if "t_prox_logp" in arrays
                    else None
                )
                if not cfg.use_decoupled_loss and prox_logp is not None:
                    # plain PPO against recomputed logp
                    old_logp, prox_logp = prox_logp, None
                loss_mask = arrays["t_loss_mask"] > 0
                loss, stats = F.ppo_actor_loss_fn(
                    logprobs=newlogp,
                    old_logprobs=old_logp,
                    advantages=arrays["t_advantages"].astype(jnp.float32),
                    eps_clip=cfg.eps_clip,
                    loss_mask=loss_mask,
                    c_clip=cfg.c_clip,
                    proximal_logprobs=prox_logp,
                    behav_imp_weight_cap=cfg.behav_imp_weight_cap,
                    eps_clip_higher=cfg.eps_clip_higher,
                )
                return loss, stats

            self._cached_loss_fn = grpo_loss_fn
        return self._cached_loss_fn


def _ppo_loss_weight_fn(arrays) -> jnp.ndarray:
    return jnp.maximum(
        (arrays["t_loss_mask"] > 0).astype(jnp.float32).sum(), 1.0
    )


_gae_jit = jax.jit(F.gae_padded, static_argnums=(3, 4))


def _adv_normalize(adv, loss_mask, an, group_size: int) -> np.ndarray:
    """Batch/group-level advantage whitening (reference ppo/actor.py:370
    `AdvNorm`)."""
    m = loss_mask.astype(np.float64)
    x = adv.astype(np.float64)

    def _mean(vals, msk, axis=None):
        return (vals * msk).sum(axis) / np.maximum(msk.sum(axis), 1.0)

    if an.mean_level == "batch":
        mean = _mean(x, m)
    elif an.mean_level == "group":
        g = group_size
        xm = _mean(
            x.reshape(-1, g, x.shape[1]), m.reshape(-1, g, x.shape[1]),
            axis=(1, 2),
        )[:, None, None]
        mean = np.broadcast_to(xm, (x.shape[0] // g, g, x.shape[1])).reshape(x.shape)
    else:
        mean = 0.0
    centered = x - mean
    if an.std_level == "batch":
        std = np.sqrt(_mean(centered**2, m)) + 1e-5
    elif an.std_level == "group":
        g = group_size
        sm = np.sqrt(
            _mean(
                (centered**2).reshape(-1, g, x.shape[1]),
                m.reshape(-1, g, x.shape[1]), axis=(1, 2),
            )
        )[:, None, None] + 1e-5
        std = np.broadcast_to(sm, (x.shape[0] // g, g, x.shape[1])).reshape(x.shape)
    else:
        std = 1.0
    return ((centered / std) * m).astype(np.float32)
