"""PPO critic: value-model training over any TrainEngine.

Role of reference PPOCriticInterface
(realhf/impl/model/interface/ppo_interface.py:984): a decoder trunk with a
scalar value head, trained on clipped value loss against GAE returns; its
values feed the actor's advantage estimation (PPOActor.compute_advantages
consumes ``data["values"]``). The engine side is the same SPMDTrainEngine
with ``config.is_critic=True`` (transformer value_head — models/
transformer.py), so every parallelism/微batching path is shared.
"""

from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from areal_tpu.api.cli_args import PPOCriticConfig
from areal_tpu.api.engine_api import TrainEngine
from areal_tpu.utils.data import Batch, batch_select, batch_size


def critic_value_hook(logits, arrays):
    """Engine forward post-hook: [R, T, 1] value logits → [R, T] values."""
    return logits[..., 0]


def critic_loss_fn_factory(eps: float):
    def critic_loss_fn(logits, arrays):
        """Clipped value loss (reference ppo_functional critic loss):
        max((v-R)^2, (clip(v, v_old±eps) - R)^2) over loss-masked tokens."""
        values = logits[..., 0].astype(jnp.float32)  # [R, T]
        returns = arrays["t_returns"].astype(jnp.float32)
        old_values = arrays["t_values"].astype(jnp.float32)
        mask = (arrays["t_loss_mask"] > 0).astype(jnp.float32)
        clipped = jnp.clip(values, old_values - eps, old_values + eps)
        l1 = (values - returns) ** 2
        l2 = (clipped - returns) ** 2
        loss_tok = jnp.maximum(l1, l2)
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = 0.5 * (loss_tok * mask).sum() / denom
        clip_frac = ((l2 > l1).astype(jnp.float32) * mask).sum() / denom
        return loss, {
            "value_loss": loss,
            "value_clip_frac": clip_frac,
            "value_mean": (values * mask).sum() / denom,
        }

    return critic_loss_fn


def _loss_weight_fn(arrays) -> jnp.ndarray:
    return jnp.maximum(
        (arrays["t_loss_mask"] > 0).astype(jnp.float32).sum(), 1.0
    )


class PPOCritic:
    """Value-model algorithm wrapper (mirrors PPOActor)."""

    def __init__(self, config: PPOCriticConfig, engine: TrainEngine):
        self.config = config
        self.engine = engine

    def compute_values(self, data: Batch) -> np.ndarray:
        """Per-position values [B, L] under current critic weights."""
        return self.engine.forward(data, post_hook=critic_value_hook)

    def critic_update(self, data: Batch) -> List[Dict[str, float]]:
        """Minibatched clipped-value update. ``data`` must carry
        ``returns`` (from the actor's GAE) and ``values`` (the old values
        used for that GAE)."""
        cfg = self.config
        if not hasattr(self, "_loss_fn"):
            self._loss_fn = critic_loss_fn_factory(cfg.value_eps_clip)
        bsz = batch_size(data)
        n_mbs = min(cfg.ppo_n_minibatches, max(bsz, 1))
        perm = np.random.permutation(bsz)
        groups = np.array_split(perm, n_mbs)
        out = []
        for g in groups:
            if len(g) == 0:
                continue
            out.append(
                self.engine.train_batch(
                    batch_select(data, g), self._loss_fn, _loss_weight_fn
                )
            )
        return out
