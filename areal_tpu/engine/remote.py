"""Remote inference client: InferenceEngine over HTTP generation servers.

Role of reference areal/engine/sglang_remote.py (`RemoteSGLangEngine`):
- server discovery (env ``AREAL_LLM_SERVER_ADDRS`` or name_resolve subtree)
  with health checks;
- round-robin server choice with rid-affinity (a resumed/interrupted request
  returns to the server holding its KV: sglang_remote.py:114-168);
- the **interruptible generation loop** — on ``abort`` (weight-update
  window) re-issue ``/generate`` with accumulated output tokens appended to
  the prompt, so long generations span weight versions
  (sglang_remote.py:186-234);
- non-blocking disk weight updates: pause all servers → wait for the
  trainer's name_resolve signal → reload → continue (sglang_remote.py:
  251-309, 368-409);
- rollout orchestration delegated to WorkflowExecutor.
"""

import asyncio
import concurrent.futures
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

import aiohttp


def _abandon_session(s: "aiohttp.ClientSession") -> None:
    """Close a session whose owning loop is gone: ``detach`` marks the
    session closed (no "Unclosed client session" __del__ noise), then the
    connector's sockets are torn down. The synchronous teardown is
    aiohttp-private (``BaseConnector._close`` — present in the pinned
    aiohttp 3.x line, where the public ``close()`` is a coroutine needing
    the dead loop); if a future aiohttp drops it, fall back to driving the
    public ``close()`` on a throwaway loop. Failures are logged, not
    swallowed."""
    try:
        conn = s.connector
        if not s.closed:
            s.detach()
        if conn is None:
            return
        if hasattr(conn, "_close"):
            conn._close()
        else:
            result = conn.close()
            if asyncio.iscoroutine(result):
                loop = asyncio.new_event_loop()
                try:
                    loop.run_until_complete(result)
                finally:
                    loop.close()
    except Exception as e:  # noqa: BLE001
        logging.getLogger("areal_tpu.remote").warning(
            "could not tear down abandoned http session: %s", e
        )
import requests as _requests

from areal_tpu.api.cli_args import InferenceEngineConfig
from areal_tpu.api.engine_api import InferenceEngine
from areal_tpu.api.io_struct import (
    ModelRequest,
    ModelResponse,
    WeightUpdateMeta,
    WeightUpdateMethod,
)
from areal_tpu.api.workflow_api import RolloutWorkflow, WorkflowExecutor
from areal_tpu.utils import logging as logging_util, name_resolve, names
from areal_tpu.utils import stats_tracker
from areal_tpu.utils.http import arequest_with_retry
from areal_tpu.utils.tracing import SpanTracer

logger = logging_util.getLogger("RemoteInferenceEngine")

SERVER_ADDRS_ENV = "AREAL_LLM_SERVER_ADDRS"


class RemoteInferenceEngine(InferenceEngine):
    def __init__(self, config: InferenceEngineConfig):
        self.config = config
        self.addresses: List[str] = []
        self._server_idx = 0
        self._rid_to_address: Dict[str, str] = {}
        self._version = 0
        self._lock = threading.Lock()
        self.executor = concurrent.futures.ThreadPoolExecutor(max_workers=2)
        self.workflow_executor: Optional[WorkflowExecutor] = None
        # client-side request lifecycle spans (submit → first-token →
        # complete; weight-update pause windows) — no-op unless
        # config.tracing.enabled
        self.tracer = SpanTracer(getattr(config, "tracing", None))
        # one session PER event loop: a session is bound to its creating
        # loop, and this engine is legitimately driven from several (the
        # WorkflowExecutor's background loop + per-sweep asyncio.run loops
        # in evaluation/run_eval) — a single slot would make concurrent
        # loops thrash/close each other's in-flight sockets
        self._sessions: Dict[int, tuple] = {}  # id(loop) -> (loop, session)

    # ------------------------------------------------------------------
    def initialize(self, addrs: Optional[List[str]] = None):
        if addrs is None:
            env = os.environ.get(SERVER_ADDRS_ENV, "")
            if env:
                addrs = [a.strip() for a in env.split(",") if a.strip()]
        if not addrs:
            key = names.gen_servers(
                self.config.experiment_name, self.config.trial_name
            )
            deadline = time.monotonic() + self.config.setup_timeout
            while time.monotonic() < deadline:
                addrs = name_resolve.get_subtree(key)
                if addrs:
                    break
                time.sleep(0.5)
        if not addrs:
            raise RuntimeError("no generation servers found")
        self.addresses = list(addrs)
        self._health_check_all()
        self.workflow_executor = WorkflowExecutor(self.config, self)
        self.workflow_executor.initialize()
        return self

    def destroy(self):
        if self.workflow_executor is not None:
            self.workflow_executor.destroy()
        self.executor.shutdown(wait=False)
        self.tracer.flush()  # drain to TracingConfig.export_path if set
        for _, (lp, s) in list(self._sessions.items()):
            if s.closed:
                continue
            if not lp.is_closed():
                try:  # close on the owning loop when it still runs
                    fut = asyncio.run_coroutine_threadsafe(s.close(), lp)
                    fut.result(timeout=5)
                    continue
                except Exception:
                    pass
            _abandon_session(s)
        self._sessions.clear()

    def _health_check_all(self):
        deadline = time.monotonic() + self.config.setup_timeout
        pending = set(self.addresses)
        while pending and time.monotonic() < deadline:
            for addr in list(pending):
                try:
                    r = _requests.get(f"http://{addr}/health", timeout=5)
                    if r.status_code == 200:
                        pending.discard(addr)
                except _requests.RequestException:
                    pass
            if pending:
                time.sleep(0.5)
        if pending:
            raise RuntimeError(f"servers failed health check: {sorted(pending)}")
        logger.info(f"{len(self.addresses)} generation server(s) healthy")

    # ------------------------------------------------------------------
    def get_version(self) -> int:
        with self._lock:
            return self._version

    def set_version(self, version: int):
        with self._lock:
            self._version = version

    # ------------------------------------------------------------------
    def choose_server(self, rid: Optional[str] = None) -> str:
        """rid-affinity first (KV locality on resume), else scheduling
        policy (reference sglang_remote.py:158-168)."""
        with self._lock:
            if rid is not None and rid in self._rid_to_address:
                return self._rid_to_address[rid]
            if self.config.schedule_policy == "least_requests":
                addr = min(
                    self.addresses,
                    key=lambda a: sum(
                        1 for v in self._rid_to_address.values() if v == a
                    ),
                )
            else:  # round_robin
                addr = self.addresses[self._server_idx % len(self.addresses)]
                self._server_idx += 1
            if rid is not None:
                self._rid_to_address[rid] = addr
                if len(self._rid_to_address) > 16384:
                    self._rid_to_address.pop(
                        next(iter(self._rid_to_address))
                    )
            return addr

    async def _get_session(self) -> aiohttp.ClientSession:
        loop = asyncio.get_running_loop()
        # reap sessions whose owning loop is gone (each asyncio.run sweep
        # leaves one behind) so the map stays bounded by LIVE loops
        for key, (lp, s) in list(self._sessions.items()):
            if lp is not loop and lp.is_closed():
                self._sessions.pop(key)
                _abandon_session(s)
        ent = self._sessions.get(id(loop))
        if ent is None or ent[1].closed:
            s = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0)
            )
            self._sessions[id(loop)] = (loop, s)
            return s
        return ent[1]

    async def agenerate(self, req: ModelRequest) -> ModelResponse:
        """Interruptible generation loop (reference sglang_remote.py:121-249)."""
        gconfig = req.gconfig
        assert gconfig.n_samples == 1, (
            "agenerate expects n_samples=1; workflows fan out samples"
        )
        session = await self._get_session()
        start = time.monotonic()
        accumulated: List[int] = []
        logprobs: List[float] = []
        versions: List[int] = []
        stop_reason = None
        ttft = None
        n_calls = 0
        n_aborts = 0
        chunk = self.config.new_tokens_per_chunk or 0
        while stop_reason not in ("stop", "length") and len(accumulated) < gconfig.max_new_tokens:
            server = self.choose_server(req.rid)
            remaining = gconfig.max_new_tokens - len(accumulated)
            ask = min(remaining, chunk) if chunk > 0 else remaining
            payload = {
                "rid": req.rid,
                "input_ids": list(req.input_ids) + accumulated,
                "sampling_params": {
                    "max_new_tokens": ask,
                },
            }
            if req.image_data:
                payload["image_data"] = list(req.image_data)
            if req.mm is not None:
                # JSON-safe multimodal payload. The big float32 patch
                # tensor goes as ONE base64 blob (nested JSON lists would
                # be ~8x the bytes and dominate request parsing); the
                # small int meta arrays stay as lists.
                import base64 as _b64
                import numpy as _np

                mm_json = {}
                for k, v in req.mm.items():
                    if k == "pixel_values":
                        arr = _np.asarray(v, _np.float32)
                        mm_json["pixel_values_b64"] = _b64.b64encode(
                            arr.tobytes()
                        ).decode()
                        mm_json["pixel_values_shape"] = list(arr.shape)
                    else:
                        mm_json[k] = (
                            v.tolist() if hasattr(v, "tolist") else v
                        )
                payload["mm"] = mm_json
            payload["sampling_params"].update(
                {
                    "min_new_tokens": max(
                        0, gconfig.min_new_tokens - len(accumulated)
                    ),
                    "temperature": gconfig.temperature,
                    "top_p": gconfig.top_p,
                    "top_k": gconfig.top_k,
                    "greedy": gconfig.greedy,
                    "stop_token_ids": gconfig.stop_token_ids,
                }
            )
            t_call = time.monotonic()
            result = await arequest_with_retry(
                session,
                f"http://{server}/generate",
                payload,
                max_retries=self.config.request_retries,
                timeout=self.config.request_timeout,
            )
            n_calls += 1
            if self.tracer.enabled:
                self.tracer.record(
                    "generate_call", req.rid, t_call, time.monotonic(),
                    server=server, new_tokens=len(result["output_ids"]),
                )
            if ttft is None and result["output_ids"]:
                ttft = time.monotonic() - start
            accumulated.extend(result["output_ids"])
            logprobs.extend(result["output_logprobs"])
            versions.extend(result["output_versions"])
            stop_reason = result["meta_info"]["finish_reason"]["type"]
            if (
                stop_reason == "length"
                and ask < remaining
                and len(result["output_ids"]) >= ask
            ):
                # chunk boundary, not a genuine stop: the server delivered
                # everything this chunk asked for — resume from here
                # (reference partial_rollout.py:181-250 refresh cycle)
                stop_reason = None
            if stop_reason == "abort":
                # server is in a weight-update window; brief backoff then
                # resume with accumulated tokens
                n_aborts += 1
                await asyncio.sleep(self.config.pause_grace_period or 0.1)
        with self._lock:
            self._rid_to_address.pop(req.rid, None)
        now = time.monotonic()
        if self.tracer.enabled:
            if ttft is not None:
                self.tracer.record(
                    "submit_to_first_token", req.rid, start, start + ttft,
                )
            self.tracer.record(
                "rollout_request", req.rid, start, now,
                output_tokens=len(accumulated),
                stop_reason=stop_reason or "length",
                n_calls=n_calls, n_aborts=n_aborts,
            )
        # generation-time staleness: how far each produced token already
        # lags the trainer at COMPLETION time (the consumed-batch lag is
        # measured again at train time, ppo/actor.compute_advantages)
        if versions:
            trainer_v = self.get_version()
            lags = [trainer_v - v for v in versions]
            stats_tracker.scalar(**{
                "rollout/staleness_lag_mean": sum(lags) / len(lags),
                "rollout/staleness_lag_max": float(max(lags)),
                "rollout/ttft_s": ttft if ttft is not None else now - start,
                "rollout/latency_s": now - start,
                "rollout/output_tokens": float(len(accumulated)),
                "rollout/aborts_per_request": float(n_aborts),
            })
        return ModelResponse(
            input_tokens=list(req.input_ids),
            output_tokens=accumulated,
            output_logprobs=logprobs,
            output_versions=versions,
            stop_reason=stop_reason or "length",
            latency=time.monotonic() - start,
            ttft=ttft or (time.monotonic() - start),
        )

    # ------------------------------------------------------------------
    # Weight updates (disk path)
    # ------------------------------------------------------------------
    def update_weights(self, meta: WeightUpdateMeta) -> concurrent.futures.Future:
        """Non-blocking: pause servers, wait for fresh weights to land
        (disk signal or device-path transfer), resume (reference
        sglang_remote.py:251-309). The whole sequence — including the
        pause posts — runs off-thread so one slow server never stalls the
        train loop."""

        def _pause_all():
            for addr in self.addresses:
                r = _requests.post(
                    f"http://{addr}/pause_generation", timeout=30
                )
                r.raise_for_status()

        # Pause SYNCHRONOUSLY before returning (reference pauses inline,
        # sglang_remote.py:252-254): callers overlap `update_weights(...)`
        # with `engine.upload_weights(meta)`, and streaming chunks into a
        # not-yet-paused server would swap weights mid-decode (round-2
        # advisor finding).
        t_pause = time.monotonic()
        _pause_all()

        def _record_pause_window():
            # the full pause→transfer→resume window: rollout capacity the
            # fleet lost to this weight update
            dur = time.monotonic() - t_pause
            self.tracer.record(
                "weight_update_pause", "__controller__", t_pause,
                t_pause + dur, model_version=meta.model_version,
            )
            stats_tracker.scalar(**{"rollout/pause_window_s": dur})

        if meta.type == WeightUpdateMethod.DEVICE:

            def _do_device_update():
                try:
                    # the trainer streams chunks directly to the servers
                    # (spmd_engine.upload_weights); wait on the SAME set of
                    # addresses it streams to (meta.addrs when given), or
                    # unstreamed servers would be polled forever
                    targets = list(meta.addrs) or self.addresses
                    # dedicated (shorter) bound: a failed upload must not
                    # hold every server paused for the full request
                    # timeout (3600s default)
                    deadline = time.monotonic() + min(
                        self.config.request_timeout,
                        getattr(self.config, "weight_update_timeout", 300.0),
                    )
                    for addr in targets:
                        while True:
                            r = _requests.get(
                                f"http://{addr}/get_model_info", timeout=30
                            )
                            r.raise_for_status()
                            if (
                                int(r.json().get("model_version", -1))
                                >= meta.model_version
                            ):
                                break
                            if time.monotonic() > deadline:
                                raise TimeoutError(
                                    f"{addr} never reached weight version "
                                    f"{meta.model_version}"
                                )
                            time.sleep(0.2)
                    self.set_version(meta.model_version)
                finally:
                    self._resume_all_best_effort()
                    _record_pause_window()

            return self.executor.submit(_do_device_update)

        def _do_update():
            try:
                # the trainer signals checkpoint readiness via name_resolve
                # (reference fsdp_engine.py:384-395); flows that save before
                # calling us are detected by the checkpoint on disk
                key = names.update_weights_from_disk(
                    self.config.experiment_name,
                    self.config.trial_name,
                    meta.model_version,
                )
                deadline = time.monotonic() + self.config.request_timeout
                while True:
                    if os.path.exists(os.path.join(meta.path, "config.json")):
                        break
                    try:
                        name_resolve.get(key)
                        break
                    except Exception:
                        pass
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"weight checkpoint never appeared at {meta.path}"
                        )
                    time.sleep(0.2)
                for addr in self.addresses:
                    r = _requests.post(
                        f"http://{addr}/update_weights_from_disk",
                        json={
                            "model_path": meta.path,
                            "version": meta.model_version,
                        },
                        timeout=600,
                    )
                    r.raise_for_status()
                    assert r.json().get("success"), r.json()
                self.set_version(meta.model_version)
            finally:
                self._resume_all_best_effort()
                _record_pause_window()

        return self.executor.submit(_do_update)

    def _resume_all_best_effort(self):
        """continue_generation on every server; one dead server must not
        leave the rest paused (or mask the original exception)."""
        for addr in self.addresses:
            try:
                _requests.post(
                    f"http://{addr}/continue_generation", timeout=30
                )
            except Exception as e:
                logger.warning(f"continue_generation to {addr} failed: {e}")

    # ------------------------------------------------------------------
    # Rollout orchestration (delegated; reference sglang_remote.py:311-365)
    # ------------------------------------------------------------------
    def submit(self, data: Dict[str, Any], workflow: RolloutWorkflow) -> None:
        self.workflow_executor.submit(data, workflow)

    def wait(self, count: int, timeout: Optional[float] = None,
             group_filter=None):
        return self.workflow_executor.wait(
            count, timeout=timeout, group_filter=group_filter
        )

    def rollout_batch(self, data: List[Dict[str, Any]], workflow,
                      group_filter=None):
        return self.workflow_executor.rollout_batch(
            data, workflow, group_filter=group_filter
        )

    def prepare_batch(self, dataloader, workflow, group_filter=None):
        return self.workflow_executor.prepare_batch(
            dataloader, workflow, group_filter=group_filter
        )

    def pause(self):
        self.workflow_executor.pause()

    def resume(self):
        self.workflow_executor.resume()
