"""Remote inference client: InferenceEngine over HTTP generation servers.

Role of reference areal/engine/sglang_remote.py (`RemoteSGLangEngine`):
- server discovery (env ``AREAL_LLM_SERVER_ADDRS`` or name_resolve subtree)
  with health checks;
- round-robin server choice with rid-affinity (a resumed/interrupted request
  returns to the server holding its KV: sglang_remote.py:114-168);
- the **interruptible generation loop** — on ``abort`` (weight-update
  window) re-issue ``/generate`` with accumulated output tokens appended to
  the prompt, so long generations span weight versions
  (sglang_remote.py:186-234);
- **zero-pause weight updates** (the r13 default,
  ``config.streamed_weight_updates``): fresh weights stream at LIVE
  servers — each server stages them into a shadow buffer
  (inference/weights.WeightStore) and flips atomically at a dispatch
  boundary, so no ``/pause_generation`` is ever posted and no
  ``weight_update_pause`` window is recorded (a ``weight_stream`` span
  covers the transfer instead). In-flight sequences finish pinned to
  the old version or resume suffix-exact on the new one; per-token
  ``output_versions`` keep the staleness fence exact either way. With
  ``streamed_weight_updates=False`` the legacy r2 protocol applies:
  pause all servers → wait for the trainer's signal or stream → resume
  (reference sglang_remote.py:251-309, 368-409);
- rollout orchestration delegated to WorkflowExecutor.
"""

import asyncio
import concurrent.futures
import logging
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import aiohttp
import requests as _requests

from areal_tpu.api.cli_args import InferenceEngineConfig
from areal_tpu.api.engine_api import InferenceEngine
from areal_tpu.api.io_struct import (
    ModelRequest,
    ModelResponse,
    WeightUpdateMeta,
    WeightUpdateMethod,
)
from areal_tpu.api.workflow_api import RolloutWorkflow, WorkflowExecutor
from areal_tpu.inference.fleet import FleetMonitor
from areal_tpu.utils import goodput
from areal_tpu.utils import logging as logging_util, name_resolve, names
from areal_tpu.utils import stats_tracker, telemetry
from areal_tpu.utils.http import HttpRequestError, arequest_with_retry
from areal_tpu.utils.tracing import (
    SpanTracer,
    new_trace_id,
    trace_headers,
)


def _abandon_session(s: "aiohttp.ClientSession") -> None:
    """Close a session whose owning loop is gone: ``detach`` marks the
    session closed (no "Unclosed client session" __del__ noise), then the
    connector's sockets are torn down. The synchronous teardown is
    aiohttp-private (``BaseConnector._close`` — present in the pinned
    aiohttp 3.x line, where the public ``close()`` is a coroutine needing
    the dead loop); if a future aiohttp drops it, fall back to driving the
    public ``close()`` on a throwaway loop. Failures are logged, not
    swallowed."""
    try:
        conn = s.connector
        if not s.closed:
            s.detach()
        if conn is None:
            return
        if hasattr(conn, "_close"):
            conn._close()
        else:
            result = conn.close()
            if asyncio.iscoroutine(result):
                loop = asyncio.new_event_loop()
                try:
                    loop.run_until_complete(result)
                finally:
                    loop.close()
    except Exception as e:  # noqa: BLE001
        logging.getLogger("areal_tpu.remote").warning(
            "could not tear down abandoned http session: %s", e
        )


logger = logging_util.getLogger("RemoteInferenceEngine")

SERVER_ADDRS_ENV = "AREAL_LLM_SERVER_ADDRS"


class NoHealthyServersError(RuntimeError):
    pass


class RemoteInferenceEngine(InferenceEngine):
    def __init__(self, config: InferenceEngineConfig):
        self.config = config
        self.addresses: List[str] = []
        self._server_idx = 0
        # rid → server affinity, LRU-bounded: eviction must drop the
        # LEAST-recently-touched rid, not the oldest insertion — a hot
        # resumed rid keeps its KV locality (mirrors the router's
        # bounded qid cache)
        self._rid_to_address: "OrderedDict[str, str]" = OrderedDict()
        # qid → server affinity (group/session key): GRPO siblings and a
        # multi-turn episode's successive turns land on the server whose
        # radix cache holds their shared prefix — without this every
        # request scatters round-robin and cross-request KV reuse is
        # structurally impossible
        self._qid_to_address: "OrderedDict[str, str]" = OrderedDict()
        self._version = 0
        # last scheduling version the fronting router reported (when
        # config.router_addr is set): the stickiness key its
        # previous_server fast path checks against
        self._router_version = -1
        # rid → previous-owner address from the router's kv_ship_from
        # hint (r16): consumed by the NEXT /generate payload for that
        # rid so the fresh server prefix-fetches before admission
        self._ship_hints: Dict[str, str] = {}
        self._lock = threading.Lock()
        self.executor = concurrent.futures.ThreadPoolExecutor(max_workers=2)
        self.workflow_executor: Optional[WorkflowExecutor] = None
        # fleet resilience plane (built in initialize once addresses are
        # known): health state machine + circuit breaker + membership
        self.fleet: Optional[FleetMonitor] = None
        # fleet telemetry hub (utils/telemetry.TelemetryCollector):
        # started in initialize when config.telemetry.enabled
        self.telemetry = None
        self._discovered = False  # addrs came from name_resolve (not env/
        # explicit) — only then may the membership watch shrink the fleet
        # last successful disk-path weight push (path, version): the
        # catch-up source for servers that missed updates while DEAD
        self._last_disk_update: Optional[tuple] = None
        # client-side request lifecycle spans (submit → first-token →
        # complete; weight-update pause windows) — no-op unless
        # config.tracing.enabled
        self.tracer = SpanTracer(
            getattr(config, "tracing", None), service="client"
        )
        # one session PER event loop: a session is bound to its creating
        # loop, and this engine is legitimately driven from several (the
        # WorkflowExecutor's background loop + per-sweep asyncio.run loops
        # in evaluation/run_eval) — a single slot would make concurrent
        # loops thrash/close each other's in-flight sockets
        self._sessions: Dict[int, tuple] = {}  # id(loop) -> (loop, session)

    # ------------------------------------------------------------------
    def initialize(self, addrs: Optional[List[str]] = None):
        if addrs is None:
            env = os.environ.get(SERVER_ADDRS_ENV, "")
            if env:
                addrs = [a.strip() for a in env.split(",") if a.strip()]
        if not addrs:
            key = names.gen_servers(
                self.config.experiment_name, self.config.trial_name
            )
            deadline = time.monotonic() + self.config.setup_timeout
            while time.monotonic() < deadline:
                addrs = name_resolve.get_subtree(key)
                if addrs:
                    break
                time.sleep(0.5)
            self._discovered = bool(addrs)
        if not addrs:
            raise RuntimeError("no generation servers found")
        self.addresses = list(addrs)
        unhealthy = self._health_check_all()
        fleet_cfg = getattr(self.config, "fleet", None)
        membership_key = None
        if (
            self._discovered
            and fleet_cfg is not None
            and fleet_cfg.watch_membership
            and self.config.experiment_name
        ):
            membership_key = names.gen_servers(
                self.config.experiment_name, self.config.trial_name
            )
        self.fleet = FleetMonitor(
            self.addresses,
            fleet_cfg,
            membership_key=membership_key,
            on_join=self._on_server_join,
            on_leave=self._on_server_leave,
            on_dead=self._on_server_dead,
            on_recover=self._on_server_recovered,
            seed_source="discovered" if membership_key else "seed",
        )
        # servers that failed the startup sweep open their circuit NOW
        # (no traffic) instead of eating live requests' first retries
        dead_after = fleet_cfg.dead_threshold if fleet_cfg else 3
        for addr in unhealthy:
            for _ in range(max(1, dead_after)):
                self.fleet.report_failure(addr)
        if fleet_cfg is None or fleet_cfg.enabled:
            self.fleet.start()
        self.workflow_executor = WorkflowExecutor(self.config, self)
        self.workflow_executor.initialize()
        tel_cfg = getattr(self.config, "telemetry", None)
        if tel_cfg is not None and tel_cfg.enabled:
            # the hub rides the SAME membership the resilience plane
            # watches, and reads staleness from the executor's ledger
            self.telemetry = telemetry.TelemetryCollector(
                addresses=list(self.addresses),
                fleet=self.fleet,
                config=tel_cfg,
                ledger=self.workflow_executor.lineage,
            ).start()
            self.telemetry.serve()
        return self

    # -- fleet callbacks (fleet lock NOT held here) --------------------
    def _on_server_join(self, addr: str):
        with self._lock:
            if addr not in self.addresses:
                self.addresses.append(addr)

    def _on_server_leave(self, addr: str):
        with self._lock:
            if addr in self.addresses:
                self.addresses.remove(addr)
            self._evict_affinity_locked(addr)

    def _on_server_dead(self, addr: str):
        # dead-server affinity eviction: in-flight requests stuck to it
        # must re-resolve on their next chunk instead of re-POSTing a
        # dead address
        with self._lock:
            evicted = self._evict_affinity_locked(addr)
        if evicted:
            logger.warning(
                f"server {addr} marked DEAD; evicted {evicted} sticky "
                f"request(s)"
            )

    def _quarantine(self, addr: str):
        """Force a server's circuit OPEN (straight to DEAD). Used when a
        server missed a weight update or failed a re-sync: merely
        marking it SUSPECT would leave it schedulable at stale weights,
        and SUSPECT→HEALTHY deliberately skips the version check — DEAD
        routes its re-admission through the on_recover re-sync."""
        if self.fleet is None:
            return
        fleet_cfg = getattr(self.config, "fleet", None)
        dead_after = fleet_cfg.dead_threshold if fleet_cfg else 3
        for _ in range(max(1, dead_after)):
            self.fleet.report_failure(addr)

    def _on_server_recovered(self, addr: str):
        """Fleet callback: a server re-entered rotation. The actual
        re-sync does blocking HTTP (up to the disk-update timeout), and
        this callback can fire from report_success INSIDE the asyncio
        event loop — so the work is dispatched to the engine's worker
        pool, never run inline. Until it completes the server may
        briefly take traffic at a stale version; _resync quarantines it
        the moment staleness is confirmed."""
        try:
            self.executor.submit(self._resync_recovered_server, addr)
        except RuntimeError:  # executor already shut down (teardown)
            pass

    def _resync_recovered_server(self, addr: str):
        """A server re-closed its circuit (RECOVERING → HEALTHY). It may
        have missed weight updates while DEAD: verify the version it
        serves; re-push the last disk checkpoint when it is behind, or —
        when there is nothing to re-push (device-path transfers are
        trainer-driven) — tell it to drain, because silently serving
        stale tokens would poison the staleness accounting."""
        try:
            current = self.get_version()
            if current <= 0:
                return
            r = _requests.get(
                f"http://{addr}/get_model_info", timeout=30
            )
            r.raise_for_status()
            served = int(r.json().get("model_version", -1))
            if served >= current:
                return
            last = self._last_disk_update
            if last is not None and last[1] >= current:
                path, version = last
                r = _requests.post(
                    f"http://{addr}/update_weights_from_disk",
                    json={"model_path": path, "version": version},
                    timeout=600,
                )
                r.raise_for_status()
                body = r.json()
                if not body.get("success"):
                    raise RuntimeError(f"re-sync push rejected: {body}")
                logger.info(
                    f"re-synced recovered server {addr}: "
                    f"v{served} -> v{version}"
                )
                return
            logger.error(
                f"recovered server {addr} serves stale weights "
                f"(v{served} < v{current}) and no disk checkpoint is "
                f"available to re-push; draining it out of rotation"
            )
            try:
                _requests.post(f"http://{addr}/drain", timeout=30)
            finally:
                if self.fleet is not None:
                    self.fleet.drain(addr)
        except Exception as e:
            # an unverifiable server must NOT linger schedulable at an
            # unknown version — back to DEAD, retried via half-open
            logger.error(f"recover re-sync for {addr} failed: {e}")
            self._quarantine(addr)

    def _evict_affinity_locked(self, addr: str) -> int:
        stale = [r for r, a in self._rid_to_address.items() if a == addr]
        for r in stale:
            del self._rid_to_address[r]
        for q in [
            q for q, a in self._qid_to_address.items() if a == addr
        ]:
            del self._qid_to_address[q]
        return len(stale)

    def destroy(self):
        if self.workflow_executor is not None:
            self.workflow_executor.destroy()
        if self.telemetry is not None:
            self.telemetry.stop()
        if self.fleet is not None:
            self.fleet.stop()
        self.executor.shutdown(wait=False)
        self.tracer.flush()  # drain to TracingConfig.export_path if set
        for _, (lp, s) in list(self._sessions.items()):
            if s.closed:
                continue
            if not lp.is_closed():
                try:  # close on the owning loop when it still runs
                    fut = asyncio.run_coroutine_threadsafe(s.close(), lp)
                    fut.result(timeout=5)
                    continue
                except Exception:
                    pass
            _abandon_session(s)
        self._sessions.clear()

    def _health_check_all(self) -> List[str]:
        """Startup health sweep. Requires at least ONE healthy server;
        the unhealthy remainder is returned (not fatal — the fleet
        monitor starts them DEAD and half-open probes re-admit them),
        because a single crashed-after-registering server must not abort
        a trainer fronting an otherwise-healthy fleet."""
        deadline = time.monotonic() + self.config.setup_timeout
        pending = set(self.addresses)
        while pending and time.monotonic() < deadline:
            for addr in list(pending):
                try:
                    r = _requests.get(f"http://{addr}/health", timeout=5)
                    if r.status_code == 200:
                        pending.discard(addr)
                except _requests.RequestException:
                    pass
            if pending:
                time.sleep(0.5)
        if len(pending) == len(self.addresses):
            raise RuntimeError(
                f"servers failed health check: {sorted(pending)}"
            )
        if pending:
            logger.warning(
                f"{len(pending)} server(s) failed the startup health "
                f"check; starting on the healthy "
                f"{len(self.addresses) - len(pending)} and leaving "
                f"{sorted(pending)} to the fleet monitor"
            )
        else:
            logger.info(
                f"{len(self.addresses)} generation server(s) healthy"
            )
        return sorted(pending)

    # ------------------------------------------------------------------
    def get_version(self) -> int:
        with self._lock:
            return self._version

    def set_version(self, version: int):
        with self._lock:
            if version != self._version:
                # fresh weights flushed every server's prefix cache —
                # group affinity to the old cached prefixes is moot (and
                # a stale map would pin whole groups to one cold server)
                self._qid_to_address.clear()
            self._version = version

    # ------------------------------------------------------------------
    def choose_server(
        self, rid: Optional[str] = None, exclude: Optional[set] = None,
        qid: Optional[str] = None,
    ) -> str:
        """rid-affinity first (KV locality on resume), then qid-affinity
        (the group/session key — GRPO siblings and multi-turn turns
        steer to the server holding their shared radix prefix), else
        scheduling policy (reference sglang_remote.py:158-168) — over
        the HEALTHY fleet only. ``exclude`` is the per-request failover
        set: servers this request already failed on. An affinity entry
        pointing at an excluded/unhealthy server is evicted, not
        honored."""
        with self._lock:
            fleet = self.fleet

            def usable(a: str) -> bool:
                if exclude and a in exclude:
                    return False
                return fleet is None or fleet.is_schedulable(a)

            def usable_continuation(a: str) -> bool:
                # rid affinity = an in-flight request's next chunk: a
                # WARMING server still serves it (it holds the KV; r11
                # warming only gates NEW work)
                if exclude and a in exclude:
                    return False
                if fleet is None:
                    return True
                cont = getattr(fleet, "is_continuation_target", None)
                return (
                    cont(a) if cont is not None
                    else fleet.is_schedulable(a)
                )

            if rid is not None and rid in self._rid_to_address:
                addr = self._rid_to_address[rid]
                if usable_continuation(addr):
                    # LRU touch: a hot resumed rid must not be the next
                    # eviction victim just because it was inserted early
                    self._rid_to_address.move_to_end(rid)
                    return addr
                del self._rid_to_address[rid]
            if qid and qid in self._qid_to_address:
                addr = self._qid_to_address[qid]
                if usable(addr):
                    self._qid_to_address.move_to_end(qid)
                    if rid is not None:
                        self._rid_to_address[rid] = addr
                        self._rid_to_address.move_to_end(rid)
                    return addr
                del self._qid_to_address[qid]
            candidates = [a for a in self.addresses if usable(a)]
            if not candidates:
                # fail open on health (a stale SUSPECT/DEAD verdict must
                # not strand requests when it is ALL we have), but never
                # on the per-request exclusions — those servers already
                # ate this request once
                candidates = [
                    a for a in self.addresses
                    if not exclude or a not in exclude
                ]
            if not candidates:
                raise NoHealthyServersError(
                    f"no generation server available (fleet={len(self.addresses)}, "
                    f"excluded={sorted(exclude) if exclude else []})"
                )
            if self.config.schedule_policy == "least_requests":
                addr = min(
                    candidates,
                    key=lambda a: sum(
                        1 for v in self._rid_to_address.values() if v == a
                    ),
                )
            else:  # round_robin
                addr = candidates[self._server_idx % len(candidates)]
                self._server_idx += 1
            if rid is not None:
                self._rid_to_address[rid] = addr
                self._rid_to_address.move_to_end(rid)
                while len(self._rid_to_address) > 16384:
                    # evict least-recently-USED, not first-inserted
                    self._rid_to_address.popitem(last=False)
            if qid:
                self._qid_to_address[qid] = addr
                self._qid_to_address.move_to_end(qid)
                while len(self._qid_to_address) > 16384:
                    self._qid_to_address.popitem(last=False)
            return addr

    async def _schedule_via_router(
        self, session, req: ModelRequest, failed: set, headers,
        qid: Optional[str] = None,
        priority: str = "bulk", tenant: str = "", resumed: bool = False,
        policy: str = "",
    ) -> Optional[str]:
        """Router-scheduled mode (config.router_addr): ask the fronting
        router for a server, forwarding the trace context so the
        router's `route` span lands on the same stitched timeline.
        Returns None (→ local choose_server fallback) when no router is
        configured, the router is unreachable, or it answered with a
        server this request already failed on. A router SHED (429) is
        NOT a fallback case — re-raised, because routing around
        admission control would defeat it; the 429's Retry-After was
        already honored by the retry loop, so what escapes here is
        sustained backpressure that belongs to the episode-retry
        budget."""
        router = getattr(self.config, "router_addr", "")
        if not router:
            return None
        with self._lock:
            prev = self._rid_to_address.get(req.rid)
            prev_version = self._router_version
        # group/session key: workflows stamp metadata["qid"] (GRPO group
        # id / episode id) and agenerate falls back to the episode's
        # lineage uid — only a standalone call degrades to the rid,
        # which scatters siblings and forfeits cross-request KV reuse
        meta = {
            "rid": req.rid,
            "qid": str(qid or req.rid),
            "prompt_len": len(req.input_ids),
            "new_token_budget": req.gconfig.max_new_tokens,
            "exclude": sorted(failed),
            "priority": priority,
            "tenant": tenant,
            # continuations must pass the router's admission gates: a
            # shed here would strand the accumulated suffix
            "resumed": resumed,
        }
        if req.metadata.get("group_size"):
            meta["group_size"] = int(req.metadata["group_size"])
        if policy:
            # named policy handle (r19): the router keys its qid
            # affinity per policy line and may resolve a bare name to
            # an exact version through its canary splitter
            meta["policy"] = policy
        if prev is not None and prev not in failed:
            meta["previous_server"] = prev
            meta["previous_version"] = prev_version
        try:
            out = await arequest_with_retry(
                session,
                f"http://{router}/schedule_request",
                meta,
                max_retries=max(3, self.config.request_retries),
                timeout=30.0,
                headers=headers,
            )
        except HttpRequestError as e:
            if e.status == 429:
                stats_tracker.scalar(**{"rollout/requests_shed": 1.0})
                if self.tracer.enabled:
                    self.tracer.instant(
                        "shed", req.rid, sched_class=priority,
                        tenant=tenant, source="router",
                    )
                raise
            logger.warning(
                f"router schedule for {req.rid} failed ({e}); "
                f"falling back to the client-local policy"
            )
            return None
        except Exception as e:
            logger.warning(
                f"router schedule for {req.rid} failed ({e}); "
                f"falling back to the client-local policy"
            )
            return None
        addr = out.get("url")
        if not addr or addr in failed:
            return None
        if out.get("policy"):
            # sticky resolution: the router's canary splitter picked an
            # exact version for this request — resumes carry it back so
            # a request never flips version mid-flight
            req.metadata["policy"] = str(out["policy"])
        with self._lock:
            self._router_version = int(
                out.get("version", self._router_version)
            )
            if out.get("kv_ship_from"):
                self._ship_hints[req.rid] = str(out["kv_ship_from"])
            self._rid_to_address[req.rid] = addr
            self._rid_to_address.move_to_end(req.rid)
            while len(self._rid_to_address) > 16384:
                self._rid_to_address.popitem(last=False)
        return addr

    async def _get_session(self) -> aiohttp.ClientSession:
        loop = asyncio.get_running_loop()
        # reap sessions whose owning loop is gone (each asyncio.run sweep
        # leaves one behind) so the map stays bounded by LIVE loops
        for key, (lp, s) in list(self._sessions.items()):
            if lp is not loop and lp.is_closed():
                self._sessions.pop(key)
                _abandon_session(s)
        ent = self._sessions.get(id(loop))
        if ent is None or ent[1].closed:
            s = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0)
            )
            self._sessions[id(loop)] = (loop, s)
            return s
        return ent[1]

    async def agenerate(self, req: ModelRequest) -> ModelResponse:
        """Interruptible generation loop (reference sglang_remote.py:121-249)."""
        gconfig = req.gconfig
        if gconfig.n_samples != 1:
            raise ValueError(
                "agenerate expects n_samples=1; workflows fan out samples"
            )
        session = await self._get_session()
        start = time.monotonic()
        accumulated: List[int] = []
        logprobs: List[float] = []
        versions: List[int] = []
        stop_reason = None
        ttft = None
        n_calls = 0
        n_aborts = 0
        n_failovers = 0
        failed: set = set()  # servers this request already failed on
        fleet_cfg = getattr(self.config, "fleet", None)
        max_failovers = (
            fleet_cfg.max_failovers_per_request if fleet_cfg else 8
        )
        chunk = self.config.new_tokens_per_chunk or 0
        # trace context: one trace id per EPISODE (the workflow
        # executor's lineage context — asyncio child tasks inherit it),
        # surviving retries and suffix-resume migrations; standalone
        # callers get a per-request id. Propagated to router + servers
        # via the X-Areal-Trace/X-Areal-Rid headers and bound onto this
        # client's own spans.
        episode = telemetry.current_episode()
        trace_id = (
            episode.trace_id if episode is not None
            else str(req.metadata.get("trace_id") or new_trace_id())
        )
        # affinity key for prefix-cache steering: the workflow's stamped
        # group/session id, else the episode uid (stable across a GRPO
        # group's sibling requests AND a multi-turn episode's turns —
        # both run inside one episode context)
        ep_uid = episode.uid if episode is not None else ""
        if ep_uid == "?":
            ep_uid = ""  # uid-less episodes must not all glue together
        qid = str(req.metadata.get("qid") or ep_uid or "") or None
        # traffic-plane stamps (api/cli_args.TrafficConfig): scheduling
        # class + tenant ride every /generate and router schedule;
        # workflows stamp metadata["priority"]/"tenant", the engine
        # config's default tenant covers the rest, and anything
        # unlabeled is bulk (shed-able — never silently promoted)
        traffic_cfg = getattr(self.config, "traffic", None)
        priority = str(req.metadata.get("priority") or "bulk")
        if priority not in ("interactive", "bulk"):
            priority = "bulk"
        tenant = str(
            req.metadata.get("tenant")
            or (traffic_cfg.tenant if traffic_cfg is not None else "")
        )
        deadline_s = req.metadata.get("deadline_s")
        deadline_at = (
            start + float(deadline_s)
            if deadline_s is not None and float(deadline_s) > 0
            else None
        )
        hdrs = trace_headers(trace_id, req.rid)
        self.tracer.bind_trace(req.rid, trace_id)
        lineage = telemetry.RequestLineage(
            rid=req.rid,
            attempt=episode.attempt if episode is not None else 0,
            # self-play stamps: which side of a multi-agent episode this
            # request belongs to (workflow/selfplay.py); "" elsewhere
            agent=str(req.metadata.get("agent") or ""),
            role=str(req.metadata.get("role") or ""),
        )
        routed = False  # this rid ever held a router schedule (ledger)
        try:
            while (
                stop_reason not in ("stop", "length")
                and len(accumulated) < gconfig.max_new_tokens
            ):
                if failed and len(failed) >= len(self.addresses):
                    # every server has failed this request once — forgive
                    # the exclusions (one may have recovered) rather than
                    # fail closed; max_failovers still bounds total hops
                    failed.clear()
                # named policy handle (r19): workflows stamp
                # metadata["policy"] ("actor", "actor@v13", ...);
                # re-read each chunk because the router's canary
                # splitter writes the resolved exact-version handle
                # back, keeping resumes on the same version
                policy = str(req.metadata.get("policy") or "")
                router_server = await self._schedule_via_router(
                    session, req, failed, hdrs, qid=qid,
                    priority=priority, tenant=tenant,
                    resumed=len(accumulated) > 0,
                    policy=policy,
                )
                policy = str(req.metadata.get("policy") or policy)
                lineage.policy = policy
                routed = routed or router_server is not None
                server = router_server or self.choose_server(
                    req.rid, exclude=failed, qid=qid
                )
                remaining = gconfig.max_new_tokens - len(accumulated)
                ask = min(remaining, chunk) if chunk > 0 else remaining
                payload = {
                    "rid": req.rid,
                    "input_ids": list(req.input_ids) + accumulated,
                    "priority": priority,
                    "tenant": tenant,
                    # suffix-resume continuations carry client progress:
                    # the server's admission bound never sheds them
                    "resumed": len(accumulated) > 0,
                    "sampling_params": {
                        "max_new_tokens": ask,
                    },
                }
                if policy:
                    payload["policy"] = policy
                with self._lock:
                    ship_from = self._ship_hints.pop(req.rid, None)
                if ship_from and ship_from != server:
                    # router affinity-miss hint (r16): the target server
                    # fetches this session's committed prefix from its
                    # previous owner before admitting the request
                    payload["kv_ship_from"] = ship_from
                deadline_left = (
                    deadline_at - time.monotonic()
                    if deadline_at is not None
                    else 0.0
                )
                if deadline_left > 0:
                    # per-chunk remaining deadline budget (the engine
                    # tracks an absolute deadline from chunk submit).
                    # An EXPIRED deadline is not restamped: the miss
                    # already happened, and a near-zero deadline on
                    # every remaining chunk would preempt one bulk
                    # victim per chunk and count one miss per chunk
                    payload["deadline_s"] = deadline_left
                if req.image_data:
                    payload["image_data"] = list(req.image_data)
                if req.mm is not None:
                    # JSON-safe multimodal payload. The big float32 patch
                    # tensor goes as ONE base64 blob (nested JSON lists
                    # would be ~8x the bytes and dominate request
                    # parsing); the small int meta arrays stay as lists.
                    import base64 as _b64
                    import numpy as _np

                    mm_json = {}
                    for k, v in req.mm.items():
                        if k == "pixel_values":
                            arr = _np.asarray(v, _np.float32)
                            mm_json["pixel_values_b64"] = _b64.b64encode(
                                arr.tobytes()
                            ).decode()
                            mm_json["pixel_values_shape"] = list(arr.shape)
                        else:
                            mm_json[k] = (
                                v.tolist() if hasattr(v, "tolist") else v
                            )
                    payload["mm"] = mm_json
                payload["sampling_params"].update(
                    {
                        "min_new_tokens": max(
                            0, gconfig.min_new_tokens - len(accumulated)
                        ),
                        "temperature": gconfig.temperature,
                        "top_p": gconfig.top_p,
                        "top_k": gconfig.top_k,
                        "greedy": gconfig.greedy,
                        "stop_token_ids": gconfig.stop_token_ids,
                    }
                )
                t_call = time.monotonic()
                try:
                    result = await arequest_with_retry(
                        session,
                        f"http://{server}/generate",
                        payload,
                        max_retries=self.config.request_retries,
                        timeout=self.config.request_timeout,
                        headers=hdrs,
                    )
                except HttpRequestError as e:
                    # retries exhausted against THIS server. 4xx means
                    # the request itself is wrong — propagate. Everything
                    # else (connect failure, timeout, 5xx) means the
                    # server is gone or sick: fail over to a healthy one
                    # and RESUME from the accumulated tokens — migration,
                    # not restart (the suffix-resume loop makes the moved
                    # request token-exact).
                    status = getattr(e, "status", None)
                    if status == 429:
                        # sustained load shed (Retry-After already
                        # honored per attempt inside the retry loop):
                        # surface to the episode-retry budget, visibly
                        stats_tracker.scalar(
                            **{"rollout/requests_shed": 1.0}
                        )
                        if self.tracer.enabled:
                            self.tracer.instant(
                                "shed", req.rid, sched_class=priority,
                                tenant=tenant, source="server",
                            )
                        raise
                    if status is not None and 400 <= status < 500:
                        raise
                    if self.fleet is not None:
                        self.fleet.report_failure(server)
                    with self._lock:
                        if self._rid_to_address.get(req.rid) == server:
                            del self._rid_to_address[req.rid]
                    failed.add(server)
                    n_failovers += 1
                    migrated = len(accumulated) > 0
                    lineage.failovers += 1
                    if migrated:
                        lineage.migrations += 1
                    if self.fleet is not None:
                        self.fleet.record_failover(migrated)
                    if self.tracer.enabled:
                        reason = (
                            f"http_{status}" if status is not None
                            else "connect"
                        )
                        self.tracer.instant(
                            "failover", req.rid, from_server=server,
                            reason=reason,
                            resumed_tokens=len(accumulated),
                        )
                        if migrated:
                            self.tracer.instant(
                                "migration", req.rid, from_server=server,
                                resumed_tokens=len(accumulated),
                            )
                    if n_failovers > max_failovers:
                        raise HttpRequestError(
                            f"request {req.rid} exceeded "
                            f"{max_failovers} failovers (last: {e})",
                            status=status,
                        ) from e
                    logger.warning(
                        f"failover: rid={req.rid} off {server} "
                        f"({e}); resuming {len(accumulated)} tokens "
                        f"elsewhere"
                    )
                    continue
                if self.fleet is not None:
                    self.fleet.report_success(server)
                n_calls += 1
                if self.tracer.enabled:
                    self.tracer.record(
                        "generate_call", req.rid, t_call, time.monotonic(),
                        server=server, new_tokens=len(result["output_ids"]),
                    )
                if ttft is None and result["output_ids"]:
                    ttft = time.monotonic() - start
                if result["output_ids"]:
                    # lineage: which server produced this token segment
                    # at which weight version(s)
                    lineage.add_segment(
                        server,
                        len(result["output_ids"]),
                        result["output_versions"],
                    )
                accumulated.extend(result["output_ids"])
                logprobs.extend(result["output_logprobs"])
                versions.extend(result["output_versions"])
                stop_reason = result["meta_info"]["finish_reason"]["type"]
                if (
                    stop_reason == "length"
                    and ask < remaining
                    and len(result["output_ids"]) >= ask
                ):
                    # chunk boundary, not a genuine stop: the server
                    # delivered everything this chunk asked for — resume
                    # from here (reference partial_rollout.py:181-250
                    # refresh cycle)
                    stop_reason = None
                if stop_reason == "abort":
                    # server is in a weight-update window; brief backoff
                    # then resume with accumulated tokens
                    n_aborts += 1
                    await asyncio.sleep(
                        self.config.pause_grace_period or 0.1
                    )
        finally:
            # an exception anywhere above must not leave a stale affinity
            # entry pinning this rid to a server it will never revisit
            with self._lock:
                self._rid_to_address.pop(req.rid, None)
            self.tracer.unbind_trace(req.rid)
            # hand the request's path to the episode's lineage record
            # even on failure — a half-generated, exception-killed
            # request is exactly what the ledger must explain. Runs
            # BEFORE the best-effort router notify below: a cancelled
            # await there must not cost the ledger its record.
            if episode is not None:
                lineage.ttft_s = ttft
                episode.add_request(lineage)
            # release the router's in-flight ledger entry (tenant/class
            # capacity) — on failure paths too, but ONLY for rids the
            # router actually scheduled (local-fallback requests never
            # entered its ledger, and pinging a wedged router from
            # every completion would stall the fallback path the outage
            # is relying on). Best-effort: the router's TTL sweep
            # covers a lost release; a fresh CancelledError here (loop
            # teardown) is suppressed without masking one already
            # propagating through this finally.
            router = getattr(self.config, "router_addr", "")
            if router and routed:
                try:
                    await arequest_with_retry(
                        session,
                        f"http://{router}/finish_request",
                        {"rid": req.rid},
                        max_retries=1,
                        timeout=5.0,
                    )
                except asyncio.CancelledError:
                    pass
                except Exception as e:
                    logger.debug(
                        f"finish_request for {req.rid} failed: {e}"
                    )
        now = time.monotonic()
        if self.tracer.enabled:
            # recorded after the finally-block unbind: carry the trace
            # attr explicitly so the lifecycle spans still stitch
            if ttft is not None:
                self.tracer.record(
                    "submit_to_first_token", req.rid, start, start + ttft,
                    trace=trace_id,
                )
            self.tracer.record(
                "rollout_request", req.rid, start, now,
                output_tokens=len(accumulated),
                stop_reason=stop_reason or "length",
                n_calls=n_calls, n_aborts=n_aborts,
                n_failovers=n_failovers, trace=trace_id,
            )
        # generation-time staleness: how far each produced token already
        # lags the trainer at COMPLETION time (the consumed-batch lag is
        # measured again at train time, ppo/actor.compute_advantages)
        if versions:
            trainer_v = self.get_version()
            lags = [trainer_v - v for v in versions]
            stats_tracker.scalar(**{
                "rollout/staleness_lag_mean": sum(lags) / len(lags),
                "rollout/staleness_lag_max": float(max(lags)),
                "rollout/ttft_s": ttft if ttft is not None else now - start,
                "rollout/latency_s": now - start,
                "rollout/output_tokens": float(len(accumulated)),
                "rollout/aborts_per_request": float(n_aborts),
                "rollout/failovers_per_request": float(n_failovers),
            })
            pol = str(req.metadata.get("policy") or "")
            if pol:
                # per-policy staleness attribution (r19): same lag
                # measure keyed by the line name, so canary vs stable
                # drift is separable on the trainer's dashboards
                pname = pol.split("@", 1)[0]
                stats_tracker.scalar(**{
                    f"rollout/policy/{pname}/staleness_lag_mean": (
                        sum(lags) / len(lags)
                    ),
                    f"rollout/policy/{pname}/staleness_lag_max": float(
                        max(lags)
                    ),
                    f"rollout/policy/{pname}/output_tokens": float(
                        len(accumulated)
                    ),
                    f"rollout/policy/{pname}/latency_s": now - start,
                })
        return ModelResponse(
            input_tokens=list(req.input_ids),
            output_tokens=accumulated,
            output_logprobs=logprobs,
            output_versions=versions,
            stop_reason=stop_reason or "length",
            latency=time.monotonic() - start,
            ttft=ttft or (time.monotonic() - start),
        )

    # ------------------------------------------------------------------
    # Weight updates (disk path)
    # ------------------------------------------------------------------
    def update_weights(self, meta: WeightUpdateMeta) -> concurrent.futures.Future:
        """Non-blocking weight push.

        Streamed mode (``config.streamed_weight_updates``, the default):
        no server is ever paused — the trainer streams chunks (or posts
        the disk reload) at live servers, each applies into a shadow
        buffer and flips at a dispatch boundary
        (inference/weights.WeightStore), and this client records one
        ``weight_stream`` span (``rollout/weight_stream_s``) instead of
        a ``weight_update_pause`` window. Legacy mode pauses every
        update-target server first (reference sglang_remote.py:251-309)
        and resumes after. Either way the wait/fan-out runs off-thread
        so one slow server never stalls the train loop."""
        streamed = bool(
            getattr(self.config, "streamed_weight_updates", True)
        )

        # fan-out target set: skip servers the fleet already knows are
        # DEAD/DRAINING — posting at them would stall or fail the whole
        # update for capacity that isn't serving anyway; WARMING servers
        # ARE included (see update_target_addresses)
        _alive_addresses = self.update_target_addresses

        def _pause_all():
            for addr in _alive_addresses():
                try:
                    r = _requests.post(
                        f"http://{addr}/pause_generation", timeout=30
                    )
                    r.raise_for_status()
                except Exception as e:
                    # a server that cannot even pause is effectively
                    # gone; open its circuit and keep the rest of the
                    # fleet moving (on recover, the re-sync path
                    # re-pushes the last disk checkpoint or drains it)
                    logger.error(f"pause_generation {addr} failed: {e}")
                    self._quarantine(addr)

        # Legacy mode pauses SYNCHRONOUSLY before returning (reference
        # pauses inline, sglang_remote.py:252-254): callers overlap
        # `update_weights(...)` with `engine.upload_weights(meta)`, and
        # streaming chunks into a not-yet-paused LEGACY server would
        # swap weights mid-decode. Streamed mode skips the pause
        # entirely — streamed servers stage into a shadow buffer and
        # flip between dispatches, so live decode is exactly the point.
        t_pause = time.monotonic()
        if not streamed:
            _pause_all()

        def _record_pause_window():
            # the full transfer window. Legacy: a pause span — rollout
            # capacity the fleet lost. Streamed: a weight_stream span —
            # wall time the push took while decode kept running (zero
            # pause spans is the r13 acceptance invariant,
            # trace_report --weights --require-zero-pause pins it).
            dur = time.monotonic() - t_pause
            if streamed:
                self.tracer.record(
                    "weight_stream", "__controller__", t_pause,
                    t_pause + dur, model_version=meta.model_version,
                )
                stats_tracker.scalar(**{"rollout/weight_stream_s": dur})
                return
            self.tracer.record(
                "weight_update_pause", "__controller__", t_pause,
                t_pause + dur, model_version=meta.model_version,
            )
            stats_tracker.scalar(**{"rollout/pause_window_s": dur})

        if meta.type == WeightUpdateMethod.DEVICE:

            def _do_device_update():
                try:
                    # the trainer streams chunks directly to the servers
                    # (spmd_engine.upload_weights); wait on the SAME set of
                    # addresses it streams to (meta.addrs when given), or
                    # unstreamed servers would be polled forever
                    targets = list(meta.addrs) or _alive_addresses()
                    # dedicated (shorter) bound: a failed upload must not
                    # hold every server paused for the full request
                    # timeout (3600s default)
                    deadline = time.monotonic() + min(
                        self.config.request_timeout,
                        getattr(self.config, "weight_update_timeout", 300.0),
                    )
                    reached = []
                    for addr in targets:
                        try:
                            while True:
                                r = _requests.get(
                                    f"http://{addr}/get_model_info",
                                    timeout=30,
                                )
                                r.raise_for_status()
                                if (
                                    int(r.json().get("model_version", -1))
                                    >= meta.model_version
                                ):
                                    reached.append(addr)
                                    break
                                if time.monotonic() > deadline:
                                    raise TimeoutError(
                                        f"{addr} never reached weight "
                                        f"version {meta.model_version}"
                                    )
                                time.sleep(0.2)
                        except Exception as e:
                            # one lost server must not strand the update
                            # on the surviving fleet — but it now holds
                            # STALE weights, so its circuit opens and
                            # re-admission goes through the version check
                            logger.error(
                                f"device weight update: {addr} dropped "
                                f"({e})"
                            )
                            self._quarantine(addr)
                    if not reached:
                        raise RuntimeError(
                            f"no server reached weight version "
                            f"{meta.model_version}"
                        )
                    self.set_version(meta.model_version)
                finally:
                    if not streamed:
                        self._resume_all_best_effort()
                    _record_pause_window()

            return self.executor.submit(_do_device_update)

        def _do_update():
            try:
                # the trainer signals checkpoint readiness via name_resolve
                # (reference fsdp_engine.py:384-395); flows that save before
                # calling us are detected by the checkpoint on disk
                key = names.update_weights_from_disk(
                    self.config.experiment_name,
                    self.config.trial_name,
                    meta.model_version,
                )
                deadline = time.monotonic() + self.config.request_timeout
                while True:
                    if os.path.exists(os.path.join(meta.path, "config.json")):
                        break
                    try:
                        name_resolve.get(key)
                        break
                    except name_resolve.NameEntryNotFoundError:
                        pass  # trainer hasn't posted the signal yet
                    except Exception as e:
                        # transient backend failure (kv server restart,
                        # NFS blip): keep polling until the deadline —
                        # the checkpoint-on-disk check above still
                        # short-circuits the wait
                        logger.debug(f"signal poll for {key} failed: {e}")
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"weight checkpoint never appeared at {meta.path}"
                        )
                    time.sleep(0.2)
                updated = []
                for addr in _alive_addresses():
                    try:
                        r = _requests.post(
                            f"http://{addr}/update_weights_from_disk",
                            json={
                                "model_path": meta.path,
                                "version": meta.model_version,
                            },
                            timeout=600,
                        )
                        r.raise_for_status()
                        body = r.json()
                        if not body.get("success"):
                            raise RuntimeError(
                                f"weight update rejected: {body}"
                            )
                        updated.append(addr)
                    except Exception as e:
                        # it missed this version: quarantine so it can
                        # only re-enter through the re-sync path
                        logger.error(
                            f"disk weight update: {addr} dropped ({e})"
                        )
                        self._quarantine(addr)
                if not updated:
                    raise RuntimeError(
                        f"no server accepted weight version "
                        f"{meta.model_version}"
                    )
                self.set_version(meta.model_version)
                # catch-up source for servers that were DEAD just now:
                # _on_server_recovered re-pushes this checkpoint
                self._last_disk_update = (meta.path, meta.model_version)
            finally:
                if not streamed:
                    self._resume_all_best_effort()
                _record_pause_window()

        return self.executor.submit(_do_update)

    def update_target_addresses(self) -> List[str]:
        """The servers a weight push should reach RIGHT NOW: every
        fleet member that is not DEAD/DRAINING, WARMING included
        (`FleetMonitor.is_update_target` — a cold server skipped here
        would finish compiling straight into rotation with stale
        weights). Callers building a device-path `WeightUpdateMeta`
        put this in ``meta.addrs`` so `spmd_engine.upload_weights`
        streams at the same set `update_weights` waits on."""
        if self.fleet is None:
            return list(self.addresses)
        in_target = getattr(
            self.fleet, "is_update_target", self.fleet.is_schedulable
        )
        alive = [a for a in self.addresses if in_target(a)]
        return alive or list(self.addresses)

    def _resume_all_best_effort(self):
        """continue_generation on every server; one dead server must not
        leave the rest paused (or mask the original exception)."""
        for addr in self.addresses:
            try:
                _requests.post(
                    f"http://{addr}/continue_generation", timeout=30
                )
            except Exception as e:
                logger.warning(f"continue_generation to {addr} failed: {e}")

    # ------------------------------------------------------------------
    # Rollout orchestration (delegated; reference sglang_remote.py:311-365)
    # ------------------------------------------------------------------
    def submit(self, data: Dict[str, Any], workflow: RolloutWorkflow) -> bool:
        """False when the sample is quarantined (not queued) — submit-N/
        wait-N callers must not count it or wait() starves."""
        return self.workflow_executor.submit(data, workflow)

    def wait(self, count: int, timeout: Optional[float] = None,
             group_filter=None):
        # rollout_wait: the async gap the goodput ledger measures —
        # trainer wall time spent blocked on generation (reentrant
        # no-op when the step loop already opened the bucket)
        with goodput.trainer_bucket("rollout_wait"):
            return self.workflow_executor.wait(
                count, timeout=timeout, group_filter=group_filter
            )

    def rollout_batch(self, data: List[Dict[str, Any]], workflow,
                      group_filter=None):
        with goodput.trainer_bucket("rollout_wait"):
            return self.workflow_executor.rollout_batch(
                data, workflow, group_filter=group_filter
            )

    def prepare_batch(self, dataloader, workflow, group_filter=None):
        with goodput.trainer_bucket("rollout_wait"):
            return self.workflow_executor.prepare_batch(
                dataloader, workflow, group_filter=group_filter
            )

    def pause(self):
        self.workflow_executor.pause()

    def resume(self):
        self.workflow_executor.resume()
