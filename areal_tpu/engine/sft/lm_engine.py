"""SFT language-model engine (reference areal/engine/sft/lm_engine.py).

Wraps a TrainEngine with the causal-LM loss over packed streams. The loss
mask convention matches the reference: ``loss_mask[t] == 1`` marks tokens
whose *prediction* should be trained (completion tokens), so the logit at
position t-1 is scored against token t.
"""

from typing import Dict

import jax.numpy as jnp

from areal_tpu.api.engine_api import TrainEngine
from areal_tpu.ops.functional import gather_logprobs
from areal_tpu.utils.data import Batch


def _shifted_targets(arrays: Dict) -> tuple:
    """(next_tokens, trainable-position mask) for packed [R, T] arrays."""
    tokens = arrays["tokens"]
    seg = arrays["segment_ids"]
    nxt_tok = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1
    )
    same = jnp.concatenate(
        [seg[:, 1:] == seg[:, :-1], jnp.zeros_like(seg[:, :1], bool)], axis=1
    ) & (seg > 0)
    if "t_loss_mask" in arrays:
        nxt_lm = jnp.concatenate(
            [
                arrays["t_loss_mask"][:, 1:],
                jnp.zeros_like(arrays["t_loss_mask"][:, :1]),
            ],
            axis=1,
        )
        mask = same & (nxt_lm > 0)
    else:
        mask = same
    return nxt_tok, mask


def sft_loss_fn(logits: jnp.ndarray, arrays: Dict):
    nxt_tok, mask = _shifted_targets(arrays)
    logp = gather_logprobs(logits, nxt_tok)
    m = mask.astype(jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    loss = -jnp.sum(logp * m) / denom
    # perplexity proxy stat (masked mean logp)
    return loss, {"nll": loss}


def sft_loss_weight_fn(arrays: Dict) -> jnp.ndarray:
    _, mask = _shifted_targets(arrays)
    return jnp.maximum(mask.astype(jnp.float32).sum(), 1.0)


class LMEngine:
    """Thin algorithm wrapper: train_lm/evaluate_lm over any TrainEngine."""

    def __init__(self, engine: TrainEngine):
        self.engine = engine

    def train_lm(self, data: Batch) -> Dict[str, float]:
        return self.engine.train_batch(data, sft_loss_fn, sft_loss_weight_fn)

    def evaluate_lm(self, data: Batch) -> Dict[str, float]:
        return self.engine.eval_batch(data, sft_loss_fn, sft_loss_weight_fn)
