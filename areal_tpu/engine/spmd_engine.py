"""SPMD train engine: sharded train state + jitted update on a device mesh.

Role of reference areal/engine/fsdp_engine.py + base_hf_engine.py, re-designed
TPU-first. Where the reference composes FSDP2 module wrapping + DTensor TP
plans + NCCL process groups, here ONE jitted train step over a
(data, fsdp, seq, tensor) mesh does everything: params carry NamedShardings
derived from logical-axis rules, XLA inserts the collectives (all-gather for
fsdp params, psum for grads — the ZeRO-3 schedule falls out of sharding
propagation), and microbatch gradient accumulation happens on device.

Contracts:
- ``loss_fn(logits, arrays) -> (loss, stats_dict)`` — pure, jit-traced.
  ``arrays`` holds tokens/segment_ids/positions plus packed per_token/per_seq
  aux data ("t_" / "s_" key prefixes).
- ``loss_weight_fn(arrays) -> scalar`` — each microbatch's contribution
  weight (e.g. valid token count); total is summed host-side so microbatch
  grads combine exactly as one big batch would (reference
  base_hf_engine.py:423-486 train_batch).
"""

import functools
import os
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from areal_tpu.api.cli_args import TrainEngineConfig
from areal_tpu.api.engine_api import TrainEngine
from areal_tpu.api.io_struct import FinetuneSpec, SaveLoadMeta, WeightUpdateMeta
from areal_tpu.models import hf_io
from areal_tpu.models.config import ModelConfig, load_hf_config
from areal_tpu.models.forward import packed_forward
from areal_tpu.models.transformer import (
    count_params,
    init_params,
    param_logical_axes,
)
from areal_tpu.parallel import distributed as distributed_lib
from areal_tpu.parallel import mesh as mesh_lib
from areal_tpu.parallel import sharding as sharding_lib
from areal_tpu.utils import data as data_utils
from areal_tpu.utils import goodput
from areal_tpu.utils import logging as logging_util
from areal_tpu.utils.data import Batch

logger = logging_util.getLogger("SPMDTrainEngine")

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}


def _lr_schedule(cfg, total_steps: int) -> optax.Schedule:
    opt = cfg.optimizer
    # proportion 0 means NO warmup: the first step must run at full lr
    # (max(1, ...) here made step 0 a silent no-op update)
    warmup = int(opt.warmup_steps_proportion * total_steps)
    if opt.warmup_steps_proportion > 0:
        warmup = max(1, warmup)
    end = opt.lr * opt.min_lr_ratio
    if opt.lr_scheduler_type == "cosine":
        main = optax.cosine_decay_schedule(
            opt.lr, max(1, total_steps - warmup), alpha=opt.min_lr_ratio
        )
    elif opt.lr_scheduler_type == "linear":
        main = optax.linear_schedule(
            opt.lr, end, max(1, total_steps - warmup)
        )
    else:
        main = optax.constant_schedule(opt.lr)
    if warmup == 0:
        return main
    return optax.join_schedules(
        [optax.linear_schedule(0.0, opt.lr, warmup), main], [warmup]
    )


def _make_optimizer(opt, schedule) -> optax.GradientTransformation:
    """OptimizerConfig.type dispatch (reference cli_args.py:140 `type`):
    adamw (default; f32 moments = the reference's mixed-precision Adam) or
    sgd (stateless — lets model sizes whose Adam moments exceed HBM, e.g.
    the 1.5B bench anchor on one 16 GB chip, still take real steps)."""
    if opt.type == "sgd":
        return optax.chain(
            optax.clip_by_global_norm(opt.gradient_clipping),
            # decay is stateless — dropping Adam's moments to fit HBM is
            # no reason to silently drop the configured regularizer
            optax.add_decayed_weights(opt.weight_decay),
            optax.sgd(learning_rate=schedule),
        )
    if opt.type != "adamw":
        raise ValueError(f"unknown optimizer type {opt.type!r}")
    return optax.chain(
        optax.clip_by_global_norm(opt.gradient_clipping),
        optax.adamw(
            learning_rate=schedule,
            b1=opt.beta1,
            b2=opt.beta2,
            eps=opt.eps,
            weight_decay=opt.weight_decay,
            mu_dtype=jnp.float32,
        ),
    )


class SPMDTrainEngine(TrainEngine):
    """The TPU analog of FSDPEngine: one SPMD program over one mesh."""

    def __init__(self, config: TrainEngineConfig):
        self.config = config
        self.model_config: Optional[ModelConfig] = None
        self.mesh = None
        self.params = None
        self.opt_state = None
        self.optimizer = None
        self.lr_schedule = None
        self.step_count = 0
        self._version = 0
        self.compute_dtype = _DTYPES[config.dtype]
        self.param_dtype = _DTYPES[config.param_dtype]
        self._jit_cache: Dict[Any, Callable] = {}
        self.initialized = False

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    def initialize(
        self,
        ft_spec: Optional[FinetuneSpec] = None,
        model_config: Optional[ModelConfig] = None,
        seed: int = 0,
    ):
        cfg = self.config
        self.mesh = mesh_lib.make_mesh(cfg.parallel)
        if model_config is not None:
            self.model_config = model_config
        elif cfg.path:
            self.model_config = load_hf_config(cfg.path)
        else:
            raise ValueError("need config.path or explicit model_config")
        mc = self.model_config
        is_critic = bool(getattr(cfg, "is_critic", False))
        logical = param_logical_axes(mc, value_head=is_critic)
        self._param_shardings = sharding_lib.tree_shardings(self.mesh, logical)
        if cfg.path and not cfg.init_from_scratch:
            host_params = hf_io.load_params(cfg.path, mc, dtype=self.param_dtype)
            if is_critic:
                # fresh scalar head on top of the pretrained trunk
                # (reference critic init: actor trunk + new value head)
                import numpy as _np

                host_params["value_head"] = (
                    _np.asarray(
                        jax.random.normal(
                            jax.random.PRNGKey(seed + 101),
                            (mc.hidden_size, 1),
                        )
                    )
                    * 0.02
                ).astype(self.param_dtype)
                host_params.pop("lm_head", None)
        else:
            host_params = init_params(
                mc, jax.random.PRNGKey(seed), dtype=self.param_dtype,
                value_head=is_critic,
            )
        self.params = jax.tree_util.tree_map(
            lambda a, sh: distributed_lib.make_global_array(
                np.asarray(a), sh
            ),
            host_params,
            self._param_shardings,
        )
        if cfg.optimizer is not None:
            total_steps = ft_spec.total_train_steps if ft_spec else 10000
            self.lr_schedule = _lr_schedule(cfg, total_steps)
            self.optimizer = _make_optimizer(
                cfg.optimizer, self.lr_schedule
            )
            # jit without out_shardings: XLA's sharding propagation gives the
            # adam moments their params' shardings (they are elementwise maps
            # of the params) — the ZeRO "shard optimizer state" property for
            # free.
            self.opt_state = jax.jit(self.optimizer.init)(self.params)
        if cfg.attn_impl == "flash" and jax.default_backend() != "cpu":
            # probe the splash block edge once per process so the fast
            # long-context path is the default, not an env-var opt-in
            # (round-3 driver capture silently lost 5x on the opt-in)
            from areal_tpu.ops import flash as flash_ops

            self._splash_block = flash_ops.probe_block_size()
        n = count_params(self.params)
        logger.info(
            f"initialized {mc.family} model: {n/1e6:.1f}M params on mesh "
            f"{dict(zip(self.mesh.axis_names, self.mesh.devices.shape))}"
        )
        self.initialized = True
        return self

    def rebuild_optimizer(
        self, opt_config, total_steps: int = 10000
    ) -> None:
        """Swap the optimizer (fresh state) without touching params —
        e.g. an RL phase following SFT needs a far smaller step size.
        Clears the jitted-program cache (apply programs close over the
        optimizer)."""
        cfg = self.config
        old_opt = cfg.optimizer
        cfg.optimizer = opt_config
        try:
            self.lr_schedule = _lr_schedule(cfg, total_steps)
        finally:
            cfg.optimizer = old_opt
        self.optimizer = _make_optimizer(opt_config, self.lr_schedule)
        self.opt_state = jax.jit(self.optimizer.init)(self.params)
        self._jit_cache.clear()

    def destroy(self):
        self.params = None
        self.opt_state = None
        self._jit_cache.clear()
        self.initialized = False

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------
    @property
    def data_parallel_rank(self) -> int:
        return jax.process_index()

    @property
    def data_parallel_world_size(self) -> int:
        return jax.process_count()

    def get_version(self) -> int:
        return self._version

    def set_version(self, version: int):
        self._version = version

    # ------------------------------------------------------------------
    # Packing
    # ------------------------------------------------------------------
    def _dp_rows(self) -> int:
        p = self.config.parallel
        return (
            getattr(p, "dcn_data_parallel_size", 1)
            * getattr(p, "dcn_fsdp_parallel_size", 1)
            * p.data_parallel_size
            * p.fsdp_parallel_size
        )

    def _batch_sharding(self):
        return sharding_lib.batch_sharding(self.mesh)

    def _mb_pad_to(self, mbs: List[Batch]) -> Optional[int]:
        """Static per-row token pad for multi-microbatch steps: every
        microbatch pads to ONE shared bucket (sized from the largest
        microbatch, not the cap — min_n_mbs-forced splits of small batches
        must not pay near-cap compute), so the expensive grad program
        compiles once instead of per FFD-packed size."""
        if len(mbs) <= 1:
            return None
        rows = self._dp_rows()
        seq_mult = self.config.parallel.seq_parallel_size
        biggest = max(
            int(np.asarray(mb["attention_mask"]).sum()) for mb in mbs
        )
        # coarse quantum: every distinct bucket compiles the (expensive)
        # grad program once, and FFD-packed sizes jitter step to step —
        # a 1k quantum trades ~4% padding for a handful of compiles total
        return data_utils.next_bucket_size(
            -(-biggest // rows), 1024 * seq_mult
        )

    def _pack_for_device(
        self, mb: Batch, pad_to: Optional[int] = None
    ) -> Tuple[data_utils.PackedRows, Dict[str, jnp.ndarray]]:
        rows = self._dp_rows()
        seq_mult = self.config.parallel.seq_parallel_size
        # bucket quantum must divide evenly across the seq axis
        try:
            packed = data_utils.pack_batch_rows(
                mb, n_rows=rows, quantum=256 * seq_mult, pad_to=pad_to
            )
        except ValueError:
            # a row outgrew the static pad (one very long sequence);
            # fall back to the dynamic bucket for this microbatch
            packed = data_utils.pack_batch_rows(
                mb, n_rows=rows, quantum=256 * seq_mult
            )
        arrays: Dict[str, Any] = dict(
            tokens=packed.tokens,
            segment_ids=packed.segment_ids,
            positions=packed.positions,
        )
        for k, v in packed.per_token.items():
            arrays[f"t_{k}"] = v
        for k, v in packed.per_seq.items():
            arrays[f"s_{k}"] = v
        bsh = self._batch_sharding()
        rep = sharding_lib.replicated(self.mesh)
        shardings = {}
        for k, v in arrays.items():
            shardings[k] = bsh if (
                v.ndim >= 2 and v.shape[:2] == packed.tokens.shape
            ) else (
                NamedSharding(self.mesh, P(("data", "fsdp")))
                if v.ndim >= 1 and v.shape[0] == packed.tokens.shape[0]
                else rep
            )
        if jax.process_count() == 1:
            # ONE tree-wide transfer: per-key device_put pays a host
            # round-trip each on driver-tunneled chips (~25x slower)
            dev = jax.device_put(
                {k: np.asarray(v) for k, v in arrays.items()}, shardings
            )
        else:
            # multi-host: every process holds the identical full batch (the
            # DP-head broadcast guarantees it) and contributes only its
            # addressable shards to the global array
            dev = {
                k: distributed_lib.make_global_array(
                    np.asarray(v), shardings[k]
                )
                for k, v in arrays.items()
            }
        return packed, dev

    # ------------------------------------------------------------------
    # Train
    # ------------------------------------------------------------------
    def _flash_window(self, input_: Batch) -> int:
        """Pow2-bucketed max sequence length: the splash kernel's
        block-sparse local window (full causal over a long packed stream is
        T² block iteration; sequences only need their own length)."""
        if self.config.attn_impl != "flash":
            return 0
        lens = np.asarray(input_["attention_mask"]).sum(1)
        m = max(1, int(lens.max()))
        w = 256
        while w < m:
            w *= 2
        return w

    def _act_sharding(self):
        """[B, T, D] activation constraint: rows over (data, fsdp), tokens
        over seq. Pinning this stops GSPMD from propagating the embedding
        table's column sharding onto the batch (which replicates every
        layer activation across fsdp — measured 81 GB/device of layer
        temps on a 7B/16-device lowering)."""
        return NamedSharding(self.mesh, P(("data", "fsdp"), "seq", None))

    def _lazy_head(self) -> bool:
        """Whether loss paths get the lazy ChunkedLogits view (critics
        always get real values — their head is [D, 1])."""
        return bool(
            getattr(self.config, "chunked_lm_head", True)
            and not getattr(self.config, "is_critic", False)
        )

    def _attend_fn(self, window: int = 0):
        """Attention kernel override: "flash" (Pallas splash, TPU-only),
        "ring"/"ulysses" (explicit SP shard_map), or None for the XLA kernel
        with GSPMD auto-sharding."""
        impl = self.config.attn_impl
        if impl == "flash":
            from areal_tpu.ops.flash import flash_segment_attention

            return functools.partial(flash_segment_attention, window=window)
        if impl == "auto" or self.config.parallel.seq_parallel_size == 1:
            return None
        if not hasattr(self, "_cached_attend"):
            from areal_tpu.ops.ring_attention import make_sharded_attention

            self._cached_attend = make_sharded_attention(self.mesh, impl=impl)
        return self._cached_attend

    def _get_grad_fn(
        self, loss_fn: Callable, loss_weight_fn: Callable, window: int = 0
    ):
        key = ("grad", loss_fn, loss_weight_fn, window)
        if key not in self._jit_cache:
            mc = self.model_config
            remat = self.config.gradient_checkpointing
            compute_dtype = self.compute_dtype
            attend = self._attend_fn(window)
            lazy_head = self._lazy_head()
            act_sh = self._act_sharding()

            def fwd_loss(params, arrays):
                cparams = jax.tree_util.tree_map(
                    lambda p: p.astype(compute_dtype), params
                )
                logits, router_aux = packed_forward(
                    cparams, mc, arrays, remat=remat,
                    remat_save_attn=self.config.remat_save_attn,
                    attend_fn=attend,
                    return_router_loss=True, return_hidden=lazy_head,
                    act_sharding=act_sh,
                )
                loss, stats = loss_fn(logits, arrays)
                if mc.is_moe and mc.router_aux_loss_coef:
                    loss = loss + mc.router_aux_loss_coef * router_aux
                    stats = dict(stats, router_aux_loss=router_aux)
                w = loss_weight_fn(arrays).astype(jnp.float32)
                return loss * w, (loss, stats, w)

            def grad_step(params, grad_accum, arrays):
                grads, (loss, stats, w) = jax.grad(fwd_loss, has_aux=True)(
                    params, arrays
                )
                new_accum = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), grad_accum, grads
                )
                return new_accum, loss, stats, w

            self._jit_cache[key] = jax.jit(grad_step, donate_argnums=(1,))
        return self._jit_cache[key]

    def _get_apply_fn(self):
        key = "apply"
        if key not in self._jit_cache:

            def apply_step(params, opt_state, grad_accum, total_weight):
                grads = jax.tree_util.tree_map(
                    lambda g: g / total_weight, grad_accum
                )
                grad_norm = optax.global_norm(grads)
                updates, new_opt = self.optimizer.update(
                    grads, opt_state, params
                )
                new_params = optax.apply_updates(params, updates)
                # keep the declared param dtype: f32 updates would silently
                # promote bf16 params (breaking donation every step)
                new_params = jax.tree_util.tree_map(
                    lambda n, o: n.astype(o.dtype), new_params, params
                )
                # skip non-finite updates (reference base_hf_engine.py:474)
                ok = jnp.isfinite(grad_norm)
                new_params = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok, n, o), new_params, params
                )
                new_opt = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok, n, o), new_opt, opt_state
                )
                return new_params, new_opt, grad_norm, ok

            self._jit_cache[key] = jax.jit(apply_step, donate_argnums=(0, 1, 2))
        return self._jit_cache[key]

    def _zero_grads(self):
        key = "zeros"
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(
                lambda params: jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params
                ),
                out_shardings=self._param_shardings,
            )
        return self._jit_cache[key](self.params)

    def train_batch(
        self,
        input_: Batch,
        loss_fn: Callable,
        loss_weight_fn: Callable,
    ) -> Dict[str, float]:
        if self.optimizer is None:
            raise RuntimeError("no optimizer configured")
        t_start = time.perf_counter()
        mbs = data_utils.split_padded_batch_into_mb_list(
            input_, self.config.mb_spec.max_tokens_per_mb,
            min_n_mbs=self.config.mb_spec.n_mbs,
        )
        window = self._flash_window(input_)
        grad_fn = self._get_grad_fn(loss_fn, loss_weight_fn, window)
        grad_accum = self._zero_grads()
        pad_to = self._mb_pad_to(mbs.mbs)
        losses, weights, all_stats = [], [], []
        pack_s, grad_call_s = 0.0, []
        # goodput attribution: host packing books data_h2d, the grad
        # dispatches book fwd_bwd (minus any compile, which the trainer
        # CompileTracker carves into the compile bucket with this step's
        # shape signature), the apply + scalar fetch books optim
        gp_sig = f"mbs{len(mbs.mbs)}|pad{pad_to}|window{window}"
        for mb in mbs.mbs:
            t0 = time.perf_counter()
            with goodput.trainer_bucket("data_h2d"):
                _, arrays = self._pack_for_device(mb, pad_to=pad_to)
            t1 = time.perf_counter()
            pack_s += t1 - t0
            with goodput.trainer_bucket("fwd_bwd"), goodput.dispatch_scope(
                goodput.trainer_tracker(), "fwd_bwd", gp_sig
            ):
                grad_accum, loss, stats, w = grad_fn(
                    self.params, grad_accum, arrays
                )
            # wall time of the (async) dispatch: a multi-second outlier on
            # one call = that call traced/compiled a fresh program
            grad_call_s.append(round(time.perf_counter() - t1, 3))
            losses.append(loss)
            weights.append(w)
            all_stats.append(stats)
        total_w = functools.reduce(lambda a, b: a + b, weights)
        apply_fn = self._get_apply_fn()
        t_apply = time.perf_counter()
        # the optim bucket spans apply THROUGH the blocking scalar
        # fetch: the fetch is where every async dispatch's device
        # compute actually lands on the wall clock
        with goodput.trainer_bucket("optim"):
            with goodput.dispatch_scope(
                goodput.trainer_tracker(), "optim", gp_sig
            ):
                self.params, self.opt_state, grad_norm, ok = apply_fn(
                    self.params, self.opt_state, grad_accum, total_w
                )
            lr = float(self.lr_schedule(self.step_count))
            self.step_count += 1
            t_fetch = time.perf_counter()
            # ONE packed host fetch for every scalar this step produced —
            # each separate float() is a full device round-trip
            stat_keys = sorted(all_stats[0])
            scalars = [ok, grad_norm, total_w] + losses + weights + [
                s[k] for s in all_stats for k in stat_keys
            ]
            blob = np.asarray(
                jnp.stack(
                    [
                        jnp.asarray(x, jnp.float32).reshape(())
                        for x in scalars
                    ]
                )
            )
        n_mb = len(mbs.mbs)
        h_ok, h_gnorm, h_total_w = blob[0], blob[1], blob[2]
        h_losses = blob[3 : 3 + n_mb]
        h_weights = blob[3 + n_mb : 3 + 2 * n_mb]
        h_stats = blob[3 + 2 * n_mb :].reshape(n_mb, len(stat_keys))
        out = {
            "update_successful": float(h_ok),
            "grad_norm": float(h_gnorm),
            "lr": lr,
            "loss": float((h_losses * h_weights).sum() / h_total_w),
            "n_mbs": float(n_mb),
        }
        for j, k in enumerate(stat_keys):
            out[k] = float((h_stats[:, j] * h_weights).sum() / h_total_w)
        t_end = time.perf_counter()
        # diagnostics for bench/driver post-hoc analysis: where did this
        # step's wall time go, and did any dispatch compile?
        self.last_timing = {
            "total_s": round(t_end - t_start, 3),
            "pack_s": round(pack_s, 3),
            "grad_dispatch_s": grad_call_s,
            "apply_fetch_s": round(t_end - t_apply, 3),
            "fetch_s": round(t_end - t_fetch, 3),
            "n_mbs": n_mb,
            "pad_to": pad_to,
            "window": window,
        }
        # same breakdown through the stats plane: StatsLogger.commit
        # persists it per step alongside the rollout/staleness telemetry
        from areal_tpu.utils import stats_tracker

        stats_tracker.scalar(**{
            "spmd/train_batch_s": t_end - t_start,
            "spmd/pack_s": pack_s,
            "spmd/grad_dispatch_s": float(sum(grad_call_s)),
            "spmd/apply_fetch_s": t_end - t_apply,
            "spmd/n_mbs": float(n_mb),
        })
        return out

    def eval_batch(
        self, input_: Batch, loss_fn: Callable, loss_weight_fn: Callable
    ) -> Dict[str, float]:
        mbs = data_utils.split_padded_batch_into_mb_list(
            input_, self.config.mb_spec.max_tokens_per_mb,
            min_n_mbs=self.config.mb_spec.n_mbs,
        )
        window = self._flash_window(input_)
        key = ("eval", loss_fn, loss_weight_fn, window)
        if key not in self._jit_cache:
            mc = self.model_config
            compute_dtype = self.compute_dtype
            attend = self._attend_fn(window)
            lazy_head = self._lazy_head()
            act_sh = self._act_sharding()

            def eval_step(params, arrays):
                cparams = jax.tree_util.tree_map(
                    lambda p: p.astype(compute_dtype), params
                )
                logits = packed_forward(
                    cparams, mc, arrays, remat=False, attend_fn=attend,
                    return_hidden=lazy_head, act_sharding=act_sh,
                )
                loss, stats = loss_fn(logits, arrays)
                return loss, stats, loss_weight_fn(arrays).astype(jnp.float32)

            self._jit_cache[key] = jax.jit(eval_step)
        pad_to = self._mb_pad_to(mbs.mbs)
        losses, weights = [], []
        for mb in mbs.mbs:
            _, arrays = self._pack_for_device(mb, pad_to=pad_to)
            loss, stats, w = self._jit_cache[key](self.params, arrays)
            losses.append(loss)
            weights.append(w)
        blob = np.asarray(
            jnp.stack(
                [jnp.asarray(x, jnp.float32).reshape(()) for x in losses + weights]
            )
        )
        n = len(losses)
        return {
            "loss": float(
                (blob[:n] * blob[n:]).sum() / max(blob[n:].sum(), 1.0)
            )
        }

    # ------------------------------------------------------------------
    # Forward (inference over the train model, e.g. logprob recompute)
    # ------------------------------------------------------------------
    def forward(
        self,
        input_: Batch,
        post_hook: Optional[Callable] = None,
    ) -> np.ndarray:
        """Run the model over `input_` and return a padded [B, L] per-token
        array in the original order, where L is the input's padded width
        (reference base_hf_engine.py:525).

        `post_hook(logits, arrays) -> [R, T] array` must be jittable; default
        returns target-aligned logprobs.
        """
        mbs = data_utils.split_padded_batch_into_mb_list(
            input_, self.config.mb_spec.max_tokens_per_mb,
            min_n_mbs=self.config.mb_spec.n_mbs,
        )
        hook = post_hook or _default_logprob_hook
        window = self._flash_window(input_)
        key = ("fwd", hook, window)
        if key not in self._jit_cache:
            mc = self.model_config
            compute_dtype = self.compute_dtype
            attend = self._attend_fn(window)
            lazy_head = self._lazy_head()
            act_sh = self._act_sharding()

            def fwd(params, arrays):
                cparams = jax.tree_util.tree_map(
                    lambda p: p.astype(compute_dtype), params
                )
                logits = packed_forward(
                    cparams, mc, arrays, remat=False, attend_fn=attend,
                    return_hidden=lazy_head, act_sharding=act_sh,
                )
                return hook(logits, arrays)

            # replicated output: under multi-process the per-token result
            # must be fully addressable for the host np.asarray fetch
            self._jit_cache[key] = jax.jit(
                fwd,
                out_shardings=sharding_lib.replicated(self.mesh),
            )
        pad_to = self._mb_pad_to(mbs.mbs)
        outs = []
        for mb in mbs.mbs:
            packed, arrays = self._pack_for_device(mb, pad_to=pad_to)
            vals = np.asarray(self._jit_cache[key](self.params, arrays))
            outs.append(data_utils.unpack_rows_per_token(packed, vals))
        # scatter back to original order at the input's padded width
        bsz = data_utils.batch_size(input_)
        width = np.asarray(input_["attention_mask"]).shape[1]
        out = np.zeros((bsz, width) + outs[0].shape[2:], outs[0].dtype)
        for group, o in zip(mbs.groups, outs):
            out[np.asarray(group), : o.shape[1]] = o
        return out

    # ------------------------------------------------------------------
    # Save / load / weight push
    # ------------------------------------------------------------------
    def _host_tree(self, tree, dtype=None):
        """Gather a (possibly cross-process-sharded) pytree to host.

        Multi-process arrays are not fully addressable, so they are first
        replicated through a jitted identity (one all-gather — every rank
        participates: this is a COLLECTIVE and must be called on all
        processes) and then fetched."""
        if dtype is not None:
            tree = jax.tree_util.tree_map(
                lambda p: p.astype(dtype), tree
            )
        if jax.process_count() > 1:
            # memoized per tree structure: a fresh lambda per call would
            # recompile the full all-gather program on every weight push
            treedef = jax.tree_util.tree_structure(tree)
            key = ("host_gather", treedef)
            if key not in self._jit_cache:
                rep = sharding_lib.replicated(self.mesh)
                self._jit_cache[key] = jax.jit(
                    lambda t: t,
                    out_shardings=jax.tree_util.tree_unflatten(
                        treedef,
                        [rep] * treedef.num_leaves,
                    ),
                )
            tree = self._jit_cache[key](tree)
        return jax.device_get(tree)

    def save(self, meta: SaveLoadMeta):
        if meta.weight_format == "hf":
            host = self._host_tree(self.params)
            if jax.process_index() == 0:
                hf_io.save_params(host, self.model_config, meta.path)
            if meta.with_optim:
                self._save_optim(os.path.join(meta.path, "optim"))
        else:
            import orbax.checkpoint as ocp

            ckpt = {"params": self.params, "step": self.step_count}
            if meta.with_optim and self.opt_state is not None:
                ckpt["opt_state"] = self.opt_state
            ocp.StandardCheckpointer().save(
                os.path.abspath(meta.path), ckpt, force=True
            )

    def _save_optim(self, path: str):
        flat, _ = jax.tree_util.tree_flatten(self._host_tree(self.opt_state))
        if jax.process_index() != 0:
            return
        os.makedirs(path, exist_ok=True)
        np.savez(
            os.path.join(path, "opt_state.npz"),
            *[np.asarray(x) for x in flat],
            step=self.step_count,
        )

    def load(self, meta: SaveLoadMeta):
        if meta.weight_format == "hf":
            host = hf_io.load_params(
                meta.path, self.model_config, dtype=self.param_dtype
            )
            self.params = jax.device_put(host, self._param_shardings)
            optim_path = os.path.join(meta.path, "optim", "opt_state.npz")
            if meta.with_optim and os.path.exists(optim_path):
                data = np.load(optim_path)
                flat, treedef = jax.tree_util.tree_flatten(self.opt_state)
                arrs = [data[f"arr_{i}"] for i in range(len(flat))]
                host_opt = jax.tree_util.tree_unflatten(treedef, arrs)
                shardings = jax.tree_util.tree_map(
                    lambda x: x.sharding, self.opt_state
                )
                self.opt_state = jax.device_put(host_opt, shardings)
                self.step_count = int(data["step"])
        else:
            import orbax.checkpoint as ocp

            restored = ocp.StandardCheckpointer().restore(
                os.path.abspath(meta.path)
            )
            self.params = jax.device_put(
                restored["params"], self._param_shardings
            )
            self.step_count = int(restored["step"])
            if meta.with_optim and "opt_state" in restored:
                shardings = jax.tree_util.tree_map(
                    lambda x: x.sharding, self.opt_state
                )
                self.opt_state = jax.device_put(
                    restored["opt_state"], shardings
                )

    def iter_weight_chunks(self, chunk_bytes: int, dtype=None):
        """Yield ``(chunk_index, n_chunks, [(name, np.ndarray)])`` one FFD
        chunk at a time, gathering each chunk to host independently — peak
        host memory is O(chunk_bytes), never O(model) (the reference
        streams ≤1 GB FFD chunks the same way, fsdp_engine.py:435-444;
        round-2 verdict flagged the full-model host gather as a v5e-host
        OOM risk at 7B).

        COLLECTIVE in multi-process runs: every rank must drain the
        generator in the same order (each chunk's replication is an
        all-gather)."""
        from areal_tpu.utils import weight_transfer as wt

        leaves = wt.flatten_params(self.params)  # (name, jax.Array)
        plan = wt.chunk_leaves(leaves, chunk_bytes)
        n = len(plan)
        multiproc = jax.process_count() > 1
        for i, items in enumerate(plan):
            arrs = [a for _, a in items]
            if dtype is not None or multiproc:
                key = ("chunk_gather", dtype, i, n)
                if key not in self._jit_cache:
                    kwargs = {}
                    if multiproc:
                        rep = sharding_lib.replicated(self.mesh)
                        kwargs["out_shardings"] = [rep] * len(arrs)
                    dt = dtype

                    def _g(xs, dt=dt):
                        return [
                            x if dt is None else x.astype(dt) for x in xs
                        ]

                    self._jit_cache[key] = jax.jit(_g, **kwargs)
                arrs = self._jit_cache[key](arrs)
            fetched = jax.device_get(arrs)
            yield i, n, [
                (name, np.asarray(a))
                for (name, _), a in zip(items, fetched)
            ]

    def upload_weights(self, meta: WeightUpdateMeta):
        """Push fresh weights to the generation side.

        DISK: write an HF checkpoint the generation engine reloads
        (reference fsdp_engine.py:384-395).

        DEVICE: stream the sharded params chunk-by-chunk
        (``iter_weight_chunks``): each ≤chunk_bytes FFD chunk is gathered
        to host, posted as one binary POST to every generation server, and
        freed before the next gather — no disk round-trip and no
        full-model host copy (reference _update_weights_from_distributed,
        fsdp_engine.py:414-433). Server addresses come from meta.addrs or
        the AREAL_LLM_SERVER_ADDRS environment.
        """
        from areal_tpu.api.io_struct import WeightUpdateMethod
        from areal_tpu.utils import stats_tracker

        t_upload = time.perf_counter()

        if meta.type == WeightUpdateMethod.DISK:
            with goodput.trainer_bucket("weight_push"):
                host = self._host_tree(self.params)  # collective
                if jax.process_index() == 0:
                    hf_io.save_params(host, self.model_config, meta.path)
            stats_tracker.scalar(**{
                "spmd/upload_weights_s": time.perf_counter() - t_upload
            })
            return
        from areal_tpu.utils import weight_transfer as wt

        addrs = list(meta.addrs or [])
        if not addrs:
            env = os.environ.get("AREAL_LLM_SERVER_ADDRS", "")
            addrs = [a for a in env.split(",") if a]
        if not addrs:
            raise ValueError(
                "WeightUpdateMethod.DEVICE needs server addresses "
                "(meta.addrs or AREAL_LLM_SERVER_ADDRS)"
            )
        import json as _json
        from concurrent.futures import ThreadPoolExecutor

        def _post(addr: str, i: int, body: bytes):
            req = urllib.request.Request(
                f"http://{addr}/update_weights_from_distributed",
                data=body,
                headers={"Content-Type": "application/octet-stream"},
            )
            with urllib.request.urlopen(req, timeout=600) as r:
                resp = _json.loads(r.read())
            if resp.get("success") is not True:
                raise RuntimeError(
                    f"weight chunk {i} rejected by {addr}: {resp}"
                )

        # fan each chunk out to all servers concurrently (the reference's
        # broadcast reaches every server at once). Streamed-mode servers
        # (r13, the default) stay LIVE through the transfer — each chunk
        # lands in a shadow buffer while decode runs — but wall time
        # still matters: it bounds how stale the flip is by the time it
        # applies, and legacy servers sit paused for all of it. The
        # generator is collective: non-zero ranks drain it without
        # posting.
        with goodput.trainer_bucket("weight_push"), ThreadPoolExecutor(
            max_workers=max(1, len(addrs))
        ) as pool:
            for i, n_chunks, chunk in self.iter_weight_chunks(
                meta.chunk_bytes, dtype=self.compute_dtype
            ):
                if jax.process_index() != 0:
                    continue
                body = wt.encode_chunk(
                    meta.model_version, i, n_chunks, chunk
                )
                del chunk
                futs = [
                    pool.submit(_post, addr, i, body) for addr in addrs
                ]
                for f in futs:
                    f.result()
        stats_tracker.scalar(**{
            "spmd/upload_weights_s": time.perf_counter() - t_upload
        })


def target_aligned_logprobs(
    logits: jnp.ndarray, arrays: Dict, temperature: float = 1.0
) -> jnp.ndarray:
    """Logprobs aligned to the TARGET token: out[t] = log p(token_t | <t),
    0 at each sequence's first token and on padding. This matches the
    per-generated-token logprobs the rollout engine reports, so behavior /
    proximal / new logprobs line up index-for-index (reference
    ppo/actor.py compute_logp + utils/functional.py:29)."""
    from areal_tpu.ops.functional import gather_logprobs

    tokens = arrays["tokens"]
    seg = arrays["segment_ids"]
    logp_shift = gather_logprobs(
        logits[:, :-1], tokens[:, 1:], temperature=temperature
    )
    out = jnp.concatenate(
        [jnp.zeros_like(logp_shift[:, :1]), logp_shift], axis=1
    )
    prev_same = jnp.concatenate(
        [jnp.zeros_like(seg[:, :1], bool), seg[:, 1:] == seg[:, :-1]], axis=1
    ) & (seg > 0)
    return jnp.where(prev_same, out, 0.0)


def _default_logprob_hook(logits: jnp.ndarray, arrays: Dict) -> jnp.ndarray:
    return target_aligned_logprobs(logits, arrays)
