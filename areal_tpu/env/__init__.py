"""Environment implementations for agentic workflows."""

from areal_tpu.env.math_code_env import MathCodeSingleStepEnv  # noqa: F401

# env/service.py (the environment service plane) is exported lazily: it
# pulls in the HTTP client stack, which module-level importers of this
# package (and every env-worker subprocess) shouldn't pay for unless
# they actually touch the remote plane.
_SERVICE_EXPORTS = (
    "EnvServiceError",
    "EnvSessionLostError",
    "EnvWorkerUnavailableError",
    "RemoteEnv",
    "RemoteToolEnv",
    "ToolEnvAdapter",
    "serve_env",
)

__all__ = ["MathCodeSingleStepEnv", *_SERVICE_EXPORTS]


def __getattr__(name):
    if name in _SERVICE_EXPORTS:
        from areal_tpu.env import service

        return getattr(service, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
