"""Environment implementations for agentic workflows."""

from areal_tpu.env.math_code_env import MathCodeSingleStepEnv  # noqa: F401
