"""Countdown arithmetic-game environment with a calculator tool.

Role of reference examples/countdown/train.py + examples/countdown/
countdown_utils (the runnable agentic workload: given a list of numbers and
a target, produce an arithmetic expression using each number at most once
that evaluates to the target; binary verifiable reward with format credit).
Here the game is exposed the TPU-framework way: as a *tool-calling* episode
— the agent calls ``eval_expression`` through the OpenAI-compatible client
(api/openai_client.py), sees the computed value as a tool message, and
submits via ``submit_expression``; the reward comes from the environment,
not from parsing free text.

Expression evaluation is AST-based (no ``eval``): only numeric literals,
+ - * /, unary minus, and parentheses are admitted, so model-authored
expressions cannot execute code.
"""

import ast
import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple


def safe_eval_arithmetic(expr: str) -> float:
    """Evaluate an arithmetic expression via the AST; raises ValueError on
    anything but numbers, + - * /, unary +/- and parentheses."""
    try:
        tree = ast.parse(expr, mode="eval")
    except SyntaxError as e:
        raise ValueError(f"unparsable expression: {e}") from None

    def ev(node: ast.AST) -> float:
        if isinstance(node, ast.Expression):
            return ev(node.body)
        if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)
        ):
            return float(node.value)
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Add, ast.Sub, ast.Mult, ast.Div)
        ):
            a, b = ev(node.left), ev(node.right)
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Sub):
                return a - b
            if isinstance(node.op, ast.Mult):
                return a * b
            if b == 0:
                raise ValueError("division by zero")
            return a / b
        if isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.UAdd, ast.USub)
        ):
            v = ev(node.operand)
            return v if isinstance(node.op, ast.UAdd) else -v
        raise ValueError(f"disallowed syntax: {ast.dump(node)[:60]}")

    return ev(tree)


def expression_numbers(expr: str) -> List[float]:
    """All numeric literals in the expression (multiset, for the
    use-each-number-at-most-once rule)."""
    tree = ast.parse(expr, mode="eval")
    return [
        float(n.value)
        for n in ast.walk(tree)
        if isinstance(n, ast.Constant) and isinstance(n.value, (int, float))
    ]


def countdown_score(
    expr: str, numbers: List[int], target: float
) -> Tuple[float, str]:
    """(reward, explanation). 1.0 = valid numbers and exact target;
    0.1 = evaluates but wrong/illegal numbers (format credit, the
    reference's rank-style partial credit); 0.0 = not evaluable."""
    try:
        value = safe_eval_arithmetic(expr)
        used = expression_numbers(expr)
    except ValueError as e:
        return 0.0, str(e)
    pool = list(numbers)
    for u in used:
        if u in pool:
            pool.remove(u)
        else:
            return 0.1, f"number {u:g} not available (pool {numbers})"
    if abs(value - target) < 1e-6:
        return 1.0, "correct"
    return 0.1, f"evaluates to {value:g}, target {target:g}"


TOOL_SCHEMAS: List[Dict[str, Any]] = [
    {
        "type": "function",
        "function": {
            "name": "eval_expression",
            "description": (
                "Evaluate an arithmetic expression (numbers, + - * /, "
                "parentheses) and return its value."
            ),
            "parameters": {
                "type": "object",
                "properties": {
                    "expression": {"type": "string"},
                },
                "required": ["expression"],
            },
        },
    },
    {
        "type": "function",
        "function": {
            "name": "submit_expression",
            "description": (
                "Submit the final expression that reaches the target. Ends "
                "the episode."
            ),
            "parameters": {
                "type": "object",
                "properties": {
                    "expression": {"type": "string"},
                },
                "required": ["expression"],
            },
        },
    },
]


@dataclasses.dataclass
class CountdownEnv:
    """One countdown instance; tools are executed via :meth:`call`."""

    numbers: List[int]
    target: int
    submitted: Optional[str] = None
    reward: float = 0.0
    detail: str = "no submission"

    @property
    def tools(self) -> List[Dict[str, Any]]:
        return TOOL_SCHEMAS

    def prompt(self) -> str:
        return (
            f"Using the numbers {self.numbers} (each at most once) and the "
            f"operations + - * /, build an expression equal to "
            f"{self.target}. You can check intermediate values with the "
            "eval_expression tool; finish with submit_expression."
        )

    @property
    def done(self) -> bool:
        return self.submitted is not None

    def call(self, name: str, arguments: str) -> str:
        """Execute one parsed tool call; returns the tool-message content."""
        try:
            args = json.loads(arguments) if arguments else {}
        except ValueError:
            return "error: arguments are not valid JSON"
        expr = str(args.get("expression", ""))
        if name == "eval_expression":
            try:
                return f"{safe_eval_arithmetic(expr):g}"
            except ValueError as e:
                return f"error: {e}"
        if name == "submit_expression":
            self.submitted = expr
            self.reward, self.detail = countdown_score(
                expr, self.numbers, self.target
            )
            return f"submitted ({self.detail})"
        return f"error: unknown tool {name!r}"


def sample_instance(rng) -> "CountdownEnv":
    """Solvable instance: compose the target from a random subset so a
    perfect policy can always score 1.0."""
    n = int(rng.integers(3, 5))
    numbers = [int(rng.integers(1, 20)) for _ in range(n)]
    target = numbers[0]
    for x in numbers[1:]:
        op = int(rng.integers(0, 3))
        if op == 0:
            target = target + x
        elif op == 1:
            target = target - x
        else:
            target = target * x
    return CountdownEnv(numbers=numbers, target=int(target))
