"""Single-step verifiable-reward environment (math and code).

Role of reference realhf/impl/environment/math_code_single_step_env.py:
the Env the legacy agents step once per episode — the action is the
model's full completion; the reward is the verifiable score (math answer
equivalence or code execution), and the episode is done.

Query metadata decides the verifier per reset:
  {"task": "math", "answer": "..."}         → reward/math_parser
  {"task": "code", "tests": [...], ...}     → reward/code_verifier

``verifier_addrs`` (or env AREAL_TPU_VERIFIER_ADDRS, comma-separated)
routes verification to a remote pool (reward/verifier_service — the
reference's FUNCTIONCALL_SERVICE_DOMAIN mode, functioncall/base/call.py:21)
so interpreters never run on the trainer host. In remote mode an
unreachable pool raises ``VerifierUnavailableError`` out of ``astep`` —
the executor's episode retry/quarantine machinery owns it; a fabricated
0.0 reward would silently poison training.
"""

import asyncio
import contextvars
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional, Sequence, Tuple

from areal_tpu.api.env_api import Env

# dedicated executor for REMOTE verification waits: asyncio's default
# executor caps at ~32 threads, which would bottleneck a large verifier
# pool (each wait just blocks on HTTP, so threads are cheap)
_REMOTE_POOL = ThreadPoolExecutor(max_workers=128, thread_name_prefix="verif")


class MathCodeSingleStepEnv(Env):
    # one pure verification step per episode: replaying (reset kwargs,
    # the single action) on another worker reproduces the same reward,
    # so a dead env worker can resume this env's sessions
    replay_safe = True

    def __init__(
        self,
        timeout_s: float = 15.0,
        verifier_addrs: Optional[Sequence[str]] = None,
    ):
        self.timeout_s = timeout_s
        self._query: Dict[str, Any] = {}
        addrs = verifier_addrs or [
            a
            for a in os.environ.get("AREAL_TPU_VERIFIER_ADDRS", "").split(",")
            if a
        ]
        self._remote = None
        if addrs:
            from areal_tpu.reward.verifier_service import RemoteVerifier

            # explicit remote mode: NEVER run interpreters on this host.
            # A dead pool raises VerifierUnavailableError into episode
            # retry/quarantine — not a silent 0.0 score
            self._remote = RemoteVerifier(addrs, local_fallback=False)

    async def areset(self, **kwargs) -> Any:
        """kwargs = the query metadata (task, answer/tests, prompt...)."""
        self._query = dict(kwargs)
        return self._query.get("prompt", "")

    async def astep(
        self, action: Any
    ) -> Tuple[Any, float, bool, Dict[str, Any]]:
        completion = str(action)
        task = self._query.get("task", "math")
        loop = asyncio.get_running_loop()
        if self._remote is not None:
            item = (
                {
                    "kind": "math",
                    "completion": completion,
                    "answer": str(self._query.get("answer", "")),
                }
                if task != "code"
                else {
                    "kind": "code",
                    "completion": completion,
                    "test_cases": self._query.get("test_cases"),
                    "test_code": self._query.get("test_code"),
                    "timeout": self.timeout_s,
                }
            )
            # carry the episode-lineage contextvar into the worker thread
            # (run_in_executor does not propagate context): the verifier
            # client reads it for X-Areal-Trace header propagation
            ctx = contextvars.copy_context()
            reward = await loop.run_in_executor(
                _REMOTE_POOL, ctx.run, lambda: self._remote.verify(item)
            )
            return None, float(reward), True, {"task": task}
        if task == "code":
            from areal_tpu.reward.code_verifier import code_reward_fn

            reward = await loop.run_in_executor(
                None,
                lambda: code_reward_fn(
                    self._query.get("prompt", ""),
                    completion,
                    None,
                    None,
                    test_cases=self._query.get("test_cases"),
                    test_code=self._query.get("test_code"),
                    timeout=self.timeout_s,
                ),
            )
        else:
            from areal_tpu.reward.math_parser import process_results

            reward = await loop.run_in_executor(
                None,
                lambda: process_results(
                    completion, str(self._query.get("answer", ""))
                ),
            )
        return None, float(reward), True, {"task": task}
