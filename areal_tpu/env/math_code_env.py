"""Single-step verifiable-reward environment (math and code).

Role of reference realhf/impl/environment/math_code_single_step_env.py:
the Env the legacy agents step once per episode — the action is the
model's full completion; the reward is the verifiable score (math answer
equivalence or code execution), and the episode is done.

Query metadata decides the verifier per reset:
  {"task": "math", "answer": "..."}         → reward/math_parser
  {"task": "code", "tests": [...], ...}     → reward/code_verifier
"""

import asyncio
from typing import Any, Dict, Tuple

from areal_tpu.api.env_api import Env


class MathCodeSingleStepEnv(Env):
    def __init__(self, timeout_s: float = 15.0):
        self.timeout_s = timeout_s
        self._query: Dict[str, Any] = {}

    async def areset(self, **kwargs) -> Any:
        """kwargs = the query metadata (task, answer/tests, prompt...)."""
        self._query = dict(kwargs)
        return self._query.get("prompt", "")

    async def astep(
        self, action: Any
    ) -> Tuple[Any, float, bool, Dict[str, Any]]:
        completion = str(action)
        task = self._query.get("task", "math")
        loop = asyncio.get_running_loop()
        if task == "code":
            from areal_tpu.reward.code_verifier import code_reward_fn

            reward = await loop.run_in_executor(
                None,
                lambda: code_reward_fn(
                    self._query.get("prompt", ""),
                    completion,
                    None,
                    None,
                    test_cases=self._query.get("test_cases"),
                    test_code=self._query.get("test_code"),
                    timeout=self.timeout_s,
                ),
            )
        else:
            from areal_tpu.reward.math_parser import process_results

            reward = await loop.run_in_executor(
                None,
                lambda: process_results(
                    completion, str(self._query.get("answer", ""))
                ),
            )
        return None, float(reward), True, {"task": task}
