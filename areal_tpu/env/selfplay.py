"""Proposer side of the countdown self-play pair.

The solver side already exists (env/countdown.py: a tool-calling episode
graded by ``countdown_score``). This module adds the other half of the
first self-play workload (ROADMAP item 4): a **proposer** environment in
which the model AUTHORS a countdown instance — a numbers/target pair —
through a grader-validated schema, and the validated instance is then
handed to the solver's episode by the self-play workflow
(workflow/selfplay.py).

Grader-family validation (the style of reward/grader.py): every rejected
proposal names a FAMILY (``count``/``range``/``integer``/``target``/
``unsolvable``/``parse``) so tests pin agreement vectors per family and
the metrics plane can count invalid proposals without string-matching
free text.

Everything here is a pure function of the call log — no RNG, no clock —
so ``ProposerEnv`` is ``replay_safe`` under the env service's journaled
replay (ARCHITECTURE.md §13): a worker death mid-episode replays to a
bit-identical state.

Instance text formats (the toy tokenizer has no JSON punctuation, so the
compact form is first-class, not a fallback):

- compact: ``"3 5 2 = 21"`` — whitespace-separated numbers, ``=``, target
- JSON:    ``{"numbers": [3, 5, 2], "target": 21}``
"""

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

# Bounds mirror the solver generator (countdown.sample_instance): numbers
# in 1..19, 3-4 of them. The proposer is graded against the same contract
# the solver was trained on.
NUMBER_MIN = 1
NUMBER_MAX = 19
DEFAULT_MIN_NUMBERS = 3
DEFAULT_MAX_NUMBERS = 4
DEFAULT_MAX_TARGET = 1000


def parse_instance(text: str) -> Tuple[List[int], int]:
    """Parse an instance from either accepted format; raises ValueError
    (family ``parse``) on anything else. Numbers/target must be integers
    — the countdown pool is integer by contract."""
    text = text.strip()
    if not text:
        raise ValueError("empty instance")
    if text.startswith("{"):
        try:
            obj = json.loads(text)
        except ValueError as e:
            raise ValueError(f"bad JSON: {e}") from None
        if not isinstance(obj, dict):
            raise ValueError("JSON instance must be an object")
        numbers, target = obj.get("numbers"), obj.get("target")
        if not isinstance(numbers, list):
            raise ValueError("JSON instance needs a 'numbers' list")
        if isinstance(target, bool) or not isinstance(target, (int, float)):
            raise ValueError("JSON instance needs a numeric 'target'")
    else:
        left, sep, right = text.partition("=")
        if not sep:
            raise ValueError(
                "compact instance must look like '3 5 2 = 21'"
            )
        numbers = left.split()
        target = right.strip()
        if not numbers or not target:
            raise ValueError("compact instance missing numbers or target")

    def _as_int(v: Any, what: str) -> int:
        if isinstance(v, bool):
            raise ValueError(f"{what} must be an integer, got {v!r}")
        try:
            f = float(v)
        except (TypeError, ValueError):
            raise ValueError(
                f"{what} must be an integer, got {v!r}"
            ) from None
        if f != int(f):
            raise ValueError(f"{what} must be an integer, got {v!r}")
        return int(f)

    nums = [_as_int(n, "number") for n in numbers]
    return nums, _as_int(target, "target")


def instance_solvable(numbers: List[int], target: int) -> bool:
    """Whether the target is reachable with + - * / using each number at
    most once (subsets allowed — the solver's scoring rule). Exhaustive
    pairwise-combine search; fine for the contract's <= 4 numbers."""
    tol = 1e-6

    def rec(vals: List[float]) -> bool:
        if any(abs(v - target) < tol for v in vals):
            return True
        n = len(vals)
        for i in range(n):
            for j in range(n):
                if i == j:
                    continue
                a, b = vals[i], vals[j]
                rest = [vals[k] for k in range(n) if k not in (i, j)]
                cands = [a + b, a - b, a * b]
                if abs(b) > tol:
                    cands.append(a / b)
                for c in cands:
                    if rec(rest + [c]):
                        return True
        return False

    return rec([float(x) for x in numbers])


def validate_instance(
    numbers: List[int],
    target: int,
    min_numbers: int = DEFAULT_MIN_NUMBERS,
    max_numbers: int = DEFAULT_MAX_NUMBERS,
    max_target: int = DEFAULT_MAX_TARGET,
    require_solvable: bool = True,
) -> Tuple[bool, str, str]:
    """(ok, family, detail). Families: ``count`` (wrong number count),
    ``range`` (a number outside [NUMBER_MIN, NUMBER_MAX]), ``target``
    (|target| above max_target), ``unsolvable`` (no expression reaches
    the target), ``ok``."""
    if not (min_numbers <= len(numbers) <= max_numbers):
        return (
            False,
            "count",
            f"need {min_numbers}-{max_numbers} numbers, got {len(numbers)}",
        )
    for n in numbers:
        if not (NUMBER_MIN <= n <= NUMBER_MAX):
            return (
                False,
                "range",
                f"number {n} outside [{NUMBER_MIN}, {NUMBER_MAX}]",
            )
    if abs(target) > max_target:
        return False, "target", f"|{target}| exceeds {max_target}"
    if require_solvable and not instance_solvable(numbers, target):
        return (
            False,
            "unsolvable",
            f"no expression over {numbers} reaches {target}",
        )
    return True, "ok", "valid instance"


def difficulty_band(numbers: List[int], target: int) -> int:
    """Deterministic difficulty band 0..3 of a VALID instance — the
    proposer's graded outcome. Pure arithmetic of the instance (no RNG,
    no solver rollout) so banding is bit-stable under replay: more
    numbers and larger/negative targets mean more combination depth."""
    band = 0
    if len(numbers) >= 4:
        band += 1
    if abs(target) > 50:
        band += 1
    if abs(target) > 200 or target < 0:
        band += 1
    return min(band, 3)


def proposer_reward(
    valid: bool,
    band: int,
    solver_reward: float,
    mode: str = "banded",
) -> float:
    """Map a proposal's outcome to the proposer's scalar reward.

    - ``banded``: invalid -> 0.0; valid -> (1 + band) / 4 in {0.25, 0.5,
      0.75, 1.0} — harder (higher-band) instances earn more, independent
      of the solver's luck.
    - ``zero_sum``: invalid -> 0.0; valid -> 1.0 - solver_reward — the
      adversarial mapping (proposer wins what the solver loses).
    """
    if not valid:
        return 0.0
    if mode == "banded":
        return (1.0 + min(max(int(band), 0), 3)) / 4.0
    if mode == "zero_sum":
        return 1.0 - float(solver_reward)
    raise ValueError(f"unknown proposer reward mode {mode!r}")


PROPOSER_TOOL_SCHEMAS: List[Dict[str, Any]] = [
    {
        "type": "function",
        "function": {
            "name": "check_instance",
            "description": (
                "Validate a candidate countdown instance without "
                "committing it; returns the grader verdict and the "
                "difficulty band."
            ),
            "parameters": {
                "type": "object",
                "properties": {"instance": {"type": "string"}},
                "required": ["instance"],
            },
        },
    },
    {
        "type": "function",
        "function": {
            "name": "propose_instance",
            "description": (
                "Commit the final countdown instance ('3 5 2 = 21' or "
                "JSON {numbers, target}). A valid instance ends the "
                "episode; an invalid one is rejected with the reason."
            ),
            "parameters": {
                "type": "object",
                "properties": {"instance": {"type": "string"}},
                "required": ["instance"],
            },
        },
    },
]


@dataclasses.dataclass
class ProposerEnv:
    """Tool-style env (the protocol AgenticToolWorkflow speaks) in which
    the model proposes one countdown instance. The episode ends when a
    valid instance is committed, or after ``max_attempts`` invalid
    ``propose_instance`` calls (deterministic budget — the env, not the
    workflow, owns episode termination so replay needs no client state).

    The committed instance travels in the FINAL OBSERVATION as JSON
    (``accepted {"numbers": ..., "target": ..., "band": ...}``): under
    the env service's journaled replay the observation is the one channel
    that is bit-reproduced, so the workflow parses the instance from
    there rather than from private env attributes."""

    min_numbers: int = DEFAULT_MIN_NUMBERS
    max_numbers: int = DEFAULT_MAX_NUMBERS
    max_target: int = DEFAULT_MAX_TARGET
    require_solvable: bool = True
    max_attempts: int = 3
    attempts: int = 0
    instance: Optional[Tuple[List[int], int]] = None
    band: int = 0
    reward: float = 0.0
    detail: str = "no proposal"
    done: bool = False
    info: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def tools(self) -> List[Dict[str, Any]]:
        return PROPOSER_TOOL_SCHEMAS

    def prompt(self) -> str:
        return (
            f"Propose a countdown instance: {self.min_numbers}-"
            f"{self.max_numbers} numbers in [{NUMBER_MIN}, {NUMBER_MAX}] "
            f"and an integer target (|target| <= {self.max_target}) "
            "reachable from them with + - * / using each number at most "
            "once. Harder instances score higher. Check candidates with "
            "check_instance; commit with propose_instance as "
            "'3 5 2 = 21'."
        )

    def _grade(self, text: str) -> Tuple[bool, str, str, Any]:
        try:
            numbers, target = parse_instance(text)
        except ValueError as e:
            return False, "parse", str(e), None
        ok, family, detail = validate_instance(
            numbers,
            target,
            min_numbers=self.min_numbers,
            max_numbers=self.max_numbers,
            max_target=self.max_target,
            require_solvable=self.require_solvable,
        )
        return ok, family, detail, (numbers, target)

    def call(self, name: str, arguments: str) -> str:
        try:
            args = json.loads(arguments) if arguments else {}
        except ValueError:
            return "error: arguments are not valid JSON"
        text = str(args.get("instance", ""))
        if name == "check_instance":
            ok, family, detail, inst = self._grade(text)
            if ok:
                numbers, target = inst
                return f"valid (band {difficulty_band(numbers, target)})"
            return f"invalid [{family}]: {detail}"
        if name == "propose_instance":
            ok, family, detail, inst = self._grade(text)
            if ok:
                numbers, target = inst
                self.instance = (numbers, target)
                self.band = difficulty_band(numbers, target)
                self.reward = 1.0
                self.detail = f"accepted (band {self.band})"
                self.done = True
                self.info = {
                    "selfplay": {"valid": True, "band": self.band}
                }
                return "accepted " + json.dumps(
                    {
                        "numbers": numbers,
                        "target": target,
                        "band": self.band,
                    }
                )
            self.attempts += 1
            if self.attempts >= self.max_attempts:
                self.reward = 0.0
                self.detail = f"rejected [{family}]: {detail}"
                self.done = True
                self.info = {
                    "selfplay": {"valid": False, "band": -1}
                }
            return f"rejected [{family}]: {detail}"
        return f"error: unknown tool {name!r}"


def build_side_env(kwargs: Dict[str, Any]):
    """One factory for BOTH sides of a countdown self-play episode,
    keyed by ``side``: the self-play workflow (and the env service's
    ``selfplay_env`` hosting factory) opens a proposer session and later
    a solver session carrying the accepted instance — one code path
    whether the envs run in-process or behind the env service."""
    side = str(kwargs.get("side") or "solver")
    if side == "proposer":
        return ProposerEnv(
            min_numbers=int(kwargs.get("min_numbers", DEFAULT_MIN_NUMBERS)),
            max_numbers=int(kwargs.get("max_numbers", DEFAULT_MAX_NUMBERS)),
            max_target=int(kwargs.get("max_target", DEFAULT_MAX_TARGET)),
            require_solvable=bool(kwargs.get("require_solvable", True)),
            max_attempts=int(kwargs.get("max_attempts", 3)),
        )
    if side == "solver":
        from areal_tpu.env.countdown import CountdownEnv

        return CountdownEnv(
            numbers=[int(x) for x in kwargs["numbers"]],
            target=int(kwargs["target"]),
        )
    raise ValueError(f"unknown self-play side {side!r}")


_ACCEPTED_PREFIX = "accepted "


def parse_accepted_observation(
    text: str,
) -> Optional[Tuple[List[int], int, int]]:
    """(numbers, target, band) from a ``propose_instance`` acceptance
    observation, or None for any other tool output. The workflow's only
    way to read the committed instance — see ProposerEnv docstring."""
    text = text.strip()
    # the workflow may see the observation wrapped for a template-less
    # tokenizer: "propose_instance -> accepted {...}"
    idx = text.find(_ACCEPTED_PREFIX)
    if idx < 0:
        return None
    try:
        obj = json.loads(text[idx + len(_ACCEPTED_PREFIX):].split("\n")[0])
        numbers = [int(x) for x in obj["numbers"]]
        return numbers, int(obj["target"]), int(obj["band"])
    except (ValueError, KeyError, TypeError):
        return None
