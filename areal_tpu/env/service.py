"""Environment service plane: sessionful env workers + failover client.

ROADMAP open item 5 ("environment-as-a-service"): environments used to run
in-process with the rollout thread, so one hung or crashing tool call
stalled or killed an episode, and env-worker loss had no story at all.
This module gives env/reward execution the same independent-failure-domain
treatment the generation fleet got in PR 4 (ROLL Flash's agentic
asynchrony and Laminar's decoupled trajectory workers make the same
separation):

**Worker** (``serve_env`` / ``python -m areal_tpu.env.service``): a
threaded HTTP service hosting one :class:`areal_tpu.api.env_api.Env`
instance per session over the session protocol

    POST /reset  {"kwargs": {...}}          -> {"session", "observation",
                                                "replay_safe"}
    POST /step   {"session", "action"}      -> {"observation", "reward",
                                                "done", "info"}
    POST /close  {"session"}                -> {"closed"}
    GET  /health                            -> {"status": "ok"|"draining"}
    GET  /metrics (Prometheus)   GET /trace (span drain)
    POST /drain  (stop admitting; deregister when sessions empty)
    POST /chaos  (runtime fault injection, utils/chaos.py grammar)

Workers self-register under the name_resolve ``env_servers`` subtree, so
the same :class:`areal_tpu.inference.fleet.FleetMonitor` state machine
that probes generation servers health-probes and circuit-breaks env
workers (``env_fleet_monitor``), and ``/health`` draining is classified
out-of-rotation without opening a circuit.

**Client** (:class:`RemoteEnv`): implements the ``Env`` contract with
per-call timeouts and the ``utils/http`` retry policy (connect/timeout/
5xx-only retries, bounded-jitter backoff, client-side chaos hooks), and
**deterministic episode replay on worker death**: each session journals
``(reset_kwargs, [(action, observation, reward, done), ...])`` and, when
its worker goes DEAD mid-episode, replays the journal onto a healthy
worker to reconstruct the session — token-exactly for ``replay_safe``
envs (replayed observations are verified against the journal). Envs that
do NOT declare ``replay_safe`` raise :class:`EnvSessionLostError`
instead, which the workflow lets propagate so the executor's episode
retry/quarantine machinery (PR 6) owns the failure — the rollout thread
never hangs and never silently trains on divergent state.

:class:`RemoteToolEnv` adapts a remote session to the tool-env protocol
``AgenticToolWorkflow`` speaks (``tools`` / ``prompt()`` / ``acall()`` /
``done`` / ``reward``), and :class:`ToolEnvAdapter` is the server-side
inverse (hosts a tool env behind the gym contract), so the shipped
countdown game runs remote end-to-end (``countdown_env``).
"""

import asyncio
import contextlib

import aiohttp
import importlib
import json
import os
import threading
import time
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from areal_tpu.api.cli_args import EnvServiceConfig
from areal_tpu.api.env_api import (
    Env,
    EnvActionError,
    EnvServiceError,
    EnvSessionLostError,
    EnvWorkerUnavailableError,
)
from areal_tpu.utils import chaos, name_resolve, names, telemetry
from areal_tpu.utils import logging as logging_util
from areal_tpu.utils.http import HttpRequestError, arequest_with_retry
from areal_tpu.utils.tracing import (
    TRACE_HEADER,
    SpanTracer,
    TracingConfig,
    register_metric_types,
    render_prometheus,
    trace_headers,
    trace_response,
)

logger = logging_util.getLogger("EnvService")

# env var the launcher exports so trainer processes can find the workers
# without a shared name_resolve (comma-separated host:port)
ADDRS_ENV = "AREAL_ENV_SERVER_ADDRS"


# The typed error family lives in api/env_api.py (next to the Env
# contract, so workflows type-match without importing this HTTP stack);
# re-exported here for the service plane's callers.
__all__ = [
    "EnvActionError",
    "EnvServiceError",
    "EnvSessionLostError",
    "EnvWorkerUnavailableError",
    "RemoteEnv",
    "RemoteToolEnv",
    "ToolEnvAdapter",
    "serve_env",
]


def _is_infra_error(e: Exception) -> bool:
    """Whether an exception raised INSIDE a hosted env means "a backend
    this env depends on is down" rather than "the action was poison".
    Infra errors must answer 500 (worker-failure semantics → client
    failover → episode retry/quarantine when the whole plane is sick);
    mapping them to 422 would convert e.g. a dead verifier pool back
    into error-observation rows — the silent poisoning this PR removes."""
    from areal_tpu.api.reward_api import RewardTimeoutError
    from areal_tpu.reward.verifier_service import VerifierUnavailableError

    return isinstance(
        e, (EnvServiceError, VerifierUnavailableError, RewardTimeoutError)
    )


# ---------------------------------------------------------------------------
# Hosted-env resolution
# ---------------------------------------------------------------------------
def resolve_env_factory(spec: str) -> Callable[[], Env]:
    """``module:attr`` -> zero-arg factory producing one Env per session.
    ``attr`` may already be such a factory (or an Env subclass)."""
    mod, _, attr = spec.partition(":")
    if not mod or not attr:
        raise ValueError(
            f"env spec {spec!r} must look like 'package.module:attr'"
        )
    obj = getattr(importlib.import_module(mod), attr)
    if not callable(obj):
        raise TypeError(f"env spec {spec!r} resolved to non-callable {obj!r}")
    return obj


class ToolEnvAdapter(Env):
    """Host a tool-style env (``tools``/``prompt()``/``call()``/``done``/
    ``reward`` — the protocol AgenticToolWorkflow speaks) behind the gym
    Env contract so the service can serve it sessionfully. The reset
    observation carries the prompt and tool schemas; an action is one
    parsed tool call ``{"name", "arguments"}``; reward is delivered when
    the tool env reports done.

    ``replay_safe`` is the FACTORY AUTHOR'S promise about the wrapped
    env (the adapter cannot know): default True fits pure state machines
    of their call log (the shipped countdown); wrap a tool env with
    hidden nondeterminism (web lookups, unseeded randomness) with
    ``replay_safe=False`` so worker death quarantines instead of
    silently resuming a divergent trajectory."""

    def __init__(
        self,
        factory: Callable[[Dict[str, Any]], Any],
        replay_safe: bool = True,
    ):
        self._factory = factory
        self._env = None
        self.replay_safe = replay_safe

    async def areset(self, **kwargs) -> Any:
        self._env = self._factory(dict(kwargs))
        return {"prompt": self._env.prompt(), "tools": self._env.tools}

    async def astep(
        self, action: Any
    ) -> Tuple[Any, float, bool, Dict[str, Any]]:
        name = str(action.get("name", ""))
        arguments = action.get("arguments", "")
        # tool call() is sync and possibly slow (sandboxes, subprocesses):
        # run it on the executor so one hung tool cannot wedge the
        # worker's shared env loop — every other session keeps stepping
        result = await asyncio.get_running_loop().run_in_executor(
            None, self._env.call, name, arguments
        )
        done = bool(self._env.done)
        reward = float(getattr(self._env, "reward", 0.0)) if done else 0.0
        # structured env-authored info (e.g. the self-play proposer's
        # {"selfplay": {...}} grading summary) rides alongside the
        # canonical detail string; "detail" stays adapter-owned
        extra = getattr(self._env, "info", None)
        info = dict(extra) if isinstance(extra, dict) else {}
        info["detail"] = str(getattr(self._env, "detail", ""))
        return result, reward, done, info

    async def aclose(self):
        self._env = None


def countdown_env() -> ToolEnvAdapter:
    """Factory for serving the countdown game (env/countdown.py) as a
    remote tool env: reset kwargs are {"numbers": [...], "target": n}."""
    from areal_tpu.env.countdown import CountdownEnv

    return ToolEnvAdapter(
        lambda kw: CountdownEnv(
            numbers=[int(x) for x in kw["numbers"]], target=int(kw["target"])
        )
    )


def proposer_env() -> ToolEnvAdapter:
    """Factory for serving the countdown PROPOSER side (env/selfplay.py)
    as a remote tool env; reset kwargs override the instance-schema
    bounds ({"min_numbers", "max_numbers", "max_target", ...})."""
    from areal_tpu.env import selfplay

    return ToolEnvAdapter(
        lambda kw: selfplay.build_side_env({**kw, "side": "proposer"})
    )


def selfplay_env() -> ToolEnvAdapter:
    """Factory for serving BOTH sides of a countdown self-play episode
    from one worker pool, keyed by the reset kwarg ``side``:
    "proposer" -> ProposerEnv (schema bounds from the other kwargs),
    "solver" -> CountdownEnv ({"numbers", "target"}). One pool serving
    both sessions keeps the episode's replay journals co-located."""
    from areal_tpu.env import selfplay

    return ToolEnvAdapter(selfplay.build_side_env)


def math_code_env() -> Env:
    """Factory for serving the single-step verifiable-reward env."""
    from areal_tpu.env.math_code_env import MathCodeSingleStepEnv

    return MathCodeSingleStepEnv()


# ---------------------------------------------------------------------------
# Worker (server side)
# ---------------------------------------------------------------------------
class _Session:
    __slots__ = (
        "sid", "env", "lock", "steps", "created", "last_active",
        "last_action", "last_response",
    )

    def __init__(self, sid: str, env: Env, t: float):
        self.sid = sid
        self.env = env
        # steps within one session are serialized (envs are stateful);
        # different sessions run concurrently on the handler threads
        self.lock = threading.Lock()
        self.steps = 0
        self.created = t
        self.last_active = t
        # idempotency cache for the LAST applied step: a client whose
        # response was lost in flight re-POSTs (seq, action) and gets the
        # cached answer back instead of double-applying the action
        self.last_action: Any = None
        self.last_response: Optional[Dict[str, Any]] = None


class EnvWorkerState:
    """Everything the handler shares: the env factory, live sessions, a
    dedicated asyncio loop thread the Env coroutines run on, counters,
    drain mode, and the name_resolve registration to tear down."""

    def __init__(
        self,
        factory: Callable[[], Env],
        max_sessions: int = 512,
        tracer: Optional[SpanTracer] = None,
        session_ttl_s: float = 3600.0,
    ):
        self.factory = factory
        self.max_sessions = max_sessions
        self.session_ttl_s = session_ttl_s
        # `is not None`, not truthiness: SpanTracer defines __len__, so
        # a fresh (empty) tracer is falsy and `or` would discard it
        self.tracer = (
            tracer if tracer is not None
            else SpanTracer(TracingConfig(enabled=False))
        )
        self.sessions: Dict[str, _Session] = {}
        # resets reserved but not yet inserted (counts against capacity
        # and keeps _watch_drain honest about in-flight sessions)
        self.pending_resets = 0
        self.lock = threading.Lock()
        self.draining = threading.Event()
        self.registration_key: Optional[str] = None
        self.counters = {
            "resets_total": 0.0,
            "steps_total": 0.0,
            "closes_total": 0.0,
            "errors_total": 0.0,
            "rejected_draining_total": 0.0,
            "rejected_capacity_total": 0.0,
            "sessions_expired_total": 0.0,
        }
        # step-latency EWMA (seconds) — the per-worker health signal the
        # telemetry hub can scrape without draining traces
        self.step_latency_ewma_s = 0.0
        # env coroutines run on ONE loop thread (handler threads submit
        # via run_coroutine_threadsafe): envs may hold loop-bound state
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, daemon=True, name="env-loop"
        )
        self._loop_thread.start()
        self._drain_watcher: Optional[threading.Thread] = None
        # idle-session TTL sweeper: crashed clients, failed best-effort
        # closes, and abandoned replays leak sessions; without a GC they
        # ratchet sessions_active up to max_sessions (every reset 429s)
        # and a drain never completes. TTL <= 0 disables (tests).
        if session_ttl_s > 0:
            threading.Thread(
                target=self._sweep_expired, daemon=True, name="env-ttl"
            ).start()

    def _sweep_expired(self) -> None:
        interval = max(0.05, self.session_ttl_s / 4.0)
        while True:
            time.sleep(interval)
            now = time.monotonic()
            with self.lock:
                expired = [
                    (sid, s) for sid, s in self.sessions.items()
                    if now - s.last_active > self.session_ttl_s
                ]
                for sid, _ in expired:
                    self.sessions.pop(sid, None)
            for sid, sess in expired:
                logger.warning(
                    f"session {sid} expired after "
                    f"{self.session_ttl_s:.0f}s idle (client gone?)"
                )
                with sess.lock:
                    try:
                        self.run(sess.env.aclose(), timeout=30)
                    except Exception as e:
                        logger.warning(f"expired aclose {sid}: {e}")
                self.bump("sessions_expired_total")
                if self.tracer.enabled:
                    self.tracer.unbind_trace(sid)

    def run(self, coro, timeout: Optional[float] = None):
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(
            timeout
        )

    def bump(self, key: str, n: float = 1.0) -> None:
        with self.lock:
            self.counters[key] = self.counters.get(key, 0.0) + n

    def metrics(self) -> Dict[str, float]:
        with self.lock:
            out = dict(self.counters)
            out["sessions_active"] = float(len(self.sessions))
            out["draining"] = float(self.draining.is_set())
            out["step_latency_ewma_s"] = self.step_latency_ewma_s
        t = self.tracer
        if t.enabled:
            out["trace_spans"] = float(len(t))
            out["tracing_dropped_spans_total"] = float(t.dropped)
        return out

    def deregister(self) -> None:
        key, self.registration_key = self.registration_key, None
        if key is None:
            return
        try:
            name_resolve.delete(key)
            logger.info(f"env worker deregistered {key}")
        except Exception as e:
            logger.warning(f"env worker deregister failed: {e}")

    def start_drain(self) -> int:
        """Enter drain mode; in-flight sessions may step to completion,
        new /reset calls get 503. Returns the live-session count."""
        self.draining.set()
        with self.lock:
            n = len(self.sessions)
        if self._drain_watcher is None or not self._drain_watcher.is_alive():
            self._drain_watcher = threading.Thread(
                target=self._watch_drain, daemon=True
            )
            self._drain_watcher.start()
        return n

    def _watch_drain(self) -> None:
        while True:
            with self.lock:
                if not self.sessions and self.pending_resets == 0:
                    break
            time.sleep(0.2)
        self.deregister()
        logger.info("env drain complete: no live sessions, deregistered")


_METRIC_HELP = {
    "sessions_active": "env sessions currently live on this worker",
    "resets_total": "sessions created (POST /reset)",
    "steps_total": "env steps executed (POST /step)",
    "closes_total": "sessions closed (POST /close)",
    "errors_total": "env calls that raised (answered 500)",
    "rejected_draining_total": "resets refused while draining (503)",
    "rejected_capacity_total": "resets refused at max_sessions (429)",
    "sessions_expired_total": "idle sessions reaped by the TTL sweeper",
    "selfplay_proposals_total": (
        "self-play proposer instances graded (settled propose_instance "
        "calls)"
    ),
    "selfplay_valid_proposals_total": (
        "proposals the instance grader accepted"
    ),
    "selfplay_invalid_proposals_total": (
        "proposals rejected by the instance grader (episode budget "
        "exhausted)"
    ),
    "draining": "1 while this worker is draining",
    "step_latency_ewma_s": "EWMA of env step execution latency",
    "trace_spans": "spans currently buffered (drained by GET /trace)",
    "tracing_dropped_spans_total": (
        "spans lost to ring-buffer overflow (the trace is truncated)"
    ),
}
register_metric_types(
    {
        n: ("counter" if n.endswith("_total") else "gauge")
        for n in _METRIC_HELP
    }
)


class _EnvHandler(BaseHTTPRequestHandler):
    state: EnvWorkerState = None  # set by serve_env()
    chaos_endpoint: bool = True  # CLI path gates behind --enable-chaos
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet
        pass

    # -- plumbing (inference/server.py idiom) ---------------------------
    def _send_json(self, obj, code: int = 200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, body: bytes, content_type: str):
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        if length == 0:
            return {}
        return json.loads(self.rfile.read(length))

    def _apply_chaos(self) -> bool:
        """Server-side chaos (shared dispatch, utils/chaos.py): how the
        chaos test makes an env worker die mid-episode, deterministically."""
        return chaos.apply_server_chaos(self, self._send_json)

    # -- endpoints ------------------------------------------------------
    def do_GET(self):
        if self._apply_chaos():
            return
        st = self.state
        url = urllib.parse.urlparse(self.path)
        if url.path == "/health":
            self._send_json(
                {"status": "draining" if st.draining.is_set() else "ok"}
            )
        elif url.path == "/metrics":
            body = render_prometheus(
                st.metrics(), prefix="areal_tpu_env_",
                help_text=_METRIC_HELP,
            ).encode()
            self._send_text(body, "text/plain; version=0.0.4")
        elif url.path == "/trace":
            body, ctype = trace_response(st.tracer, url.query)
            self._send_text(body, ctype)
        else:
            self._send_json({"error": f"unknown path {self.path}"}, 404)

    def do_POST(self):
        if self._apply_chaos():
            return
        st = self.state
        try:
            payload = self._read_json()
        except json.JSONDecodeError:
            self._send_json({"error": "bad json"}, 400)
            return
        try:
            if self.path == "/reset":
                self._do_reset(payload)
            elif self.path == "/step":
                self._do_step(payload)
            elif self.path == "/close":
                self._do_close(payload)
            elif self.path == "/drain":
                n = st.start_drain()
                self._send_json({"status": "draining", "sessions": n})
            elif self.path == "/chaos":
                if not self.chaos_endpoint:
                    self._send_json(
                        {"error": "chaos endpoint disabled "
                         "(start the worker with --enable-chaos)"}, 403
                    )
                    return
                inj = chaos.configure(payload.get("spec") or None)
                self._send_json({
                    "success": True,
                    "rules": inj.stats() if inj else [],
                })
            else:
                self._send_json({"error": f"unknown path {self.path}"}, 404)
        except Exception as e:  # env bugs become 500s, never worker death
            st.bump("errors_total")
            logger.error(f"{self.path} failed: {type(e).__name__}: {e}")
            self._send_json({"error": f"{type(e).__name__}: {e}"}, 500)

    def _bind_trace(self, sid: str) -> None:
        trace_id = self.headers.get(TRACE_HEADER)
        if trace_id and self.state.tracer.enabled:
            self.state.tracer.bind_trace(sid, trace_id)

    def _do_reset(self, payload: dict) -> None:
        st = self.state
        # admission is one atomic reservation: draining + capacity are
        # checked and the slot claimed under a single lock hold, so two
        # racing resets cannot overshoot max_sessions and a drain that
        # starts mid-reset cannot report complete (and deregister) while
        # this session is still materializing (_watch_drain also counts
        # pending_resets)
        with st.lock:
            if st.draining.is_set():
                reject: Optional[Tuple[dict, int]] = (
                    {"error": "draining"}, 503,
                )
            elif len(st.sessions) + st.pending_resets >= st.max_sessions:
                reject = ({"error": f"at max_sessions={st.max_sessions}"},
                          429)
            else:
                reject = None
                st.pending_resets += 1
        if reject is not None:
            st.bump(
                "rejected_draining_total" if reject[1] == 503
                else "rejected_capacity_total"
            )
            self._send_json(*reject)
            return
        try:
            kwargs = payload.get("kwargs") or {}
            env = st.factory()
            sid = uuid.uuid4().hex[:16]
            self._bind_trace(sid)
            try:
                with st.tracer.span("env_reset", sid):
                    obs = st.run(env.areset(**kwargs))
            except Exception as e:
                if _is_infra_error(e):
                    raise  # backend failure inside the env → 500
                # the ENV rejected the reset — infrastructure is fine,
                # the kwargs were poison: 422 is the client's "action
                # error" signal (episode-level error, never a failover)
                st.bump("errors_total")
                self._send_json(
                    {"error": f"{type(e).__name__}: {e}"}, 422
                )
                return
            sess = _Session(sid, env, time.monotonic())
            with st.lock:
                st.sessions[sid] = sess
        finally:
            with st.lock:
                st.pending_resets -= 1
        st.bump("resets_total")
        self._send_json({
            "session": sid,
            "observation": obs,
            "replay_safe": bool(getattr(env, "replay_safe", False)),
            "info": {},
        })

    def _do_step(self, payload: dict) -> None:
        st = self.state
        sid = str(payload.get("session", ""))
        with st.lock:
            sess = st.sessions.get(sid)
        if sess is None:
            # 404 is the session-loss signal: a client whose worker
            # restarted under it must replay, not blind-retry (4xx is
            # never retried by the http policy)
            self._send_json({"error": f"unknown session {sid!r}"}, 404)
            return
        action = payload.get("action")
        seq = payload.get("seq")
        t0 = time.monotonic()
        with sess.lock:
            # step idempotency: /step is a non-idempotent POST behind a
            # retrying client, so each step carries its journal index.
            # A retry of the step just applied (response lost in flight)
            # replays the cached answer; any other mismatch is a
            # journal/session desync and answers 409 — the client
            # treats it as session loss and rebuilds via replay,
            # keeping its journal the single source of truth.
            if seq is not None:
                seq = int(seq)
                if seq == sess.steps - 1:
                    if action == sess.last_action and (
                        sess.last_response is not None
                    ):
                        self._send_json(sess.last_response)
                        return
                    self._send_json(
                        {"error": f"seq {seq} was applied with a "
                         f"different action (session desynced)"}, 409
                    )
                    return
                if seq != sess.steps:
                    self._send_json(
                        {"error": f"seq {seq} != expected {sess.steps} "
                         f"(session desynced)"}, 409
                    )
                    return
            try:
                with st.tracer.span("env_step", sid, step=sess.steps):
                    obs, reward, done, info = st.run(
                        sess.env.astep(action)
                    )
            except Exception as e:
                if _is_infra_error(e):
                    raise  # backend failure inside the env → 500
                # env-raised ≠ worker-dead: 422 tells the client the
                # action was poison (error observation for the model),
                # where a 500 would read as infrastructure failure and
                # trigger a pointless replay storm across healthy
                # workers. Step count and cache are untouched — the
                # journal still matches the session.
                st.bump("errors_total")
                self._send_json(
                    {"error": f"{type(e).__name__}: {e}"}, 422
                )
                return
            resp = {
                "observation": obs,
                "reward": float(reward),
                "done": bool(done),
                "info": info or {},
            }
            sess.steps += 1
            sess.last_active = time.monotonic()
            sess.last_action = action
            sess.last_response = resp
        dt = time.monotonic() - t0
        with st.lock:
            st.step_latency_ewma_s = (
                dt if st.step_latency_ewma_s == 0.0
                else 0.9 * st.step_latency_ewma_s + 0.1 * dt
            )
        st.bump("steps_total")
        # self-play workload counters: the proposer env stamps a grading
        # summary into info when a proposal settles — counters only ever
        # appear on workers actually serving proposer sessions (strict
        # metric no-op for every other env)
        sp = info.get("selfplay") if isinstance(info, dict) else None
        if isinstance(sp, dict):
            st.bump("selfplay_proposals_total")
            st.bump(
                "selfplay_valid_proposals_total"
                if sp.get("valid")
                else "selfplay_invalid_proposals_total"
            )
        self._send_json(resp)

    def _do_close(self, payload: dict) -> None:
        st = self.state
        sid = str(payload.get("session", ""))
        with st.lock:
            sess = st.sessions.pop(sid, None)
        if sess is None:
            self._send_json({"closed": False})
            return
        with sess.lock:
            try:
                st.run(sess.env.aclose(), timeout=30)
            except Exception as e:
                logger.warning(f"aclose for {sid} failed: {e}")
        if st.tracer.enabled:
            st.tracer.unbind_trace(sid)
        st.bump("closes_total")
        self._send_json({"closed": True})


def serve_env(
    env_factory: Callable[[], Env],
    host: str = "127.0.0.1",
    port: int = 0,
    experiment_name: str = "",
    trial_name: str = "",
    max_sessions: int = 512,
    background: bool = False,
    tracer: Optional[SpanTracer] = None,
    chaos_endpoint: bool = True,
    session_ttl_s: float = 3600.0,
) -> ThreadingHTTPServer:
    """Start one env worker; returns the server (``server_address``
    carries the bound port, ``env_state`` the worker state). Registers
    under the name_resolve ``env_servers`` subtree when experiment/trial
    names are given, so FleetMonitor membership discovers it."""
    if tracer is None:
        tracer = SpanTracer(TracingConfig(enabled=True, max_spans=20_000))
    state = EnvWorkerState(
        env_factory, max_sessions, tracer, session_ttl_s=session_ttl_s
    )
    handler = type(
        "EnvHandler", (_EnvHandler,),
        {"state": state, "chaos_endpoint": chaos_endpoint},
    )
    # port 0 goes straight to the kernel (no find-then-bind TOCTOU);
    # server_address carries the assignment
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.daemon_threads = True
    port = httpd.server_address[1]
    if not tracer.service:
        tracer.service = f"env:{host}:{port}"
    httpd.env_state = state  # for tests/introspection
    if experiment_name and trial_name:
        state.registration_key = name_resolve.add_subentry(
            names.env_servers(experiment_name, trial_name),
            f"{host}:{port}",
        )
    logger.info(f"env worker listening on {host}:{port}")
    if background:
        threading.Thread(
            target=httpd.serve_forever, daemon=True, name="env-http"
        ).start()
    else:
        httpd.serve_forever()
    return httpd


# ---------------------------------------------------------------------------
# Fleet membership helpers
# ---------------------------------------------------------------------------
def discover_env_workers(
    experiment_name: str = "", trial_name: str = ""
) -> List[str]:
    """Worker addresses: the name_resolve env_servers subtree when
    experiment/trial are known, else the launcher's ADDRS_ENV export."""
    if experiment_name and trial_name:
        try:
            addrs = name_resolve.get_subtree(
                names.env_servers(experiment_name, trial_name)
            )
            if addrs:
                return sorted(addrs)
        except Exception as e:
            logger.warning(f"env worker discovery failed: {e}")
    return [a for a in os.environ.get(ADDRS_ENV, "").split(",") if a]


def env_fleet_monitor(
    config: EnvServiceConfig,
    addresses: Optional[Sequence[str]] = None,
    experiment_name: str = "",
    trial_name: str = "",
    **kwargs,
):
    """A FleetMonitor over the env-worker fleet: same state machine,
    circuit breaker, and drain classification as the generation fleet,
    watching the ``env_servers`` subtree for dynamic membership."""
    from areal_tpu.inference.fleet import FleetMonitor

    membership_key = None
    if experiment_name and trial_name:
        membership_key = names.env_servers(experiment_name, trial_name)
    seeded = list(addresses) if addresses else discover_env_workers(
        experiment_name, trial_name
    )
    return FleetMonitor(
        seeded,
        config=config.fleet,
        membership_key=membership_key,
        seed_source="seed" if addresses else "discovered",
        service="env",
        **kwargs,
    )


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------
class RemoteEnv(Env):
    """Env-contract client over the worker fleet, with journaled replay.

    One RemoteEnv is one session (one episode): ``areset`` opens it on a
    schedulable worker, ``astep`` drives it, ``aclose`` releases it (and
    the HTTP session). Worker death mid-episode is handled here: for
    ``replay_safe`` envs the journal is replayed onto a healthy worker
    (verified step-for-step when ``verify_replay``); otherwise
    :class:`EnvSessionLostError` propagates into episode retry/quarantine.
    ``replay_safe`` on this class mirrors what the WORKER declared at
    reset time, so journaling/replay policy follows the hosted env."""

    def __init__(
        self,
        addrs: Optional[Sequence[str]] = None,
        monitor=None,
        config: Optional[EnvServiceConfig] = None,
        tracer: Optional[SpanTracer] = None,
        rr_offset: int = 0,
        experiment_name: str = "",
        trial_name: str = "",
    ):
        self.config = config or EnvServiceConfig()
        self.monitor = monitor
        self._experiment_name = experiment_name
        self._trial_name = trial_name
        self._addrs = [a for a in (addrs or [])]
        # a discovered pool may be refreshed when it goes fully dark
        # (launcher-respawned workers re-register under new ports; an
        # explicit addr list or a monitor is the caller's to maintain)
        self._discovered = not self._addrs and monitor is None
        if not self._addrs and monitor is not None:
            self._addrs = monitor.addresses()
        if not self._addrs:
            self._addrs = discover_env_workers(experiment_name, trial_name)
        if not self._addrs:
            raise ValueError("RemoteEnv needs at least one worker address")
        self.tracer = tracer
        # starting index into the worker pool. One RemoteEnv = one
        # episode, so a fresh instance's default 0 would land EVERY
        # parallel episode on worker[0]; factories stripe episodes by
        # passing a shared counter's next value (tests pass 0 for a
        # deterministic first-worker session)
        self._rr = int(rr_offset)
        self._http: Optional["aiohttp.ClientSession"] = None  # noqa: F821
        # session state + journal
        self._addr: Optional[str] = None
        self._sid: Optional[str] = None
        self.replay_safe = False
        self._reset_kwargs: Dict[str, Any] = {}
        self._journal: List[Tuple[Any, Any, float, bool]] = []
        # counters (trace_report --env reads the spans; these feed tests
        # and the bench cell directly)
        self.stats = {"resets": 0, "steps": 0, "replays": 0, "failovers": 0}

    # -- plumbing -------------------------------------------------------
    async def _session(self):
        if self._http is None or self._http.closed:
            self._http = aiohttp.ClientSession()
        return self._http

    def _headers(self) -> Optional[Dict[str, str]]:
        ep = telemetry.current_episode()
        if ep is None:
            return None
        return trace_headers(ep.trace_id, rid=self._sid or "")

    def _candidates(self, exclude: Optional[str] = None) -> List[str]:
        """Schedulable workers (monitor view when there is one), round-
        robined so parallel episodes spread, minus the dead one."""
        pool = self._addrs
        if self.monitor is not None:
            sched = [a for a in self.monitor.schedulable_addresses()]
            # the monitor may know workers we were not seeded with
            pool = sched or pool
        pool = [a for a in pool if a != exclude]
        if not pool:
            return []
        k = self._rr % len(pool)
        self._rr += 1
        return pool[k:] + pool[:k]

    async def _post(
        self, addr: str, path: str, payload: Dict[str, Any], timeout: float
    ) -> Dict[str, Any]:
        sess = await self._session()
        return await arequest_with_retry(
            sess, f"http://{addr}{path}", payload,
            max_retries=self.config.call_retries, timeout=timeout,
            retry_delay=self.config.retry_delay_s,
            headers=self._headers(),
        )

    def _span(self, name: str, **attrs):
        t = self.tracer
        if t is None:
            return contextlib.nullcontext()
        return t.span(name, self._sid or "env", **attrs)

    def _worker_failed(self, addr: str) -> None:
        self.stats["failovers"] += 1
        if self.monitor is not None:
            self.monitor.report_failure(addr)
        ep = telemetry.current_episode()
        if ep is not None:
            ep.env_failovers += 1
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.instant(
                "env_failover", self._sid or "env", addr=addr
            )

    # -- Env contract ---------------------------------------------------
    async def areset(self, **kwargs) -> Any:
        self._reset_kwargs = dict(kwargs)
        self._journal = []
        try:
            obs, _ = await self._open_session(kwargs)
        except EnvWorkerUnavailableError:
            # a DISCOVERED pool that went fully dark may have been
            # replaced under us (launcher respawns register new ports):
            # refresh the registry view once before giving up
            if not self._discovered:
                raise
            fresh = discover_env_workers(
                self._experiment_name, self._trial_name
            )
            if not fresh or set(fresh) == set(self._addrs):
                raise
            logger.info(
                f"env pool refreshed from discovery: {fresh}"
            )
            self._addrs = fresh
            obs, _ = await self._open_session(kwargs)
        self.stats["resets"] += 1
        return obs

    async def _open_session(self, kwargs: Dict[str, Any]) -> Tuple[Any, str]:
        last: Optional[Exception] = None
        for addr in self._candidates():
            t0 = time.monotonic()
            try:
                out = await self._post(
                    addr, "/reset", {"kwargs": kwargs},
                    self.config.reset_timeout_s,
                )
            except HttpRequestError as e:
                if e.status == 422:
                    raise EnvActionError(str(e)) from e
                if e.status is not None and 400 <= e.status < 500:
                    raise  # the reset itself is wrong; no worker fixes it
                last = e
                self._worker_failed(addr)
                continue
            self._addr = addr
            self._sid = str(out["session"])
            self.replay_safe = bool(out.get("replay_safe", False))
            # recorded AFTER the session id exists so the span carries
            # the real rid (trace_report --env counts sessions by
            # distinct env_reset rids)
            if self.tracer is not None:
                self.tracer.record(
                    "env_reset", self._sid, t0, time.monotonic(),
                    addr=addr,
                )
            if self.monitor is not None:
                self.monitor.report_success(addr)
            return out.get("observation"), addr
        raise EnvWorkerUnavailableError(
            f"no env worker reachable for reset (tried {self._addrs})"
        ) from last

    async def astep(
        self, action: Any
    ) -> Tuple[Any, float, bool, Dict[str, Any]]:
        if self._sid is None:
            raise EnvServiceError("astep before areset")
        for hop in range(self.config.max_failovers + 1):
            addr = self._addr
            try:
                with self._span("env_step", addr=addr):
                    out = await self._post(
                        addr, "/step",
                        {
                            "session": self._sid,
                            "action": action,
                            # journal index: lets the worker detect a
                            # retried POST of an already-applied step
                            # (cached response) vs a desynced session
                            # (409) — /step retries stay exactly-once
                            "seq": len(self._journal),
                        },
                        self.config.call_timeout_s,
                    )
            except HttpRequestError as e:
                if e.status == 422:
                    # the ENV rejected the action (raised server-side):
                    # surface it as an action error — the workflow turns
                    # it into an error observation, exactly like a local
                    # env.call raising; failing over would just re-run
                    # the poison action across every healthy worker
                    raise EnvActionError(str(e)) from e
                # 404: the worker doesn't know the session (it restarted
                # or expired it). 409: it knows a DIFFERENT history than
                # our journal (e.g. a cancelled call half-applied). Both
                # mean "this session object is unusable" — but the
                # worker itself is alive and replay-eligible.
                session_lost = e.status in (404, 409)
                if (
                    e.status is not None
                    and 400 <= e.status < 500
                    and not session_lost
                ):
                    raise  # malformed action — not a worker failure
                if not session_lost:
                    # connect error / timeout / exhausted 5xx: the worker
                    # is gone (or rebooted, which loses sessions anyway)
                    self._worker_failed(addr)
                if not self.replay_safe:
                    raise EnvSessionLostError(
                        f"env worker {addr} lost session {self._sid} and "
                        f"the env is not replay_safe; episode must retry "
                        f"from reset"
                    ) from e
                if e.status == 409:
                    # the desynced session still exists server-side:
                    # release it so it doesn't squat a slot until TTL
                    with contextlib.suppress(Exception):
                        await self._post(
                            addr, "/close", {"session": self._sid},
                            self.config.call_timeout_s,
                        )
                # a RESPONDING worker (404/409) stays eligible as the
                # replay target — with a single-worker pool, excluding
                # it would fail every episode a restart could save
                await self._replay(
                    exclude=None if session_lost else addr
                )
                continue
            obs = out.get("observation")
            reward = float(out.get("reward", 0.0))
            done = bool(out.get("done", False))
            info = out.get("info") or {}
            if self.monitor is not None:
                self.monitor.report_success(self._addr)
            self._journal.append((action, obs, reward, done))
            self.stats["steps"] += 1
            return obs, reward, done, info
        raise EnvWorkerUnavailableError(
            f"session {self._sid} exceeded max_failovers="
            f"{self.config.max_failovers} worker hops"
        )

    async def _replay(self, exclude: Optional[str]) -> None:
        """Reconstruct the session on a healthy worker: re-reset with the
        journaled kwargs, re-apply every journaled action, and (when
        ``verify_replay``) check the replayed trajectory is bit-identical
        to what the episode already saw — divergence means the env lied
        about ``replay_safe`` and the session is unrecoverable."""
        last: Optional[Exception] = None
        for addr in self._candidates(exclude=exclude):
            sid = None
            t0 = time.monotonic()
            try:
                out = await self._post(
                    addr, "/reset", {"kwargs": self._reset_kwargs},
                    self.config.reset_timeout_s,
                )
                sid = str(out["session"])
                if self.tracer is not None:
                    self.tracer.record(
                        "env_reset", sid, t0, time.monotonic(),
                        addr=addr, replay=True,
                    )
                for i, (action, obs, reward, done) in enumerate(
                    self._journal
                ):
                    rep = await self._post(
                        addr, "/step",
                        {"session": sid, "action": action, "seq": i},
                        self.config.call_timeout_s,
                    )
                    if self.config.verify_replay and (
                        rep.get("observation") != obs
                        or float(rep.get("reward", 0.0)) != reward
                        or bool(rep.get("done", False)) != done
                    ):
                        raise EnvSessionLostError(
                            f"replay diverged at step {i} on {addr}: "
                            f"env declared replay_safe but reproduced a "
                            f"different trajectory"
                        )
            except EnvSessionLostError:
                # divergence: release the half-built session before the
                # episode routes to retry/quarantine (TTL is the backstop)
                if sid is not None:
                    with contextlib.suppress(Exception):
                        await self._post(
                            addr, "/close", {"session": sid},
                            self.config.call_timeout_s,
                        )
                raise
            except HttpRequestError as e:
                if e.status == 422:
                    # a journaled action that SUCCEEDED before now makes
                    # the env raise: that's divergence, not worker death
                    if sid is not None:
                        with contextlib.suppress(Exception):
                            await self._post(
                                addr, "/close", {"session": sid},
                                self.config.call_timeout_s,
                            )
                    raise EnvSessionLostError(
                        f"replay diverged on {addr}: journaled action "
                        f"now raises ({e})"
                    ) from e
                last = e
                self._worker_failed(addr)
                continue
            self._addr = addr
            self._sid = sid
            self.stats["replays"] += 1
            ep = telemetry.current_episode()
            if ep is not None:
                ep.env_replays += 1
            if self.tracer is not None and self.tracer.enabled:
                self.tracer.instant(
                    "env_replay", sid, addr=addr,
                    steps=len(self._journal),
                )
            if self.monitor is not None:
                self.monitor.report_success(addr)
            logger.info(
                f"session replayed onto {addr} "
                f"({len(self._journal)} steps)"
            )
            return
        raise EnvWorkerUnavailableError(
            f"no healthy worker to replay session onto "
            f"(journal of {len(self._journal)} steps)"
        ) from last

    async def aclose(self):
        try:
            if self._sid is not None and self._addr is not None:
                try:
                    await self._post(
                        self._addr, "/close", {"session": self._sid},
                        self.config.call_timeout_s,
                    )
                except Exception:
                    pass  # best-effort; the worker GC owns leaked sessions
        finally:
            self._sid = None
            self._addr = None
            if self._http is not None and not self._http.closed:
                await self._http.close()
            self._http = None


class RemoteToolEnv:
    """Tool-env facade over a remote session, for AgenticToolWorkflow:
    ``astart()`` opens the session and pulls prompt/tools; ``acall``
    steps it (the workflow awaits it under its tool timeout); ``done``/
    ``reward`` mirror the remote env once it reports done."""

    def __init__(self, remote: RemoteEnv, reset_kwargs: Dict[str, Any]):
        self._remote = remote
        self._reset_kwargs = dict(reset_kwargs)
        self._prompt = ""
        self._tools: List[Dict[str, Any]] = []
        self.done = False
        self.reward = 0.0
        self.detail = ""

    @property
    def tools(self) -> List[Dict[str, Any]]:
        return self._tools

    @property
    def stats(self) -> Dict[str, int]:
        return self._remote.stats

    def prompt(self) -> str:
        return self._prompt

    async def astart(self) -> None:
        obs = await self._remote.areset(**self._reset_kwargs)
        if not isinstance(obs, dict):
            raise EnvServiceError(
                f"tool env reset observation must be a dict with "
                f"prompt/tools, got {type(obs).__name__}"
            )
        self._prompt = str(obs.get("prompt", ""))
        self._tools = list(obs.get("tools", []))

    async def acall(self, name: str, arguments: str) -> str:
        obs, reward, done, info = await self._remote.astep(
            {"name": name, "arguments": arguments}
        )
        if done:
            self.done = True
            self.reward = float(reward)
            self.detail = str((info or {}).get("detail", ""))
        return str(obs)

    async def aclose(self) -> None:
        await self._remote.aclose()


def make_remote_tool_env_factory(
    addrs: Optional[Sequence[str]] = None,
    monitor=None,
    config: Optional[EnvServiceConfig] = None,
    tracer: Optional[SpanTracer] = None,
    reset_keys: Optional[Sequence[str]] = None,
):
    """``env_factory`` for AgenticToolWorkflow over the remote plane: each
    episode gets its own session. ``reset_keys`` selects which dataset
    fields become reset kwargs (None = every JSON-serializable field the
    hosted env's factory expects is the caller's contract)."""

    import itertools

    stripe = itertools.count()

    def factory(data: Dict[str, Any]) -> RemoteToolEnv:
        kwargs = (
            {k: data[k] for k in reset_keys if k in data}
            if reset_keys is not None
            else dict(data)
        )
        return RemoteToolEnv(
            RemoteEnv(
                addrs=addrs, monitor=monitor, config=config, tracer=tracer,
                # stripe parallel episodes across the pool (a fresh
                # RemoteEnv per episode would otherwise always start at
                # worker[0])
                rr_offset=next(stripe),
            ),
            reset_kwargs=kwargs,
        )

    return factory


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def main(argv: Optional[list] = None):
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--env", required=True,
        help="hosted env: 'module:attr' zero-arg factory "
        "(e.g. areal_tpu.env.service:countdown_env)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--max-sessions", type=int, default=512)
    p.add_argument(
        "--session-ttl", type=float, default=3600.0,
        help="idle seconds before a leaked session is expired "
        "(<= 0 disables the sweeper)",
    )
    p.add_argument("--experiment-name", default="")
    p.add_argument("--trial-name", default="")
    p.add_argument(
        "--enable-chaos", action="store_true",
        help="open the runtime POST /chaos fault-injection endpoint "
        "(resilience testing only — it can hard-kill the worker)",
    )
    args = p.parse_args(argv)
    # subprocess workers rendezvous in the launcher's namespace
    name_resolve.reconfigure_from_env()
    factory = resolve_env_factory(args.env)
    httpd = serve_env(
        factory,
        host=args.host,
        port=args.port,
        experiment_name=args.experiment_name,
        trial_name=args.trial_name,
        max_sessions=args.max_sessions,
        background=True,
        chaos_endpoint=args.enable_chaos,
        session_ttl_s=args.session_ttl,
    )
    # announce the bound port on stdout (the spawn idiom tests/bench use)
    print(f"PORT {httpd.server_address[1]}", flush=True)
    # lifetime: when a parent holds our stdin as a PIPE (tests, bench),
    # its death/close is the shutdown signal. Under a launcher or daemon,
    # stdin is /dev/null or closed — read() would return EOF IMMEDIATELY
    # and the worker would exit 0 an instant after booting (invisible to
    # the supervisor, which only reacts to nonzero exits) — so anything
    # that isn't a live pipe/tty means "serve until killed".
    import stat as _stat
    import sys

    hold_on_stdin = False
    try:
        mode = os.fstat(sys.stdin.fileno()).st_mode
        hold_on_stdin = (
            _stat.S_ISFIFO(mode)
            or _stat.S_ISSOCK(mode)
            or os.isatty(sys.stdin.fileno())
        )
    except (OSError, ValueError):
        pass
    if hold_on_stdin:
        try:
            sys.stdin.read()
        except Exception:
            pass
    else:
        threading.Event().wait()
    httpd.env_state.deregister()
    httpd.shutdown()


if __name__ == "__main__":
    main()
