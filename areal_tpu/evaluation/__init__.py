"""Offline evaluation harness (reference evaluation/: math_eval etc.)."""

from areal_tpu.evaluation.eval_runner import (  # noqa: F401
    EvalReport,
    evaluate_dataset,
)
