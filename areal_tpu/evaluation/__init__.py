"""Offline evaluation harness (reference evaluation/: math_eval etc.).

The grading subsystem lives here: ``grader`` (family-structured
equivalence, the single source of truth shared with training rewards) and
``extract`` (per-benchmark extraction conventions).
"""

from areal_tpu.evaluation.eval_runner import (  # noqa: F401
    EvalReport,
    evaluate_dataset,
)
from areal_tpu.evaluation.extract import (  # noqa: F401
    CONVENTIONS,
    convention_for,
    extract_pred,
    parse_ground_truth,
    resolve_benchmark,
)
from areal_tpu.evaluation.grader import (  # noqa: F401
    FAMILIES,
    GradeResult,
    answers_equal,
    grade_answer,
)
