"""Codeforces-Elo rating estimation from per-problem outcomes.

Role of the reference's evaluation/cf_elo_caculator.py (the instrument
behind its "Codeforces rating" claims): given a model's pass/fail results
on problems with known difficulty ratings, estimate the Elo rating whose
predicted solve probabilities best explain the outcomes. Fresh
implementation of the standard model: P(solve | rating r, difficulty d) =
1 / (1 + 10^((d - r) / 400)); the estimate maximizes the Bernoulli
log-likelihood over r (golden-section on the concave log-likelihood), with
a percentile helper against a user-supplied rating distribution.
"""

import math
from typing import Dict, List, Optional, Sequence, Tuple


def solve_probability(rating: float, difficulty: float) -> float:
    """Elo win probability of a `rating` player against a `difficulty`
    problem."""
    return 1.0 / (1.0 + 10 ** ((difficulty - rating) / 400.0))


def log_likelihood(
    rating: float, outcomes: Sequence[Tuple[float, bool]]
) -> float:
    ll = 0.0
    for difficulty, solved in outcomes:
        p = min(max(solve_probability(rating, difficulty), 1e-12), 1 - 1e-12)
        ll += math.log(p) if solved else math.log(1.0 - p)
    return ll


def estimate_elo(
    outcomes: Sequence[Tuple[float, bool]],
    lo: float = 0.0,
    hi: float = 4000.0,
    tol: float = 0.5,
) -> float:
    """Maximum-likelihood Elo for (difficulty, solved) outcomes.

    The log-likelihood is concave in the rating (sum of log-sigmoids of
    affine functions), so golden-section search finds the global max. All
    solved → hi; none solved → lo (the MLE diverges; callers should treat
    the bounds as censoring)."""
    outcomes = list(outcomes)
    if not outcomes:
        raise ValueError("need at least one outcome")
    if all(s for _, s in outcomes):
        return hi
    if not any(s for _, s in outcomes):
        return lo
    phi = (math.sqrt(5.0) - 1.0) / 2.0
    a, b = lo, hi
    c = b - phi * (b - a)
    d = a + phi * (b - a)
    fc, fd = log_likelihood(c, outcomes), log_likelihood(d, outcomes)
    while b - a > tol:
        if fc >= fd:
            b, d, fd = d, c, fc
            c = b - phi * (b - a)
            fc = log_likelihood(c, outcomes)
        else:
            a, c, fc = c, d, fd
            d = a + phi * (b - a)
            fd = log_likelihood(d, outcomes)
    return (a + b) / 2.0


def elo_report(
    problems: Sequence[Dict],
    rating_key: str = "rating",
    solved_key: str = "solved",
    human_ratings: Optional[Sequence[float]] = None,
) -> Dict:
    """Aggregate per-problem results into an Elo estimate (+ optional
    percentile against a human rating sample)."""
    outcomes = [
        (float(p[rating_key]), bool(p[solved_key]))
        for p in problems
        if p.get(rating_key) is not None
    ]
    rating = estimate_elo(outcomes)
    out = {
        "elo": round(rating, 1),
        "n_problems": len(outcomes),
        "n_solved": sum(1 for _, s in outcomes if s),
        "solve_rate": round(
            sum(1 for _, s in outcomes if s) / max(len(outcomes), 1), 4
        ),
    }
    if human_ratings:
        below = sum(1 for r in human_ratings if r < rating)
        out["percentile"] = round(100.0 * below / len(human_ratings), 1)
    return out


def expected_solves(
    rating: float, difficulties: Sequence[float]
) -> float:
    """Expected number of solves at a rating (sanity/calibration check)."""
    return sum(solve_probability(rating, d) for d in difficulties)
