"""Offline code evaluation: extract candidate programs, execute against
test cases, report pass@k.

Role of the reference's evaluation/code_eval.py + python_executor.py +
code_verifier/local_verify.py (the LiveCodeBench/codeforces instrument):
completions carry fenced code blocks; the last syntactically-valid block is
the candidate; it runs sandboxed against the problem's input/output or
assert-style tests. Execution goes through reward/code_verifier (the SAME
sandbox training rewards use) — locally, or via the remote verifier pool
(reward/verifier_service) so eval never competes with a trainer host.
"""

import ast
import re
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence

_FENCE = re.compile(
    r"(?i)```(?:python|py|cpp)?\s*\n?(.*?)\n?```", re.DOTALL
)


def extract_python_code(
    text: str, min_length: int = 20, strict_syntax: bool = False
) -> Optional[str]:
    """Last fenced code block of at least ``min_length`` chars; with
    ``strict_syntax`` blocks must parse as python (reference
    code_eval.extract_python_code behavior: invalid blocks are skipped,
    the LAST valid one wins)."""
    valid = []
    for block in _FENCE.findall(text):
        code = block.strip()
        if len(code) < min_length:
            continue
        if strict_syntax:
            try:
                ast.parse(code, mode="exec")
            except (SyntaxError, IndentationError):
                continue
        valid.append(code)
    return valid[-1] if valid else None


def eval_code_completions(
    items: Sequence[Dict[str, Any]],
    completions: Sequence[Sequence[str]],
    timeout: float = 10.0,
    max_workers: int = 8,
    verifier_addrs: Optional[Sequence[str]] = None,
) -> Dict[str, Any]:
    """Score ``completions[i][j]`` (sample j for problem i) against
    ``items[i]``'s tests; returns accuracy + pass@k + per-problem detail.

    Each item carries ``test_cases`` (stdin/stdout dicts) and/or
    ``test_code`` (assert block). ``verifier_addrs`` offloads execution to
    a remote pool."""
    import numpy as np

    from areal_tpu.evaluation.eval_runner import _pass_at_k

    remote = None
    if verifier_addrs:
        from areal_tpu.reward.verifier_service import RemoteVerifier

        remote = RemoteVerifier(verifier_addrs)

    def score_one(item: Dict[str, Any], completion: str) -> float:
        # strict syntax: a trailing non-code fence must not shadow an
        # earlier valid solution
        code = extract_python_code(completion, strict_syntax=True) or (
            completion if "def " in completion or "print(" in completion
            else None
        )
        if code is None:
            return 0.0
        payload = {
            "kind": "code",
            "code": code,
            "test_cases": item.get("test_cases"),
            "test_code": item.get("test_code"),
            "timeout": timeout,
        }
        if remote is not None:
            return remote.verify(payload)
        from areal_tpu.reward.code_verifier import verify_code

        try:
            return float(
                verify_code(
                    code,
                    test_cases=item.get("test_cases"),
                    test_code=item.get("test_code"),
                    timeout=timeout,
                )
            )
        except Exception:
            return 0.0

    jobs = [
        (i, j, item, comp)
        for i, (item, comps) in enumerate(zip(items, completions))
        for j, comp in enumerate(comps)
    ]
    results: Dict[tuple, float] = {}
    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        futs = {
            pool.submit(score_one, item, comp): (i, j)
            for i, j, item, comp in jobs
        }
        for fut, (i, j) in futs.items():
            results[(i, j)] = fut.result()

    n_samples = max((len(c) for c in completions), default=0)
    succ = np.zeros((len(items), n_samples))
    for (i, j), r in results.items():
        succ[i, j] = r > 0
    return {
        "n_problems": len(items),
        "n_samples": n_samples,
        "accuracy": float(succ.mean()) if succ.size else 0.0,
        "pass_at_k": {
            k: _pass_at_k(succ, k)
            for k in (1, 2, 4, 8, 16)
            if k <= n_samples
        },
        "per_problem": succ.tolist(),
    }
