"""Offline evaluation: sample k completions per prompt from generation
servers, score them with a verifiable-reward function, report accuracy and
pass@k.

Role of the reference's `evaluation/` harness (math_eval / code_eval — the
offline loop behind its wall-clock-to-reward claims): the trained policy's
checkpoints are served (any server speaking the /generate contract) and a
dataset sweeps through with deterministic sampling, scored by the same
reward functions training uses (math parser / code verifier), so eval
accuracy is measured with exactly the training-time success criterion.

Usage (CLI):
    python -m areal_tpu.evaluation.eval_runner \
        --data path/to/test.jsonl --type gsm8k \
        --addrs host:port[,host:port...] --tokenizer-path <hf_dir> \
        --n-samples 4 --out results.jsonl
"""

import argparse
import asyncio
import dataclasses
import json
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from areal_tpu.api.cli_args import (
    DatasetConfig,
    GenerationHyperparameters,
    InferenceEngineConfig,
)
from areal_tpu.api.io_struct import ModelRequest


@dataclasses.dataclass
class EvalReport:
    n_prompts: int
    n_samples: int
    accuracy: float  # mean per-sample success
    pass_at_k: Dict[int, float]
    maj_at_k: Dict[int, float]  # majority-vote accuracy (math only)
    avg_gen_tokens: float
    wall_seconds: float
    rows: List[Dict[str, Any]]  # per-prompt details

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d.pop("rows")
        return d


def _pass_at_k(successes: np.ndarray, k: int) -> float:
    """Unbiased pass@k estimator (Codex paper): 1 - C(n-c, k)/C(n, k)."""
    from math import comb

    out = []
    for row in successes:
        n, c = len(row), int(row.sum())
        if n - c < k:
            out.append(1.0)
        else:
            out.append(1.0 - comb(n - c, k) / comb(n, k))
    return float(np.mean(out)) if out else 0.0


def _majority_correct(
    answers: List[str], truth: str, equal: Optional[Callable] = None
) -> float:
    """Majority voting over extracted answers (reference eval aggregation:
    cluster equivalent answers, check the largest cluster against truth).
    ``equal`` overrides the equivalence predicate so benchmark conventions
    (e.g. keep-units grading) apply to clustering too."""
    if equal is None:
        from areal_tpu.reward.math_parser import answers_equal as equal

    clusters: List[List[str]] = []
    for a in answers:
        if a is None:
            continue
        for c in clusters:
            if equal(a, c[0]):
                c.append(a)
                break
        else:
            clusters.append([a])
    if not clusters:
        return 0.0
    best = max(clusters, key=len)
    return float(equal(best[0], truth))


def evaluate_dataset(
    engine,
    items: List[Dict[str, Any]],
    reward_fn: Callable,
    gconfig: GenerationHyperparameters,
    tokenizer=None,
    benchmark: Optional[str] = None,
) -> EvalReport:
    """Run the sweep against any InferenceEngine (`agenerate` contract).

    ``benchmark`` names an extraction convention from
    evaluation/extract.py; when given, the maj@k clustering path extracts
    answers with that benchmark's cascade (minerva sign-off, AIME
    integers, choice letters, ...) instead of the generic reward-path
    cascade, and ground truth is parsed with the benchmark's field rules.
    """
    from areal_tpu.workflow.rlvr import RLVRWorkflow

    # eval sweeps are the INTERACTIVE traffic class: the SLO plane
    # (router admission + server shed/preemption) protects their
    # latency against concurrent bulk rollout pressure
    wf = RLVRWorkflow(
        reward_fn, gconfig, tokenizer=tokenizer,
        priority="interactive",
    )
    t0 = time.perf_counter()

    async def run_all():
        sem = asyncio.Semaphore(64)

        async def one(item):
            async with sem:
                return await wf.arun_episode(engine, item)

        return await asyncio.gather(*[one(it) for it in items])

    outs = asyncio.run(run_all())
    successes, rows, gen_tokens, majorities = [], [], [], {}
    for item, out in zip(items, outs):
        r = np.asarray(out["rewards"]).reshape(-1)
        successes.append((r > 0).astype(np.float64))
        gen_tokens.append(
            float(np.asarray(out["loss_mask"]).sum() / max(len(r), 1))
        )
        row = {
            "question": item.get("question")
            or str(item.get("messages", ""))[:200],
            "rewards": r.tolist(),
        }
        # maj@k needs the completion TEXTS: detokenize the loss-masked
        # region of each sample
        from areal_tpu.evaluation.extract import (
            convention_for,
            extract_answer,
            extract_pred,
            parse_ground_truth,
        )

        truth = ""
        if benchmark is not None:
            # the benchmark's own field rules (solution/Answer/correct/
            # final_answer/...), not just a literal "answer" key. A row
            # whose fields don't fit the convention (e.g. an mmlu letter
            # where an index is expected) must degrade to no-maj@k for
            # that row, not abort the whole sweep
            try:
                truth = parse_ground_truth(item, benchmark)
            except Exception:
                truth = str(item.get("answer", "") or "")
        elif item.get("answer") is not None:
            truth = str(item["answer"])
        # a gsm8k-formatted truth that survived convention parsing (the
        # default convention passes rationale + "#### N" through) reduces
        # to the final answer exactly like process_results does
        if "####" in truth or "\\boxed" in truth:
            truth = extract_answer(truth) or truth
        if tokenizer is not None and truth:
            ids = np.asarray(out["input_ids"])
            lm = np.asarray(out["loss_mask"])
            texts = [
                tokenizer.decode(ids[i][lm[i] > 0].tolist())
                for i in range(ids.shape[0])
            ]
            if benchmark is not None:
                answers = [extract_pred(t, benchmark) for t in texts]
                # grade maj@k clusters under the SAME convention the
                # accuracy path uses (keep-units for minerva/carp)
                conv = convention_for(benchmark)
                from areal_tpu.evaluation.grader import (
                    answers_equal as _ae,
                )

                def equal(a, b, _su=conv.strip_units):
                    return _ae(a, b, strip_units=_su)

            else:
                answers = [extract_answer(t) for t in texts]
                equal = None
            row["answers"] = answers
            for k in (1, 2, 4, 8, 16):
                if k <= len(answers):
                    majorities.setdefault(k, []).append(
                        _majority_correct(answers[:k], truth, equal=equal)
                    )
        rows.append(row)
    succ = np.asarray(successes)
    n = gconfig.n_samples
    return EvalReport(
        n_prompts=len(items),
        n_samples=n,
        accuracy=float(succ.mean()) if succ.size else 0.0,
        pass_at_k={
            k: _pass_at_k(succ, k)
            for k in (1, 2, 4, 8, 16)
            if k <= n
        },
        maj_at_k={
            k: float(np.mean(v)) for k, v in sorted(majorities.items())
        },
        avg_gen_tokens=float(np.mean(gen_tokens)) if gen_tokens else 0.0,
        wall_seconds=time.perf_counter() - t0,
        rows=rows,
    )


def main(argv: Optional[List[str]] = None):
    p = argparse.ArgumentParser()
    p.add_argument("--data", required=True)
    p.add_argument("--type", default="gsm8k", help="dataset type (gsm8k|code|raw)")
    p.add_argument("--addrs", required=True, help="server host:port list")
    p.add_argument(
        "--tokenizer-path", required=True,
        help="HF tokenizer dir (prompts are tokenized, completions "
        "detokenized for scoring)",
    )
    p.add_argument("--n-samples", type=int, default=1)
    p.add_argument("--max-new-tokens", type=int, default=1024)
    p.add_argument("--temperature", type=float, default=0.6)
    p.add_argument("--max-prompts", type=int, default=0)
    p.add_argument(
        "--verifier-addrs", default="",
        help="remote verifier pool (reward/verifier_service) for code "
        "execution off this host",
    )
    p.add_argument("--out", default="")
    args = p.parse_args(argv)

    from areal_tpu.dataset import get_custom_dataset
    from areal_tpu.engine.remote import RemoteInferenceEngine

    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(args.tokenizer_path)
    items = get_custom_dataset(
        DatasetConfig(path=args.data, type=args.type),
        tokenizer=tokenizer,
        split="test",
    )
    if args.max_prompts:
        items = items[: args.max_prompts]
    if args.type == "code":
        if args.verifier_addrs:
            from areal_tpu.reward.verifier_service import RemoteVerifier

            reward = RemoteVerifier(
                args.verifier_addrs.split(",")
            ).code_reward_fn()
        else:
            from areal_tpu.reward.code_verifier import code_reward_fn as reward
    elif args.type in ("gsm8k", "raw"):
        from areal_tpu.reward.math_parser import gsm8k_reward_fn as reward
    else:
        # dataset-aware math grading (math/math_500/minerva_math/mmlu_stem/
        # sat_math/aqua/...: evaluation/math_eval.py conventions)
        from areal_tpu.evaluation.math_eval import make_math_reward_fn

        reward = make_math_reward_fn(args.type)
    engine = RemoteInferenceEngine(
        InferenceEngineConfig(experiment_name="eval", trial_name="offline")
    ).initialize(addrs=args.addrs.split(","))
    try:
        report = evaluate_dataset(
            engine,
            items,
            reward,
            GenerationHyperparameters(
                n_samples=args.n_samples,
                max_new_tokens=args.max_new_tokens,
                temperature=args.temperature,
            ),
            tokenizer=tokenizer,
        )
    finally:
        engine.destroy()
    print(json.dumps(report.to_dict()))
    if args.out:
        with open(args.out, "w") as f:
            for row in report.rows:
                f.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
