"""Per-benchmark answer extraction conventions.

Role of the reference's evaluation/parser.py (769 LoC — the extraction half
of the instrument behind every published AReaL quality table): turning a raw
model completion into the one string the grader compares, with the cascade
order each benchmark's completion format demands, plus per-benchmark
ground-truth field conventions.

Structure (fresh design, not a transliteration):

* **Extraction primitives** — boxed / minerva sign-off / "the answer is" /
  GSM8K ``####`` / choice letter / last number / last integer — each an
  individually-testable function returning ``None`` for "not present".
* **Conventions** — a :class:`Convention` per benchmark stem names the
  primitive cascade, answer type, and whether units are stripped at grading
  time.  ``CONVENTIONS`` ships ≥8 stems (gsm8k, math, minerva_math,
  olympiadbench, aime24, amc23, sat_math, mmlu_stem) plus the long tail
  the eval harness already graded (aqua, svamp, asdiv, mawps, tabmwp,
  gaokao2023, carp_en, college_math).
* **Stem resolution** — :func:`resolve_benchmark` maps eval-file stems
  ("aime_2024", "math500", "olympiadbench_en") onto canonical conventions,
  so ``run_eval.py``'s filename dispatch and training-side reward binding
  agree on the rules.

Equivalence checking lives in :mod:`areal_tpu.evaluation.grader`; this
module only decides *what strings to compare*.
"""

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Extraction primitives
# ---------------------------------------------------------------------------

_BOXED_RE = re.compile(r"\\boxed\s*\{")
_GSM8K_RE = re.compile(r"####\s*([^\n]+)")
_NUMBER_RE = re.compile(r"-?\d[\d,]*(?:\.\d+)?(?:[eE][+-]?\d+)?")
_LAST_NUMBER_RE = re.compile(r"-?\d*\.?\d+")
_INTEGER_RE = re.compile(r"-?\d+")
_CHOICE_RE = re.compile(r"\b([A-E])\b")


def extract_boxed(text: str) -> Optional[str]:
    """Last ``\\boxed{...}`` contents, brace-balanced."""
    out = None
    for m in _BOXED_RE.finditer(text):
        start = m.end()
        depth = 1
        for i in range(start, len(text)):
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
                if depth == 0:
                    out = text[start:i]
                    break
    return out


def extract_boxed_loose(text: str) -> Optional[str]:
    """Boxed with a brace-less fallback: ``\\boxed 42$`` style (reference
    parser tolerates it). None when no "boxed" marker at all."""
    if "boxed" not in text:
        return None
    b = extract_boxed(text)
    if b is not None:
        return b
    tail = text.split("boxed")[-1]
    return tail.split("$")[0].strip()


def extract_minerva(text: str) -> Optional[str]:
    """Minerva's sign-off: ``final answer is $X$. I hope`` — outranks every
    other marker when present (reference parser.extract_answer)."""
    if "final answer is $" in text and "$. I hope" in text:
        return text.split("final answer is $", 1)[1].split("$. I hope", 1)[0]
    return None


def extract_answer_is(text: str) -> Optional[str]:
    """``The/the answer is ...`` (matched via the reference's 'he answer is'
    sentinel so both capitalizations hit)."""
    if "he answer is" in text:
        return text.split("he answer is")[-1]
    return None


def extract_final_answer_is(text: str) -> Optional[str]:
    if "final answer is" in text:
        return text.split("final answer is")[-1]
    return None


def extract_hash_answer(text: str) -> Optional[str]:
    """GSM8K's explicit ``#### N`` marker (last occurrence)."""
    m = _GSM8K_RE.findall(text)
    return m[-1].strip() if m else None


def extract_last_number(text: str) -> Optional[str]:
    """Last number in the text, thousands separators stripped. Returns ""
    (not None) when no number exists — the cascade terminator."""
    nums = _LAST_NUMBER_RE.findall(text.replace(",", ""))
    return nums[-1] if nums else ""


def extract_last_integer(text: str) -> Optional[str]:
    """Last bare integer — AIME-style benchmarks whose answers are integers
    in [0, 999]; a trailing decimal like "3.14" must not be truncated to
    its fraction digits, so integers are taken from comma-stripped text
    with decimals removed first."""
    clean = re.sub(r"-?\d*\.\d+", " ", text.replace(",", ""))
    ints = _INTEGER_RE.findall(clean)
    return ints[-1] if ints else ""


def clean_choice(pred: str) -> str:
    """Reduce a free-text prediction to its last A–E letter (reference
    grader.choice_answer_clean behavior)."""
    pred = pred.strip("\n").rstrip(".").rstrip("/").strip(" ").lstrip(":")
    letters = _CHOICE_RE.findall(pred.upper())
    if letters:
        return letters[-1]
    return pred.strip().strip(".").rstrip(".").rstrip("/")


EXTRACTORS: Dict[str, Callable[[str], Optional[str]]] = {
    "minerva": extract_minerva,
    "boxed": extract_boxed_loose,
    "answer_is": extract_answer_is,
    "final_answer_is": extract_final_answer_is,
    "hash": extract_hash_answer,
    "last_number": extract_last_number,
    "last_integer": extract_last_integer,
}


# ---------------------------------------------------------------------------
# Generic reward-path extraction (training-time contract)
# ---------------------------------------------------------------------------

def extract_answer(text: str) -> Optional[str]:
    """Final answer string from a completion: boxed > "final answer is"
    > #### (GSM8K) > last number (reference extract_answer order). This is
    the benchmark-agnostic cascade the reward path uses."""
    boxed = extract_boxed(text)
    if boxed is not None:
        return boxed.strip()
    # the explicit GSM8K marker outranks free-text "answer is" phrasing —
    # a stray "the answer is <phrase>" in a rationale must not override it
    got = extract_hash_answer(text)
    if got is not None:
        return got
    m = re.findall(
        r"(?:final answer|answer)\s*(?:is|:)\s*([^\n]+)", text,
        re.IGNORECASE,
    )
    if m:
        # keep decimals ("3.14") but cut at sentence boundaries (". ")
        cand = m[-1].strip().split(". ")[0].rstrip(".").strip()
        if cand:
            return cand
    nums = _NUMBER_RE.findall(text)
    if nums:
        return nums[-1]
    return None


# ---------------------------------------------------------------------------
# Per-benchmark conventions
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Convention:
    """One benchmark's extraction rules.

    ``cascade`` names EXTRACTORS entries tried in order (first non-None
    wins). ``answer_type`` "choice" short-circuits to letter cleanup.
    ``strip_units`` False keeps measurement words at grading time
    (reference STRIP_EXCEPTIONS: minerva/carp answers carry units)."""

    name: str
    cascade: Tuple[str, ...] = (
        "minerva", "boxed", "answer_is", "final_answer_is", "last_number",
    )
    answer_type: str = "free"  # free | choice | integer
    strip_units: bool = True


_MATH_CASCADE = (
    "minerva", "boxed", "answer_is", "final_answer_is", "last_number",
)

CONVENTIONS: Dict[str, Convention] = {
    c.name: c
    for c in [
        # NOTE: "####" is a gsm8k GROUND-TRUTH convention, not a completion
        # convention — a completion quoting "#### <rationale>" must not
        # shadow its last number (pinned by tests/test_math_eval.py)
        Convention("gsm8k", cascade=_MATH_CASCADE),
        Convention("math", cascade=_MATH_CASCADE),
        Convention("minerva_math", cascade=_MATH_CASCADE,
                   strip_units=False),
        Convention("olympiadbench", cascade=(
            "boxed", "answer_is", "final_answer_is", "last_number",
        )),
        Convention("aime24", cascade=(
            "boxed", "answer_is", "final_answer_is", "last_integer",
        ), answer_type="integer"),
        Convention("amc23", cascade=(
            "boxed", "answer_is", "final_answer_is", "last_number",
        )),
        Convention("sat_math", answer_type="choice"),
        Convention("mmlu_stem", answer_type="choice"),
        Convention("aqua", answer_type="choice"),
        Convention("gaokao2023", answer_type="choice"),
        Convention("svamp"),
        Convention("asdiv"),
        Convention("mawps"),
        Convention("tabmwp"),
        Convention("carp_en", strip_units=False),
        Convention("college_math"),
        Convention("gaokao2023en"),
        Convention("default", cascade=_MATH_CASCADE),
    ]
}

# filename-stem prefixes → canonical convention (checked in order; longest
# reasonable prefix first so "math_500" does not shadow "mathqa"-style
# stems added later)
_STEM_RULES: List[Tuple[str, str]] = [
    ("gsm", "gsm8k"),
    ("minerva", "minerva_math"),
    ("olympiad", "olympiadbench"),
    ("aime", "aime24"),
    ("amc", "amc23"),
    ("sat", "sat_math"),
    ("mmlu", "mmlu_stem"),
    ("aqua", "aqua"),
    ("gaokao2023en", "gaokao2023en"),
    ("gaokao", "gaokao2023"),
    ("svamp", "svamp"),
    ("asdiv", "asdiv"),
    ("mawps", "mawps"),
    ("tabmwp", "tabmwp"),
    ("carp", "carp_en"),
    ("college", "college_math"),
    ("math", "math"),  # math, math_500, math500 — after minerva/mmlu
]


def resolve_benchmark(stem: str) -> str:
    """Canonical convention name for an eval-file stem. Exact names win;
    otherwise prefix rules absorb year/split suffixes ("aime_2024",
    "math500", "olympiadbench_en"). Unknown stems get the default MATH
    cascade — the conservative generic rules."""
    low = str(stem).strip().lower()
    if low in CONVENTIONS:
        return low
    for prefix, name in _STEM_RULES:
        if low.startswith(prefix):
            return name
    return "default"


def convention_for(benchmark: str) -> Convention:
    return CONVENTIONS[resolve_benchmark(benchmark)]


def extract_pred(text: str, benchmark: str = "math") -> str:
    """Final-answer candidate from a completion under ``benchmark``'s
    convention (reference parser.extract_answer per-dataset order)."""
    conv = convention_for(benchmark)
    text = text.replace("ки", "")  # stray cyrillic the reference strips
    if conv.answer_type == "choice":
        return clean_choice(text)
    pred: Optional[str] = None
    for step in conv.cascade:
        pred = EXTRACTORS[step](text)
        if pred is not None:
            break
    pred = re.sub(r"\n\s*", "", (pred or "")).strip()
    pred = pred.lstrip(":").strip()
    pred = pred.rstrip(".").rstrip("/").strip()
    return pred


# ---------------------------------------------------------------------------
# Per-benchmark ground truth
# ---------------------------------------------------------------------------

def parse_ground_truth(
    example: Dict[str, Any], benchmark: str = "math"
) -> str:
    """Per-benchmark ground-truth answer (reference
    parser.parse_ground_truth field conventions)."""
    name = resolve_benchmark(benchmark)
    if name in ("math", "minerva_math", "default"):
        sol = example.get("solution") or example.get("answer") or ""
        boxed = extract_boxed(str(sol))
        return (boxed if boxed is not None else str(sol)).strip()
    if name == "gsm8k":
        ans = str(example.get("answer", ""))
        return ans.split("####")[-1].strip() if "####" in ans else ans.strip()
    if name == "olympiadbench":
        # OlympiadBench rows carry `final_answer` as a one-element list of
        # latex strings; fall back to answer/solution-boxed
        fa = example.get("final_answer")
        if isinstance(fa, (list, tuple)) and fa:
            return str(fa[0]).replace("$", "").strip()
        if fa:
            return str(fa).replace("$", "").strip()
        sol = example.get("solution") or example.get("answer") or ""
        boxed = extract_boxed(str(sol))
        return (boxed if boxed is not None else str(sol)).strip()
    if name == "aime24":
        # AIME answers are integers in [0, 999], often stored zero-padded
        # ("068"); canonicalize so the grader's string path can hit
        ans = str(example.get("answer", "")).strip().replace("$", "")
        m = _INTEGER_RE.fullmatch(ans)
        return str(int(ans)) if m else ans
    if name == "amc23":
        return str(example.get("answer", "")).replace("$", "").strip()
    if name == "mmlu_stem":
        return "ABCD"[int(example["answer"])]
    if name == "sat_math":
        return str(example.get("Answer", example.get("answer", ""))).strip()
    if name == "aqua":
        return str(example.get("correct", example.get("answer", ""))).strip()
    if name == "svamp":
        return str(example.get("Answer", example.get("answer", ""))).strip()
    if name == "asdiv":
        return re.sub(r"\(.*?\)", "", str(example.get("answer", ""))).strip()
    if name == "mawps":
        return str(example.get("target", example.get("answer", ""))).strip()
    if name == "tabmwp":
        ans = str(example.get("answer", ""))
        if example.get("ans_type") in ("integer_number", "decimal_number"):
            if "/" in ans:
                num, den = ans.split("/")[:2]
                return str(int(num) / int(den))
            return str(float(ans.replace(",", "").replace("%", "e-2")))
        return ans
    # gaokao2023en / college_math / carp_en: the answer field, de-$'d
    return str(example.get("answer", "")).replace("$", "").strip()


__all__ = [
    "Convention",
    "CONVENTIONS",
    "EXTRACTORS",
    "clean_choice",
    "convention_for",
    "extract_answer",
    "extract_answer_is",
    "extract_boxed",
    "extract_boxed_loose",
    "extract_hash_answer",
    "extract_last_integer",
    "extract_last_number",
    "extract_minerva",
    "extract_pred",
    "parse_ground_truth",
    "resolve_benchmark",
]
