"""Benchmark-grade answer equivalence: a family-structured grader.

Role of the reference's evaluation/grader.py (401 LoC, the sympy-based
``math_equal`` behind every published AReaL quality table) AND of its
reward-side twin in reward/math_parser.py: decide whether two answer
strings denote the same mathematical object. This module is the ONE source
of truth — training rewards (``reward/math_parser.py``) and offline eval
(``evaluation/math_eval.py``) both delegate here, so eval accuracy measures
exactly the training-time success criterion and a grading fix lands in both
at once.

The cascade is decomposed into explicit **equivalence families**, each an
individually-testable rule that either decides (True/False) or abstains
(None), tried in ``FAMILIES`` order:

====================  ======================================================
family                decides when / what
====================  ======================================================
``exact``             normalized strings equal (case-insensitive); abstains
                      otherwise
``choice``            truth is a bare A–E letter and the prediction's last
                      standalone letter matches (case-sensitive match
                      against the RAW prediction — uppercasing would turn
                      the article "a" into choice A); abstains on mismatch
``numeric``           both sides evaluate to numbers: rel-tol comparison
                      incl. the percent ambiguity the reference accepts
                      (x matches x/100 and 100·x). Covers
                      percent/fraction/mixed-number forms because
                      normalization rewrites them to evaluable expressions.
                      DECISIVE (True or False) when both sides are numeric
``interval``          both sides are bracketed tuples/intervals/sets:
                      elementwise recursion; bracket style ignored
                      ((0,1] == [0,1], the reference's bracket stripping);
                      brace-literal sets ({1,2}) compare UNORDERED.
                      DECISIVE when both sides split
``matrix``            both sides are pmatrix/bmatrix/array envs:
                      elementwise recursion. DECISIVE when both parse
``equation``          both sides are single equations: lhs−rhs equivalence,
                      either sign; abstains on failure
``symbolic``          timeout-bounded sympy fallback (parse, ``.equals``,
                      ``simplify(a-b)==0``, N()); hostile expressions
                      (giant pow towers) are refused up front. DECISIVE
====================  ======================================================

:func:`grade_answer` returns a :class:`GradeResult` carrying the verdict,
WHICH family decided, and a debug trace of every family consulted — the
instrument for auditing a miscounted reward before it corrupts a policy
gradient (the failure mode async-RLVR systems like ROLL Flash and Laminar
call out: a wrong reward is silent data corruption, not a visible crash).

Unit stripping ("5 cm" == "5") is part of normalization and exposed as
:func:`strip_units`; benchmarks whose answers legitimately carry units
(minerva_math, carp_en — the reference's STRIP_EXCEPTIONS) grade with
``strip_units=False``.
"""

import dataclasses
import re
import threading as _threading
from typing import List, Optional

from areal_tpu.evaluation.extract import extract_boxed

# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------

_CHOICE_RE = re.compile(r"\b([A-E])\b")

_WORD_NUMBERS = {
    "zero": "0", "one": "1", "two": "2", "three": "3", "four": "4",
    "five": "5", "six": "6", "seven": "7", "eight": "8", "nine": "9",
    "ten": "10", "eleven": "11", "twelve": "12",
}

# measurement words stripped from answers ("5 cm" == "5"); the reference
# carries a much longer unit list — these cover the GSM8K/MATH datasets
# NOTE: no bare single letters (an "m" could be algebra, not meters) and
# no words that double as operators ("times")
_UNITS = (
    "degrees?|cm|km|mm|meters?|inch(?:es)?|feet|foot|ft|miles?|mph|"
    "hours?|hrs?|minutes?|mins?|seconds?|secs?|days?|weeks?|months?|"
    "years?|dollars?|cents?|bucks?|points?|units?|square|cubic|percent|"
    "people|students?|apples?|oranges?|ways?"
)
_UNIT_RE = re.compile(r"(^|[\s\d])(" + _UNITS + r")($|\W)")


def strip_units(s: str) -> str:
    """Remove measurement words ("5 cm" → "5"). Individually testable so
    the KEEP_UNITS benchmarks can pin the NON-stripped behavior."""
    prev = None
    while prev != s:
        prev = s
        s = _UNIT_RE.sub(r"\1\3", s)
    return s


def _fix_fracs(s: str) -> str:
    """\\frac12, \\frac1{72}, \\frac{a}2 → (1)/(2) style; nested braces
    handled by repeated innermost substitution."""
    s = s.replace("\\tfrac", "\\frac").replace("\\dfrac", "\\frac")
    # brace-less arguments first: \frac12 / \frac1{72} / \frac{a}2
    s = re.sub(r"\\frac(\d)(\d)", r"\\frac{\1}{\2}", s)
    s = re.sub(r"\\frac(\d)\{", r"\\frac{\1}{", s)
    s = re.sub(r"\\frac\{([^{}]+)\}(\d)", r"\\frac{\1}{\2}", s)
    pat = re.compile(r"\\frac\{([^{}]+)\}\{([^{}]+)\}")
    while True:
        s2 = pat.sub(r"((\1)/(\2))", s)
        if s2 == s:
            return s
        s = s2


def _fix_sqrt(s: str) -> str:
    s = re.sub(r"\\sqrt\[(\d+)\]\{([^{}]+)\}", r"((\2)**(1/\1))", s)
    s = re.sub(r"\\sqrt\s*(\d+)", r"sqrt(\1)", s)
    s = re.sub(r"\\sqrt\{([^{}]+)\}", r"sqrt(\1)", s)
    return s.replace("\\sqrt", "sqrt")


def normalize_answer(ans: str, do_strip_units: bool = True) -> str:
    s = str(ans).strip().replace("\n", "")
    s = s.rstrip(".").strip()
    if "\\boxed" in s:  # a raw \boxed{...} answer normalizes to its content
        b = extract_boxed(s)
        if b is not None:
            s = b
    s = s.replace("{,}", "")  # latex thousands separator: 5{,}905
    s = s.replace("\\!", "").replace("\\,", " ").replace("\\ ", " ")
    s = s.replace("\\left", "").replace("\\right", "")
    s = s.replace("^{\\circ}", "").replace("^\\circ", "")
    s = s.replace("\\$", "").replace("$", "")
    s = s.replace("\\%", "").replace("%", "")
    s = s.replace("\\(", "").replace("\\)", "")
    # latex set braces \{...\} → bare braces (later mapped to parens like
    # every brace; the set FAMILY looks at the raw string for brace-ness)
    s = s.replace("\\{", "{").replace("\\}", "}")
    # matrix env canonicalization (array/bmatrix → pmatrix)
    s = re.sub(r"\\begin\{array\}\{[^}]*\}", r"\\begin{pmatrix}", s)
    s = s.replace("\\end{array}", "\\end{pmatrix}")
    s = s.replace("bmatrix", "pmatrix")
    s = re.sub(r"\\text\s*\{([^{}]*)\}", r"\1", s)
    s = re.sub(r"\\mbox\s*\{[^{}]*\}", "", s)
    s = s.replace("\\mathbf", "").replace("\\mathrm", "")
    # strip "x=" / "k =" style prefixes (single short lhs)
    if s.count("=") == 1 and len(s.split("=")[0].strip()) <= 2:
        s = s.split("=")[1]
    # word numbers ("two" → "2") for bare word answers
    low = s.strip().lower()
    if low in _WORD_NUMBERS:
        return _WORD_NUMBERS[low]
    if do_strip_units:
        s = strip_units(s)
    # thousands separators only — "1,234" → "1234" but "(1, 2)" keeps its
    # tuple comma
    prev = None
    while prev != s:
        prev = s
        s = re.sub(r"(\d),(?=\d{3}(\D|$))", r"\1", s)
    # innermost-out: \frac{\sqrt{3}}{2} needs the sqrt's braces resolved
    # before the frac pattern (brace-free args) can match, and vice versa
    prev = None
    while prev != s:
        prev = s
        s = _fix_sqrt(_fix_fracs(s))
    s = s.replace("\\pi", "pi").replace("\\infty", "oo").replace(
        "infinity", "oo"
    )
    s = s.replace("\\cdot", "*").replace("\\times", "*").replace(
        "\\div", "/"
    )
    s = s.replace("^{", "**{").replace("^", "**")
    s = s.replace("{", "(").replace("}", ")")
    # bare a/b (no parens) stays as-is; "2 1/2" mixed number → (2+1/2)
    m = re.fullmatch(r"\s*(-?\d+)\s+(\d+)\s*/\s*(\d+)\s*", s)
    if m:
        sign = "-" if m.group(1).startswith("-") else "+"
        s = f"({m.group(1)}{sign}({m.group(2)})/({m.group(3)}))"
    s = re.sub(r"\s+", " ", s).strip()
    s = s.rstrip(". ").lstrip()
    # "0." prefixes
    if s.startswith("."):
        s = "0" + s
    # trailing ".000"
    s = re.sub(r"(\d+)\.0+$", r"\1", s)
    s = re.sub(r"(\d+)\.0+([^\d])", r"\1\2", s)
    return s.strip()


# ---------------------------------------------------------------------------
# sympy workers (timeout-bounded)
# ---------------------------------------------------------------------------
# sympy can blow up on pathological model outputs (e.g. 9**9**9**9); all
# sympy work runs in a DAEMON thread with a wall-clock timeout (daemon so a
# stuck worker can never block interpreter exit). Abandoned hostile threads
# leak until they finish; a live counter bounds them — past the bound,
# symbolic checks fail fast to False rather than stalling the reward path.

_SYMPY_TIMEOUT_S = 3.0
_MAX_STUCK_THREADS = 16
_stuck_lock = _threading.Lock()
_stuck_count = 0


def _hostile(s: str) -> bool:
    """Cheap pre-filter for expressions whose EVALUATION cannot be
    interrupted by a thread timeout (a giant integer pow is one CPython
    bytecode — it never releases the GIL, so the only safe defense is to
    refuse it up front; the reference pays a subprocess per check for the
    same reason)."""
    if len(s) > 300:
        return True
    if s.count("**") >= 3:
        return True
    for m in re.finditer(r"\*\*\s*\(?\s*-?(\d+)", s):
        if len(m.group(1)) > 4:  # exponent >= 10^4
            return True
    if re.search(r"\d{40,}", s):  # absurdly long literals
        return True
    return False


def _with_timeout(fn, *args):
    global _stuck_count
    with _stuck_lock:
        if _stuck_count >= _MAX_STUCK_THREADS:
            return None
    box = {}
    state = {"abandoned": False, "finished": False}

    def run():
        global _stuck_count
        try:
            box["r"] = fn(*args)
        except Exception:
            box["r"] = None
        finally:
            with _stuck_lock:
                state["finished"] = True
                if state["abandoned"]:  # un-count ourselves
                    _stuck_count -= 1

    th = _threading.Thread(target=run, daemon=True, name="sympy-eval")
    th.start()
    th.join(timeout=_SYMPY_TIMEOUT_S)
    with _stuck_lock:
        if not state["finished"]:
            state["abandoned"] = True
            _stuck_count += 1
            return None
    return box.get("r")


def _parse_sym(s: str):
    """Parse a (normalized) answer into a sympy object: plain expression
    first, then LaTeX via the lark backend (reference tries parse_latex /
    parse_expr / latex2sympy in order)."""
    import sympy
    from sympy.parsing.sympy_parser import (
        implicit_multiplication_application,
        parse_expr,
        standard_transformations,
    )

    transforms = standard_transformations + (
        implicit_multiplication_application,
    )
    for attempt in (
        lambda: parse_expr(s, evaluate=True, transformations=transforms),
        lambda: sympy.parsing.latex.parse_latex(s, backend="lark"),
        lambda: sympy.sympify(s),
    ):
        try:
            out = attempt()
            if out is not None:
                return out
        except Exception:
            continue
    return None


def _sympy_equal(a: str, b: str) -> bool:
    if _hostile(a) or _hostile(b):
        return False

    def work():
        import sympy

        ea, eb = _parse_sym(a), _parse_sym(b)
        if ea is None or eb is None:
            return False
        try:
            if ea == eb or str(ea) == str(eb):
                return True
        except Exception:
            pass
        try:
            if ea.equals(eb) or sympy.simplify(ea - eb) == 0:
                return True
        except Exception:
            pass
        try:
            # equation forms: |lhs-rhs| agree
            if abs(ea.lhs - ea.rhs).equals(abs(eb.lhs - eb.rhs)):
                return True
        except Exception:
            pass
        try:
            return _isclose(float(sympy.N(ea)), float(sympy.N(eb)))
        except Exception:
            return False

    return bool(_with_timeout(work))


def numeric_value(s: str) -> Optional[float]:
    """Float value of a possibly-symbolic expression (None when the string
    does not denote a number)."""
    try:
        return float(s)
    except (ValueError, TypeError):
        pass
    if s.endswith("\\"):
        s = s[:-1]
    if _hostile(s):
        return None

    def work():
        import sympy

        v = _parse_sym(s)
        if v is not None and getattr(v, "is_number", False):
            return float(sympy.N(v))
        return None

    return _with_timeout(work)


def _isclose(a: float, b: float, rel_tol: float = 1e-4) -> bool:
    from math import isclose

    return isclose(a, b, rel_tol=rel_tol)


# ---------------------------------------------------------------------------
# Structure parsers shared by the interval / matrix families
# ---------------------------------------------------------------------------

def _split_elements(s: str) -> Optional[List[str]]:
    """Top-level comma split of a bracketed tuple/interval/set."""
    if len(s) < 2 or s[0] not in "([" or s[-1] not in ")]":
        return None
    inner = s[1:-1]
    parts, depth, cur = [], 0, []
    for ch in inner:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return [p.strip() for p in parts] if len(parts) > 1 else None


def _matrix_rows(s: str) -> Optional[List[List[str]]]:
    m = re.fullmatch(
        r"\\begin\(pmatrix\)(.*)\\end\(pmatrix\)", s
    ) or re.fullmatch(r"\\begin\{pmatrix\}(.*)\\end\{pmatrix\}", s)
    if not m:
        return None
    rows = [r.strip() for r in m.group(1).split("\\\\") if r.strip()]
    return [[c.strip() for c in r.split("&")] for r in rows]


_SET_LITERAL_RE = re.compile(r"\\?\{.*\\?\}")


def _is_set_literal(raw: str) -> bool:
    """True when the RAW answer is written in set-brace notation
    ({1, 2} or \\{1, 2\\}) — those compare unordered."""
    s = str(raw).strip().strip("$").strip()
    return bool(_SET_LITERAL_RE.fullmatch(s))


# ---------------------------------------------------------------------------
# Equivalence families
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Ctx:
    """Per-grade context threaded through the families."""

    raw_pred: str
    raw_truth: str
    rel_tol: float
    strip_units: bool
    trace: List[str]

    def recurse(self, a: str, b: str) -> bool:
        return answers_equal(
            a, b, rel_tol=self.rel_tol, strip_units=self.strip_units
        )

    def note(self, msg: str) -> None:
        self.trace.append(msg)


def family_exact(p: str, t: str, ctx: _Ctx) -> Optional[bool]:
    """Normalized string equality, case-insensitive."""
    if p.lower() == t.lower():
        ctx.note(f"exact: normalized strings equal ({p!r})")
        return True
    ctx.note(f"exact: {p!r} != {t!r}")
    return None


def family_choice(p: str, t: str, ctx: _Ctx) -> Optional[bool]:
    """Multiple choice: reference accepts "(B)" / "B." / "answer B" for
    "B". Case-sensitive against the RAW prediction — uppercasing the
    completion would turn the article "a" into choice A. Abstains on
    mismatch (a numeric answer may still match a numeric truth later)."""
    if t not in "ABCDE" or len(t) != 1:
        return None
    letters = _CHOICE_RE.findall(str(ctx.raw_pred))
    if letters and letters[-1] == t:
        ctx.note(f"choice: last letter {letters[-1]!r} matches")
        return True
    ctx.note(f"choice: letters {letters!r} do not end with {t!r}")
    return None


def family_numeric(p: str, t: str, ctx: _Ctx) -> Optional[bool]:
    """Numeric equality at rel_tol, with the percentage ambiguity the
    reference accepts (x matches x/100 and 100·x). Covers plain numbers,
    percents, fractions and mixed numbers (normalization rewrites those to
    evaluable expressions). Decisive when both sides are numeric."""
    fp, ft = numeric_value(p), numeric_value(t)
    if fp is None or ft is None:
        ctx.note(f"numeric: not both numeric (pred={fp}, truth={ft})")
        return None
    for label, target in (
        ("truth", ft), ("truth/100", ft / 100.0), ("truth*100", ft * 100.0)
    ):
        if target == 0:
            if abs(fp) < ctx.rel_tol:
                ctx.note(f"numeric: |{fp}| < rel_tol vs zero {label}")
                return True
        elif _isclose(fp, target, ctx.rel_tol):
            ctx.note(f"numeric: {fp} ~= {target} ({label})")
            return True
    ctx.note(f"numeric: {fp} != {ft} (incl. percent forms)")
    return False


def family_interval(p: str, t: str, ctx: _Ctx) -> Optional[bool]:
    """Tuples / intervals / sets: element-wise recursion. Bracket style is
    IGNORED ((0,1] == [0,1]) — matching the reference, which strips
    brackets before comparing (math_equal's "deal with [], (), {}" block).
    Raw brace-literal sets ({1,2} / \\{1,2\\}) compare unordered."""
    pe, te = _split_elements(p), _split_elements(t)
    if pe is None or te is None:
        return None
    if len(pe) != len(te):
        ctx.note(f"interval: arity {len(pe)} != {len(te)}")
        return False
    if _is_set_literal(ctx.raw_pred) and _is_set_literal(ctx.raw_truth):
        # unordered multiset match: each pred element consumes one
        # unmatched truth element
        remaining = list(te)
        for a in pe:
            for i, b in enumerate(remaining):
                if ctx.recurse(a, b):
                    remaining.pop(i)
                    break
            else:
                ctx.note(f"interval(set): no match for element {a!r}")
                return False
        ctx.note(f"interval(set): {len(pe)} elements matched unordered")
        return True
    ok = all(ctx.recurse(a, b) for a, b in zip(pe, te))
    ctx.note(
        f"interval: elementwise {'match' if ok else 'MISMATCH'} "
        f"({len(pe)} elements)"
    )
    return ok


def family_matrix(p: str, t: str, ctx: _Ctx) -> Optional[bool]:
    """Matrices / column vectors: element-wise recursion over pmatrix rows
    (array/bmatrix envs were canonicalized to pmatrix)."""
    pm, tm = _matrix_rows(p), _matrix_rows(t)
    if pm is None or tm is None:
        return None
    if [len(r) for r in pm] != [len(r) for r in tm]:
        ctx.note("matrix: shape mismatch")
        return False
    ok = all(
        ctx.recurse(a, b)
        for ra, rb in zip(pm, tm)
        for a, b in zip(ra, rb)
    )
    ctx.note(f"matrix: elementwise {'match' if ok else 'MISMATCH'}")
    return ok


def family_equation(p: str, t: str, ctx: _Ctx) -> Optional[bool]:
    """Single equations on both sides: lhs−rhs difference equivalent,
    either sign. Abstains on failure (symbolic gets the last word)."""
    if p.count("=") != 1 or t.count("=") != 1:
        return None
    pl, pr = p.split("=")
    tl, tr = t.split("=")
    if _sympy_equal(f"({pl})-({pr})", f"({tl})-({tr})") or _sympy_equal(
        f"-(({pl})-({pr}))", f"({tl})-({tr})"
    ):
        ctx.note("equation: lhs-rhs difference equivalent")
        return True
    ctx.note("equation: differences not equivalent")
    return None


def family_symbolic(p: str, t: str, ctx: _Ctx) -> Optional[bool]:
    """Timeout-bounded sympy symbolic equivalence — the cascade
    terminator: always decisive."""
    ok = _sympy_equal(p, t)
    ctx.note(f"symbolic: sympy says {'equal' if ok else 'not equal'}")
    return ok


FAMILIES: List[tuple] = [
    ("exact", family_exact),
    ("choice", family_choice),
    ("numeric", family_numeric),
    ("interval", family_interval),
    ("matrix", family_matrix),
    ("equation", family_equation),
    ("symbolic", family_symbolic),
]


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class GradeResult:
    """Verdict plus the audit trail: which family decided and what every
    consulted family saw."""

    equal: bool
    family: Optional[str]
    trace: List[str] = dataclasses.field(default_factory=list)

    def __bool__(self) -> bool:
        return self.equal


def grade_answer(
    pred: Optional[str],
    truth: Optional[str],
    rel_tol: float = 1e-4,
    strip_units: bool = True,
) -> GradeResult:
    """Run the family cascade; first family with an opinion decides."""
    trace: List[str] = []
    if pred is None or truth is None:
        return GradeResult(False, "null", ["null side"])
    if str(pred).strip().lower() == str(truth).strip().lower():
        return GradeResult(True, "exact", ["raw strings equal"])
    p = normalize_answer(pred, do_strip_units=strip_units)
    t = normalize_answer(truth, do_strip_units=strip_units)
    trace.append(f"normalized: {p!r} vs {t!r}")
    if not p or not t:
        trace.append("empty after normalization")
        return GradeResult(False, "null", trace)
    ctx = _Ctx(
        raw_pred=str(pred), raw_truth=str(truth),
        rel_tol=rel_tol, strip_units=strip_units, trace=trace,
    )
    for name, fn in FAMILIES:
        verdict = fn(p, t, ctx)
        if verdict is not None:
            return GradeResult(bool(verdict), name, trace)
    return GradeResult(False, None, trace)


def answers_equal(
    pred: Optional[str],
    truth: Optional[str],
    rel_tol: float = 1e-4,
    strip_units: bool = True,
) -> bool:
    """Boolean view of :func:`grade_answer` — the training-reward hot path
    (no trace formatting cost beyond list appends)."""
    return grade_answer(
        pred, truth, rel_tol=rel_tol, strip_units=strip_units
    ).equal


__all__ = [
    "FAMILIES",
    "GradeResult",
    "answers_equal",
    "family_choice",
    "family_equation",
    "family_exact",
    "family_interval",
    "family_matrix",
    "family_numeric",
    "family_symbolic",
    "grade_answer",
    "normalize_answer",
    "numeric_value",
    "strip_units",
]
