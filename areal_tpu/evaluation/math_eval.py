"""Dataset-aware math evaluation: extraction + ground-truth parsing.

Role of the reference's evaluation/parser.py + grader.py + math_eval.py
(the instrument behind its published quality numbers, blog/AReaL_v0_2.md).
Since the grading-subsystem refactor this module BINDS rather than
implements:

* extraction conventions → :mod:`areal_tpu.evaluation.extract`
  (per-benchmark cascades + ground-truth field rules, ≥8 stems);
* equivalence            → :mod:`areal_tpu.evaluation.grader`
  (the family-structured cascade training rewards also use).

Eval accuracy therefore measures exactly the training-time success
criterion — one source of truth. Behavior agreement with the reference's
extractor/grader is pinned by vectors in tests/test_math_eval.py (the
sentinel strings there ARE the declared behavior spec).
"""

from typing import Any, Dict, Tuple

from areal_tpu.evaluation.extract import (  # noqa: F401
    CONVENTIONS,
    clean_choice,
    convention_for,
    extract_answer,
    extract_pred,
    parse_ground_truth,
    resolve_benchmark,
)
from areal_tpu.evaluation.grader import answers_equal, grade_answer

# datasets whose answers are choice letters (reference parser.py:507) —
# derived from the convention table so the two views cannot drift
MULTIPLE_CHOICE = {
    name
    for name, conv in CONVENTIONS.items()
    if conv.answer_type == "choice"
}
# datasets graded without unit stripping (reference STRIP_EXCEPTIONS)
KEEP_UNITS = {
    name
    for name, conv in CONVENTIONS.items()
    if not conv.strip_units
}


def _safe_truth(example: Dict[str, Any], dataset: str) -> str:
    """Ground truth with graceful degradation: a row whose fields don't
    fit the convention (e.g. an mmlu LETTER where an index is expected)
    falls back to the raw answer field instead of raising — a reward fn
    that throws kills a training episode, which is worse than grading
    against the unconverted field."""
    try:
        return parse_ground_truth(example, dataset)
    except Exception:
        return str(example.get("answer", "") or "")


def grade(
    completion: str, example: Dict[str, Any], dataset: str = "math"
) -> Tuple[bool, str, str]:
    """(correct, extracted_pred, ground_truth) for one completion."""
    conv = convention_for(dataset)
    truth = _safe_truth(example, dataset)
    pred = extract_pred(completion, dataset)
    if conv.answer_type == "choice":
        return clean_choice(pred) == clean_choice(truth), pred, truth
    ok = answers_equal(pred, truth, strip_units=conv.strip_units)
    return bool(ok), pred, truth


def grade_with_trace(
    completion: str, example: Dict[str, Any], dataset: str = "math"
):
    """Debug view of :func:`grade`: returns (GradeResult, pred, truth) so a
    miscounted reward can be audited down to the deciding family."""
    conv = convention_for(dataset)
    truth = _safe_truth(example, dataset)
    pred = extract_pred(completion, dataset)
    if conv.answer_type == "choice":
        from areal_tpu.evaluation.grader import GradeResult

        p, t = clean_choice(pred), clean_choice(truth)
        return (
            GradeResult(p == t, "choice", [f"choice letters {p!r} vs {t!r}"]),
            pred,
            truth,
        )
    return (
        grade_answer(pred, truth, strip_units=conv.strip_units),
        pred,
        truth,
    )


# ground-truth fields forwarded from workflow items into the grading
# example (RLVR passes every non-prompt item key through **kw)
_GT_FIELDS = (
    "answer", "Answer", "solution", "correct", "target", "final_answer",
    "ans_type",
)


def make_math_reward_fn(dataset: str = "math"):
    """Workflow-signature reward fn bound to a dataset's conventions."""

    def fn(prompt, completion, prompt_ids, completion_ids,
           answer: str = "", solution: str = "", **kw) -> float:
        example = {"answer": answer}
        if solution:
            example["solution"] = solution
        for k in _GT_FIELDS:
            if k in kw and kw[k] is not None:
                example[k] = kw[k]
        ok, _, _ = grade(completion, example, dataset)
        return float(ok)

    return fn


__all__ = [
    "KEEP_UNITS",
    "MULTIPLE_CHOICE",
    "clean_choice",
    "extract_pred",
    "parse_ground_truth",
    "grade",
    "grade_with_trace",
    "make_math_reward_fn",
    "extract_answer",
]
