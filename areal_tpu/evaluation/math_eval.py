"""Dataset-aware math evaluation: extraction + ground-truth parsing.

Role of the reference's evaluation/parser.py + grader.py + math_eval.py
(the instrument behind its published quality numbers, blog/AReaL_v0_2.md):
robust answer extraction handles dataset-specific completion formats
(minerva's "final answer is $X$. I hope", boxed, "the answer is",
multiple-choice letters, last-number fallback) and per-dataset ground-truth
fields (gsm8k "#### N", MATH boxed solutions, mmlu answer indices...).
Equivalence grading is reward/math_parser.answers_equal — the SAME cascade
training rewards use, so eval accuracy measures the training-time success
criterion. Behavior agreement with the reference's extractor/grader is
pinned by vectors in tests/test_math_eval.py.
"""

import re
from typing import Any, Dict, Optional, Tuple

from areal_tpu.reward.math_parser import (
    answers_equal,
    extract_answer,
    extract_boxed,
)

# datasets whose answers are choice letters (reference parser.py:507)
MULTIPLE_CHOICE = {"mmlu_stem", "sat_math", "aqua", "gaokao2023"}
# datasets graded without unit stripping (reference STRIP_EXCEPTIONS)
KEEP_UNITS = {"carp_en", "minerva_math"}

_LAST_NUMBER = re.compile(r"-?\d*\.?\d+")
_CHOICE = re.compile(r"\b([A-E])\b")


def clean_choice(pred: str) -> str:
    """Reduce a free-text prediction to its last A–E letter (reference
    grader.choice_answer_clean behavior)."""
    pred = pred.strip("\n").rstrip(".").rstrip("/").strip(" ").lstrip(":")
    letters = _CHOICE.findall(pred.upper())
    if letters:
        return letters[-1]
    return pred.strip().strip(".").rstrip(".").rstrip("/")


def extract_pred(text: str, dataset: str = "math") -> str:
    """Final-answer candidate from a completion, dataset-aware
    (reference parser.extract_answer order)."""
    text = text.replace("ки", "")  # stray cyrillic the ref strips
    if dataset in MULTIPLE_CHOICE:
        return clean_choice(text)
    pred: Optional[str] = None
    if "final answer is $" in text and "$. I hope" in text:  # minerva
        pred = text.split("final answer is $", 1)[1].split("$. I hope", 1)[0]
    elif "boxed" in text:
        pred = extract_boxed(text)
        if pred is None:
            # \boxed without braces: up to the closing $
            tail = text.split("boxed")[-1]
            pred = tail.split("$")[0].strip()
    elif "he answer is" in text:  # The/the answer is
        pred = text.split("he answer is")[-1]
    elif "final answer is" in text:
        pred = text.split("final answer is")[-1]
    else:  # last number
        nums = _LAST_NUMBER.findall(text.replace(",", ""))
        pred = nums[-1] if nums else ""
    pred = re.sub(r"\n\s*", "", (pred or "")).strip()
    pred = pred.lstrip(":").strip()
    pred = pred.rstrip(".").rstrip("/").strip()
    return pred


def parse_ground_truth(
    example: Dict[str, Any], dataset: str = "math"
) -> str:
    """Per-dataset ground-truth answer (reference parser.parse_ground_truth
    field conventions)."""
    if dataset in ("math", "math_500", "minerva_math"):
        sol = example.get("solution") or example.get("answer") or ""
        boxed = extract_boxed(str(sol))
        return (boxed if boxed is not None else str(sol)).strip()
    if dataset == "gsm8k":
        ans = str(example.get("answer", ""))
        return ans.split("####")[-1].strip() if "####" in ans else ans.strip()
    if dataset == "mmlu_stem":
        return "ABCD"[int(example["answer"])]
    if dataset == "sat_math":
        return str(example.get("Answer", example.get("answer", ""))).strip()
    if dataset == "aqua":
        return str(example.get("correct", example.get("answer", ""))).strip()
    if dataset == "svamp":
        return str(example.get("Answer", example.get("answer", ""))).strip()
    if dataset == "asdiv":
        return re.sub(r"\(.*?\)", "", str(example.get("answer", ""))).strip()
    if dataset == "mawps":
        return str(example.get("target", example.get("answer", ""))).strip()
    if dataset == "tabmwp":
        ans = str(example.get("answer", ""))
        if example.get("ans_type") in ("integer_number", "decimal_number"):
            if "/" in ans:
                num, den = ans.split("/")[:2]
                return str(int(num) / int(den))
            return str(float(ans.replace(",", "").replace("%", "e-2")))
        return ans
    # gaokao2023en / college_math / default: the answer field, de-$'d
    return str(example.get("answer", "")).replace("$", "").strip()


def grade(
    completion: str, example: Dict[str, Any], dataset: str = "math"
) -> Tuple[bool, str, str]:
    """(correct, extracted_pred, ground_truth) for one completion."""
    truth = parse_ground_truth(example, dataset)
    pred = extract_pred(completion, dataset)
    if dataset in MULTIPLE_CHOICE:
        return clean_choice(pred) == clean_choice(truth), pred, truth
    return bool(answers_equal(pred, truth)), pred, truth


def make_math_reward_fn(dataset: str = "math"):
    """Workflow-signature reward fn bound to a dataset's conventions."""

    def fn(prompt, completion, prompt_ids, completion_ids,
           answer: str = "", solution: str = "", **kw) -> float:
        example = {"answer": answer}
        if solution:
            example["solution"] = solution
        ok, _, _ = grade(completion, example, dataset)
        return float(ok)

    return fn


__all__ = [
    "MULTIPLE_CHOICE",
    "clean_choice",
    "extract_pred",
    "parse_ground_truth",
    "grade",
    "make_math_reward_fn",
    "extract_answer",
]
