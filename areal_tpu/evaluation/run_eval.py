"""Multi-dataset eval orchestration: the eval_and_aggregate analog.

Role of reference evaluation/eval_and_aggregate.py + evaluation/data_loader.py
(the instrument behind the published per-benchmark accuracy tables,
blog/AReaL_v0_2.md:22-34): given a directory of `<dataset>.jsonl` files, fan
each through the serving engine with the dataset's own extraction/grading
conventions (evaluation/math_eval.py), and emit one aggregate table.

    python -m areal_tpu.evaluation.run_eval \
        --data-dir bench_data/ --addrs host:port --tokenizer-path <dir> \
        --n-samples 4 --out results/

Per-dataset conventions come from the FILENAME stem (gsm8k.jsonl -> gsm8k
ground-truth/extraction rules; *code*.jsonl -> execution-based grading).
The programmatic surface (`run_eval(engine, datasets, ...)`) takes
pre-loaded items for embedding in training loops (Evaluator hook).
"""

import argparse
import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.evaluation.eval_runner import EvalReport, evaluate_dataset

CODE_DATASETS = ("code", "humaneval", "mbpp", "lcb", "livecodebench")


def reward_fn_for(dataset: str) -> Callable:
    """Grading convention for a dataset name (reference data_loader's
    per-benchmark judge selection). Math stems resolve through
    evaluation/extract.py's convention table, so "aime_2024" /
    "math500" / "olympiadbench_en" filenames land on the right cascade."""
    low = dataset.lower()
    if any(t in low for t in CODE_DATASETS):
        from areal_tpu.reward.code_verifier import code_reward_fn

        return code_reward_fn
    from areal_tpu.evaluation.extract import resolve_benchmark
    from areal_tpu.evaluation.math_eval import make_math_reward_fn

    return make_math_reward_fn(resolve_benchmark(low))


def load_jsonl_dataset(
    path: str, tokenizer, dataset: str, max_prompts: int = 0
) -> List[Dict[str, Any]]:
    """jsonl rows -> workflow items: tokenized prompt + grading fields
    passed through (reference data_loader.load_data conventions: the
    question field varies by benchmark)."""
    items: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            ex = json.loads(line)
            q = (
                ex.get("question")
                or ex.get("problem")
                or ex.get("Question")
                or ex.get("prompt")
            )
            if not q:
                # an unrecognized question field would silently evaluate
                # empty prompts into a plausible-looking ~0 accuracy
                raise ValueError(
                    f"{path}: row {len(items)} has no question/problem/"
                    f"prompt field (keys: {sorted(ex)})"
                )
            item = dict(ex)
            item.pop("input_ids", None)
            if getattr(tokenizer, "chat_template", None):
                item["messages"] = [{"role": "user", "content": str(q)}]
            else:
                item["input_ids"] = tokenizer.encode(str(q))
            items.append(item)
            if max_prompts and len(items) >= max_prompts:
                break
    return items


def run_eval(
    engine,
    datasets: Dict[str, List[Dict[str, Any]]],
    gconfig: GenerationHyperparameters,
    tokenizer=None,
    reward_fns: Optional[Dict[str, Callable]] = None,
    out_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Evaluate every dataset through `engine`; returns {dataset: report
    dict} plus an 'average' row (unweighted mean accuracy, the reference
    aggregate convention). Writes per-dataset rows + aggregate.json when
    ``out_dir`` is given."""
    from areal_tpu.evaluation.extract import resolve_benchmark

    reports: Dict[str, EvalReport] = {}
    for name, items in datasets.items():
        fn = (reward_fns or {}).get(name) or reward_fn_for(name)
        low = name.lower()
        benchmark = (
            None
            if any(t in low for t in CODE_DATASETS)
            else resolve_benchmark(low)
        )
        reports[name] = evaluate_dataset(
            engine, items, fn, gconfig, tokenizer=tokenizer,
            benchmark=benchmark,
        )
    agg: Dict[str, Any] = {
        name: r.to_dict() for name, r in reports.items()
    }
    accs = [r.accuracy for r in reports.values()]
    agg["average"] = {
        "n_datasets": len(reports),
        "accuracy": sum(accs) / len(accs) if accs else 0.0,
    }
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        for name, r in reports.items():
            with open(os.path.join(out_dir, f"{name}_rows.jsonl"), "w") as f:
                for row in r.rows:
                    f.write(json.dumps(row) + "\n")
        with open(os.path.join(out_dir, "aggregate.json"), "w") as f:
            json.dump(agg, f, indent=2)
    return agg


def format_table(agg: Dict[str, Any]) -> str:
    """Fixed-width aggregate table (the eval_and_aggregate console
    artifact)."""
    names = [n for n in agg if n != "average"]
    head = (
        f"{'dataset':<16} {'n':>5} {'acc':>7} {'pass@k':>18} "
        f"{'maj@k':>18} {'tok':>7} {'s':>7}"
    )
    lines = [head, "-" * len(head)]
    for n in names:
        r = agg[n]
        pk = ",".join(
            f"@{k}={v:.3f}" for k, v in sorted(
                r.get("pass_at_k", {}).items(), key=lambda kv: int(kv[0])
            )
        )
        mk = ",".join(
            f"@{k}={v:.3f}" for k, v in sorted(
                r.get("maj_at_k", {}).items(), key=lambda kv: int(kv[0])
            )
        )
        lines.append(
            f"{n:<16} {r['n_prompts']:>5} {r['accuracy']:>7.3f} "
            f"{pk:>18} {mk:>18} {r['avg_gen_tokens']:>7.1f} "
            f"{r['wall_seconds']:>7.1f}"
        )
    lines.append(
        f"{'AVERAGE':<16} {'':>5} {agg['average']['accuracy']:>7.3f}"
    )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None):
    p = argparse.ArgumentParser()
    p.add_argument(
        "--data-dir", required=True,
        help="directory of <dataset>.jsonl files (stem picks conventions)",
    )
    p.add_argument("--datasets", default="",
                   help="comma list to restrict (default: all files)")
    p.add_argument("--addrs", required=True)
    p.add_argument("--tokenizer-path", required=True)
    p.add_argument("--n-samples", type=int, default=1)
    p.add_argument("--max-new-tokens", type=int, default=1024)
    p.add_argument("--temperature", type=float, default=0.6)
    p.add_argument("--max-prompts", type=int, default=0)
    p.add_argument("--out", default="")
    args = p.parse_args(argv)

    from transformers import AutoTokenizer

    from areal_tpu.api.cli_args import InferenceEngineConfig
    from areal_tpu.engine.remote import RemoteInferenceEngine

    tokenizer = AutoTokenizer.from_pretrained(args.tokenizer_path)
    want = {d for d in args.datasets.split(",") if d}
    datasets: Dict[str, List[Dict[str, Any]]] = {}
    for fname in sorted(os.listdir(args.data_dir)):
        if not fname.endswith(".jsonl"):
            continue
        stem = fname[: -len(".jsonl")]
        if want and stem not in want:
            continue
        datasets[stem] = load_jsonl_dataset(
            os.path.join(args.data_dir, fname), tokenizer, stem,
            max_prompts=args.max_prompts,
        )
    if not datasets:
        raise SystemExit(f"no .jsonl datasets found in {args.data_dir}")
    engine = RemoteInferenceEngine(
        InferenceEngineConfig(
            experiment_name="eval", trial_name="run_eval",
            consumer_batch_size=1, request_timeout=1800,
        )
    ).initialize(addrs=args.addrs.split(","))
    try:
        gconfig = GenerationHyperparameters(
            n_samples=args.n_samples,
            max_new_tokens=args.max_new_tokens,
            temperature=args.temperature,
            top_p=0.95,
        )
        agg = run_eval(
            engine, datasets, gconfig, tokenizer=tokenizer,
            out_dir=args.out or None,
        )
    finally:
        engine.destroy()
    print(format_table(agg))
    return agg


if __name__ == "__main__":
    main()
