"""Slot-based KV cache for continuous-batching decode.

Role of the reference's serving-engine KV pool (SGLang radix/paged cache,
used via HTTP in areal/engine/sglang_remote.py): on TPU a fixed-geometry
cache is the XLA-friendly design — one array per K/V of shape
[L, S, M, Hkv, D] (layers × slots × max_model_len × kv heads × head dim),
updated with static-shape dynamic slices inside jit. Slot allocation is
host-side bookkeeping; the device never sees dynamic shapes.

Prefix reuse (the radix-cache analog, reference
areal/engine/sglang_remote.py:158-168) is host-side bookkeeping over this
fixed geometry: the engine remembers what tokens a freed slot still caches
and re-claims the slot (``alloc_specific``) when a new request shares the
prefix — the interruptible-generation resubmit (prompt + accumulated
tokens) then re-prefills only the suffix.
"""

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp

from areal_tpu.models.config import ModelConfig


@dataclasses.dataclass
class CacheConfig:
    num_slots: int
    max_model_len: int

    def hbm_bytes(self, cfg: ModelConfig, dtype_bytes: int = 2) -> int:
        per_tok = 2 * cfg.num_kv_heads * cfg.head_dim * dtype_bytes
        return cfg.num_layers * self.num_slots * self.max_model_len * per_tok


def init_kv_cache(
    cfg: ModelConfig, ccfg: CacheConfig, dtype=jnp.bfloat16
) -> dict:
    shape = (
        cfg.num_layers,
        ccfg.num_slots,
        ccfg.max_model_len,
        cfg.num_kv_heads,
        cfg.head_dim,
    )
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        # per-slot current length (tokens already cached)
        "lens": jnp.zeros((ccfg.num_slots,), jnp.int32),
    }


class SlotAllocator:
    """Host-side free-list of decode slots."""

    def __init__(self, num_slots: int):
        self.num_slots = num_slots
        self._free: List[int] = list(range(num_slots))

    def alloc(self) -> Optional[int]:
        return self._free.pop() if self._free else None

    def alloc_specific(self, slot: int) -> bool:
        """Claim a particular free slot (prefix-cache reuse)."""
        if slot in self._free:
            self._free.remove(slot)
            return True
        return False

    def free(self, slot: int) -> None:
        assert 0 <= slot < self.num_slots and slot not in self._free
        self._free.append(slot)

    @property
    def n_free(self) -> int:
        return len(self._free)
