"""Paged KV-block cache for continuous-batching generation.

TPU-native analog of the paged/radix KV cache the reference relies on via
SGLang (areal/api/cli_args.py:408 ``disable_radix_cache``; 27k-token
generation recipe blog/AReaL_v0_3.md:263-284): device memory is a pool of
fixed-size pages shared by every sequence; each slot owns a *page table*
(list of logical page ids). One logical page serves all layers (the pool's
leading layer dim), so allocation is per-sequence, not per-layer.

Host-side structures (this module) are pure bookkeeping — the device never
sees dynamic shapes:

- ``PageManager`` — refcounted allocator. Pages are *shared* between
  sequences (GRPO siblings share prompt pages; concurrent requests share
  any cached prefix).
- ``RadixPrefixCache`` — the real radix tree (r9 default): one node per
  page, O(prompt) longest-prefix descent, publish-at-prefill-commit (the
  first GRPO sibling's prompt pages are claimable the moment prefill
  lands, while the owner is still decoding), and copy-on-write claims
  for divergence *within* a partial tail page (grain = the pool's
  token-packed row, so mid-page resumes never need a pool read).
- ``PrefixRegistry`` — the r1-r8 flat registry (``prefix_cache_mode=
  "flat"``): free-time-only parking, full-page-only matching,
  O(entries×tokens) scan. Kept as the bench A/B baseline.

Capacity discipline: admission reserves only the pages a prompt needs now;
decode allocates pages as sequences grow. When the pool runs dry the engine
evicts the registry and, if needed, *preempts* the youngest running request
— its pages go to the registry, so the transparent resubmit usually
re-claims them for free (matching the reference's interruptible-generation
semantics, realhf/system/partial_rollout.py:181-250).
"""

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.models.config import ModelConfig
from areal_tpu.ops.paged_attention import packed_pool_shape


@dataclasses.dataclass
class CacheConfig:
    num_pages: int  # logical pages in the pool (shared across slots)
    page_size: int  # tokens per page
    max_model_len: int

    @property
    def max_pages_per_seq(self) -> int:
        return -(-self.max_model_len // self.page_size)

    def hbm_bytes(self, cfg: ModelConfig, dtype_bytes: int = 2) -> int:
        per_tok = 2 * cfg.num_kv_heads * cfg.head_dim * dtype_bytes
        return cfg.num_layers * self.num_pages * self.page_size * per_tok


def init_kv_pool(
    cfg: ModelConfig, ccfg: CacheConfig, dtype=jnp.bfloat16,
    head_merge: bool = False,
) -> Dict[str, jnp.ndarray]:
    """Packed page pool (see ops/paged_attention.py layout contract)."""
    shape = packed_pool_shape(
        cfg.num_layers,
        cfg.num_kv_heads,
        ccfg.num_pages,
        ccfg.page_size,
        cfg.head_dim,
        head_merge=head_merge,
    )
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


class PageManager:
    """Refcounted page allocator over the device pool (host bookkeeping).

    ``reserve_first`` permanently reserves page 0 as the device-side
    trash target for dropped row writes (dynamic_update_slice clamps
    out-of-range starts, so invalid merge rows are pointed at a page that
    never holds real data instead)."""

    def __init__(self, num_pages: int, reserve_first: bool = False):
        self.num_pages = num_pages
        self.refcount = np.zeros(num_pages, np.int32)
        first = 1 if reserve_first else 0
        if reserve_first:
            self.refcount[0] = 1
        self._free: List[int] = list(range(num_pages - 1, first - 1, -1))

    @property
    def n_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate n fresh pages (refcount 1 each) or None if short."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            if self.refcount[p] != 0:
                raise RuntimeError(
                    f"free page {p} has refcount {self.refcount[p]}"
                )
            self.refcount[p] = 1
        return pages

    def share(self, pages: Sequence[int]) -> None:
        for p in pages:
            if self.refcount[p] <= 0:
                raise RuntimeError(f"sharing unreferenced page {p}")
            self.refcount[p] += 1

    def release(self, pages: Sequence[int]) -> None:
        for p in pages:
            self.refcount[p] -= 1
            if self.refcount[p] < 0:
                raise RuntimeError(f"double release of page {p}")
            if self.refcount[p] == 0:
                self._free.append(p)


class PrefixRegistry:
    """Freed sequences' cached tokens → shareable full pages (radix analog).

    Each entry holds one reference on its pages; claiming shares them
    (refcount++), so many concurrent requests can ride one cached prefix.
    """

    def __init__(self, page_size: int, min_match: int):
        self.page_size = page_size
        self.min_match = min_match
        self._entries: List[Tuple[np.ndarray, Tuple[int, ...], float]] = []
        # lifetime claim accounting (same surface as RadixPrefixCache)
        self.claims = 0
        self.hits = 0
        self.cow_claims = 0
        self.evicted_pages = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def pages(self) -> int:
        """Pool pages the registry currently holds a reference on."""
        return sum(len(p) for _, p, _ in self._entries)

    def add(
        self, pm: PageManager, tokens: np.ndarray, pages: Sequence[int]
    ) -> None:
        """Park `pages` (full pages caching `tokens`); takes ownership of
        one reference per page (caller must NOT release them).

        An existing entry that is a strict PREFIX of the new one on the
        very same leading pages is superseded (its references released
        — live claimants keep theirs): claims always prefer the longest
        match, so the shorter entry adds nothing, and without the sweep
        a k-chunk prompt's publish-at-chunk-commit stream (r15 chunked
        prefill parks its growing committed prefix each wave) would pin
        O(k^2) page references in stale entries."""
        n_full = min(len(pages), len(tokens) // self.page_size)
        if n_full == 0 or self.min_match <= 0:
            pm.release(pages)
            return
        keep = tuple(pages[:n_full])
        if n_full < len(pages):
            pm.release(pages[n_full:])
        new_tokens = np.asarray(
            tokens[: n_full * self.page_size], np.int32
        )
        survivors: List[Tuple[np.ndarray, Tuple[int, ...], float]] = []
        for toks, pgs, stamp in self._entries:
            if (
                len(toks) <= len(new_tokens)
                and pgs == keep[: len(pgs)]
                and np.array_equal(toks, new_tokens[: len(toks)])
            ):
                pm.release(list(pgs))
                continue
            survivors.append((toks, pgs, stamp))
        self._entries = survivors
        self._entries.append((new_tokens, keep, time.monotonic()))

    def claim(
        self, pm: PageManager, prompt: Sequence[int]
    ) -> Tuple[List[int], int]:
        """Longest full-page prefix match; shares the matched pages.
        Returns (pages, cached_tokens). At least one prompt token must
        remain uncached (to produce next-token logits)."""
        self.claims += 1
        if self.min_match <= 0 or not self._entries:
            return [], 0
        prompt_arr = np.asarray(prompt, np.int32)
        limit = len(prompt_arr) - 1
        best, best_len, best_i = None, 0, -1
        for i, (tokens, pages, _) in enumerate(self._entries):
            n = min(len(tokens), limit)
            if n <= best_len:
                continue
            eq = tokens[:n] == prompt_arr[:n]
            match = n if eq.all() else int(np.argmin(eq))
            match = (match // self.page_size) * self.page_size
            if match > best_len:
                best_len, best, best_i = match, pages, i
        if best is None or best_len < max(self.min_match, 1):
            return [], 0
        self.hits += 1
        # refresh the hit's LRU stamp: hot shared prefixes (system prompts)
        # must outlive cold one-off entries under eviction pressure
        tokens, pages, _ = self._entries[best_i]
        self._entries[best_i] = (tokens, pages, time.monotonic())
        shared = list(best[: best_len // self.page_size])
        pm.share(shared)
        return shared, best_len

    def evict(self, pm: PageManager, pages_needed: int) -> int:
        """LRU-evict entries until the allocator could satisfy
        `pages_needed` (or the registry is empty). Returns entries evicted.

        Eviction drops the registry's reference; pages still shared by live
        requests survive (their refcount stays > 0)."""
        evicted = 0
        self._entries.sort(key=lambda e: e[2])
        while self._entries and pm.n_free < pages_needed:
            _, pages, _ = self._entries.pop(0)
            pm.release(pages)
            evicted += 1
            self.evicted_pages += len(pages)
        return evicted

    def flush(self, pm: PageManager) -> None:
        """Drop everything (weight update → cached KV is stale)."""
        for _, pages, _ in self._entries:
            pm.release(pages)
        self._entries.clear()


class _RadixNode:
    """One page of cached tokens. ``tokens`` holds the page's cached
    content (== page_size for full/interior nodes; shorter only for a
    tail leaf, whose owner may still be decoding into the same physical
    page — tails are therefore claimable only by COPY, never by share).
    Children are keyed by their first token for O(1) descent.

    Tier states (r16, only when a KvTierManager is attached):
    ``page`` set = RESIDENT (one tree reference on the device page);
    ``page`` None + ``spill`` set = SPILLED (content lives host-side);
    ``page`` None + ``spill`` None on a non-root node = a hole (the host
    tier dropped the copy — a claim reaching it stops and re-prefills,
    and a later publish of the same block heals it in place)."""

    __slots__ = ("page", "tokens", "children", "parent", "stamp", "spill")

    def __init__(self, page: Optional[int], tokens: np.ndarray, parent):
        self.page = page
        self.tokens = tokens
        self.children: Dict[int, List["_RadixNode"]] = {}
        self.parent = parent
        self.stamp = 0
        self.spill = None


class RadixPrefixCache:
    """Refcounted radix tree over the paged pool (one node = one page).

    Replaces ``PrefixRegistry``'s linear scan with an O(prompt) descent,
    and its free-time-only parking with **publish-at-prefill-commit**:
    the engine inserts a prompt's pages into the tree the moment its
    prefill dispatch is issued, so GRPO siblings admitted in later waves
    — and turn N of a multi-turn episode riding turn N-1's pages — claim
    the shared prefix while the owner is still decoding.

    Ownership: the tree holds exactly ONE PageManager reference per
    node. ``publish`` is non-owning (it ``share``s every page it
    inserts); ``add`` is the owning free-time transfer (publish, then
    release the caller's references — pages whose content is already in
    the tree are thereby deduplicated away).

    Claims: full-page matches are shared by refcount (no copy). A match
    that continues *into* a node's page (divergence within the page, or
    a partial tail) is served copy-on-write: ``claim_cow`` returns the
    source page (with a protective reference the caller must release
    after its device copy is dispatched) and the match length floored to
    ``grain`` — the pool's token-packed row size, which keeps the
    resumed prefill row-aligned so the KV merge never needs to read the
    pool (model_runner.assemble_rows consults last_rows only for
    mid-row starts).

    Eviction is LRU-leaf-first: only childless nodes are evictable (an
    interior node's page is a live prefix), and dropping the tree's
    reference never frees a page a live claimant still holds.
    """

    def __init__(self, page_size: int, min_match: int, grain: int = 1):
        self.page_size = page_size
        self.min_match = min_match
        self.grain = max(1, grain)
        self.root = _RadixNode(None, np.empty(0, np.int32), None)
        self.node_count = 0
        self.resident_count = 0  # nodes holding a device page reference
        self._clock = 0
        # hierarchical KV tiers (r16): None = classic drop-eviction; a
        # KvTierManager turns eviction into demotion and claims into
        # promotions (attach_tiers)
        self._tiers = None
        # nodes on an in-progress claim descent: a promotion-triggered
        # nested eviction must never demote them out from under the
        # claim (their pages are shared only AFTER the descent)
        self._protect: set = set()
        # lifetime counters (engine /metrics)
        self.claims = 0
        self.hits = 0
        self.cow_claims = 0
        self.inserted_pages = 0
        self.evicted_pages = 0

    def attach_tiers(self, tiers) -> None:
        """Attach a ``kv_tiers.KvTierManager``: eviction demotes leaves
        host-side and claim descents promote spilled nodes back."""
        self._tiers = tiers

    def __len__(self) -> int:
        return self.node_count

    @property
    def pages(self) -> int:
        """Pool pages the tree holds a reference on (== resident
        nodes; spilled nodes keep their tokens but no device page)."""
        return self.resident_count

    # -- internals -----------------------------------------------------
    def _touch(self, node: _RadixNode) -> None:
        self._clock += 1
        node.stamp = self._clock

    def _children(self, node: _RadixNode, first: int) -> List[_RadixNode]:
        return node.children.get(int(first), [])

    def _insert_node(
        self, pm: PageManager, parent: _RadixNode, page: int,
        tokens: np.ndarray,
    ) -> _RadixNode:
        child = _RadixNode(int(page), tokens, parent)
        parent.children.setdefault(int(tokens[0]), []).append(child)
        pm.share([page])
        self.node_count += 1
        self.resident_count += 1
        self._touch(child)
        return child

    def _remove_leaf(self, pm: PageManager, node: _RadixNode) -> None:
        if node.children or node.parent is None:
            raise RuntimeError(
                "radix eviction targeted a non-leaf or the root"
            )
        key = int(node.tokens[0])
        sibs = node.parent.children[key]
        sibs.remove(node)
        if not sibs:
            del node.parent.children[key]
        node.parent = None
        if self._tiers is not None:
            # un-queue any pending promotion (its garbage page is the
            # reference released below) and drop the host/disk copy
            self._tiers.forget(node)
        if node.page is not None:
            pm.release([node.page])
            self.resident_count -= 1
        self.node_count -= 1

    # -- publish / add -------------------------------------------------
    def publish(
        self, pm: PageManager, tokens: np.ndarray, pages: Sequence[int]
    ) -> int:
        """Insert ``tokens`` (cached in ``pages``, page-major) into the
        tree WITHOUT taking ownership: every newly inserted page gets its
        own reference via ``pm.share``. Pages whose content an existing
        node already caches are skipped. Returns pages inserted.

        Re-publishing a grown sequence (free-time, after the commit-time
        publish) extends its own tail node in place — same physical
        page, the owner wrote the extra tokens — and continues into the
        decode pages beyond it."""
        if self.min_match <= 0:
            return 0
        arr = np.asarray(tokens, np.int32)
        bs = self.page_size
        n_pages = min(len(pages), -(-len(arr) // bs)) if len(arr) else 0
        node = self.root
        inserted = 0
        depth = 0
        for pi in range(n_pages):
            block = arr[depth : depth + bs]
            page = int(pages[pi])
            if len(block) == bs:
                nxt = None
                upgrade = None
                for child in self._children(node, block[0]):
                    ct = child.tokens
                    if len(ct) == bs and np.array_equal(ct, block):
                        nxt = child
                        break
                    if (
                        child.page == page
                        and len(ct) < bs
                        and np.array_equal(ct, block[: len(ct)])
                    ):
                        upgrade = child
                if nxt is None and upgrade is not None:
                    # tail → full in place (same physical page)
                    upgrade.tokens = block.copy()
                    nxt = upgrade
                if nxt is not None:
                    if nxt.page is None and self._tiers is not None:
                        # heal a SPILLED/dropped node in place: adopt
                        # the publisher's page (fresh reference) — a
                        # free re-promotion, the host/disk copy is now
                        # redundant
                        pm.share([page])
                        nxt.page = page
                        self._tiers.forget(nxt)
                        self.resident_count += 1
                        inserted += 1
                    self._touch(nxt)
                    node = nxt
                    depth += bs
                    continue
                node = self._insert_node(pm, node, page, block.copy())
                inserted += 1
                depth += bs
                continue
            # partial tail block (< bs): terminal by construction
            if len(block) == 0:
                break
            placed = False
            for child in self._children(node, block[0]):
                ct = child.tokens
                m = min(len(ct), len(block))
                if not np.array_equal(ct[:m], block[:m]):
                    continue  # diverges inside the page → sibling tail
                if len(ct) >= len(block):
                    # an existing node already caches at least this much
                    self._touch(child)
                    placed = True
                    break
                if child.page == page:
                    child.tokens = block.copy()  # same-page extension
                    self._touch(child)
                    placed = True
                    break
                if not child.children:
                    # longer content on a different page: replace the
                    # tail's page (tails are never SHARED by claimants —
                    # COW copies keep their own pages — so swapping the
                    # tree's reference is safe)
                    pm.release([child.page])
                    pm.share([page])
                    child.page = page
                    child.tokens = block.copy()
                    self._touch(child)
                    placed = True
                    break
            if not placed:
                self._insert_node(pm, node, page, block.copy())
                inserted += 1
            break
        self.inserted_pages += inserted
        return inserted

    def add(
        self, pm: PageManager, tokens: np.ndarray, pages: Sequence[int]
    ) -> None:
        """Ownership-transfer park (the PrefixRegistry.add contract):
        publish, then release the caller's references — pages that
        duplicated existing tree content are freed.

        Two callers: free-time parking of a finished request's full
        sequence, and publish-at-CHUNK-commit (r15 chunked prefill) —
        the engine parks a still-prefilling prompt's committed
        page-aligned prefix here between chunks, making the tree the
        prefix's only holder; the next admission wave's claim resumes
        prefill exactly at the commit (and GRPO siblings / overlapping
        prompts ride the finished chunks while the owner is still
        prefilling)."""
        if self.min_match > 0 and len(tokens) > 0:
            self.publish(pm, tokens, pages)
        pm.release(pages)

    # -- claim ---------------------------------------------------------
    def claim(
        self, pm: PageManager, prompt: Sequence[int]
    ) -> Tuple[List[int], int]:
        """PrefixRegistry-compatible claim: full shared pages only."""
        pages, off, _, _ = self.claim_cow(pm, prompt, allow_cow=False)
        return pages, off

    def claim_cow(
        self, pm: PageManager, prompt: Sequence[int], allow_cow: bool = True
    ) -> Tuple[List[int], int, Optional[int], int]:
        """Longest-prefix claim. Returns ``(shared_pages, cached_tokens,
        cow_src_page, cow_tokens)``:

        - ``shared_pages`` — full pages matched along the descent, each
          with a fresh reference (the claimant owns them).
        - ``cached_tokens`` — total tokens served from cache, i.e.
          ``len(shared_pages)*page_size + cow_tokens``; always leaves at
          least one prompt token uncached (next-token logits).
        - ``cow_src_page`` — when the match continues into a node's page
          (partial tail, or divergence within a full page): the page to
          device-copy into the claimant's next fresh page. Carries a
          protective reference the CALLER must release once its copy is
          dispatched (eviction between claim and copy must not free it).
        - ``cow_tokens`` — match length inside that page, floored to
          ``grain`` (row-aligned resume, see class docstring).
        """
        self.claims += 1
        promoted = 0
        try:
            if self.min_match <= 0 or self.node_count == 0:
                return [], 0, None, 0
            arr = np.asarray(prompt, np.int32)
            limit = len(arr) - 1
            bs = self.page_size
            node = self.root
            path: List[_RadixNode] = []
            depth = 0
            while depth + bs <= limit:
                block = arr[depth : depth + bs]
                nxt = None
                for child in self._children(node, block[0]):
                    if len(child.tokens) == bs and np.array_equal(
                        child.tokens, block
                    ):
                        nxt = child
                        break
                if nxt is None:
                    break
                if nxt.page is None:
                    # SPILLED node on the match path: promote it back
                    # into a fresh device page NOW (the engine flushes
                    # the queued host→device scatter before this wave
                    # dispatches). A hole or a dry pool ends the match
                    # — the suffix re-prefills.
                    if self._tiers is None or not self._promote(pm, nxt):
                        break
                    promoted += 1
                self._protect.add(id(nxt))
                path.append(nxt)
                node = nxt
                depth += bs
            cow_node: Optional[_RadixNode] = None
            cow_len = 0
            if allow_cow and depth < limit:
                rest = arr[depth:limit]
                for child in self._children(node, rest[0]):
                    if child.page is None:
                        # COW sources must be resident: the device copy
                        # reads the page this dispatch
                        continue
                    n = min(len(child.tokens), len(rest))
                    eq = child.tokens[:n] == rest[:n]
                    m = n if eq.all() else int(np.argmin(eq))
                    m = (m // self.grain) * self.grain
                    if m > cow_len:
                        cow_len, cow_node = m, child
                if cow_len <= 0:
                    cow_node = None
            total = depth + cow_len
            if total < max(self.min_match, 1):
                return [], 0, None, 0
            self.hits += 1
            pages = [nd.page for nd in path]
            pm.share(pages)
            for nd in path:
                self._touch(nd)
            if cow_node is not None:
                pm.share([cow_node.page])
                self._touch(cow_node)
                self.cow_claims += 1
                return pages, total, cow_node.page, cow_len
            return pages, total, None, 0
        finally:
            self._protect.clear()
            if self._tiers is not None:
                self._tiers.note_claim(promoted)

    def _promote(self, pm: PageManager, node: _RadixNode) -> bool:
        """Bring a SPILLED node back device-side: allocate a fresh page
        (evicting/demoting colder leaves if the pool is dry — the claim
        path itself is protected) and queue the host copy for the
        engine's batched pre-dispatch scatter. The new page's single
        reference is the tree's."""
        if node.spill is None:
            return False  # hole: the host tier dropped the copy
        if pm.n_free < 1:
            self.evict(pm, 1)
            if pm.n_free < 1:
                return False
        page = pm.alloc(1)[0]
        node.page = page
        self.resident_count += 1
        self._tiers.begin_promotion(node, page)
        self._touch(node)
        return True

    def match_pages(self, prompt: Sequence[int]) -> List[_RadixNode]:
        """Full-page descent WITHOUT refcount or LRU effects: the
        leading contiguous run of nodes (resident or spilled) caching
        ``prompt`` — the kv-shipping export walk. Stops at a hole (no
        data to ship) and allows matching the full prompt (the importer
        side's claim re-applies the one-uncached-token rule)."""
        if self.min_match <= 0 or self.node_count == 0:
            return []
        arr = np.asarray(prompt, np.int32)
        bs = self.page_size
        node = self.root
        out: List[_RadixNode] = []
        depth = 0
        while depth + bs <= len(arr):
            block = arr[depth : depth + bs]
            nxt = None
            for child in self._children(node, block[0]):
                if len(child.tokens) == bs and np.array_equal(
                    child.tokens, block
                ):
                    nxt = child
                    break
            if nxt is None or (nxt.page is None and nxt.spill is None):
                break
            out.append(nxt)
            node = nxt
            depth += bs
        return out

    # -- eviction / flush ---------------------------------------------
    def evict(self, pm: PageManager, pages_needed: int) -> int:
        """LRU-leaf-first eviction until the allocator could satisfy
        ``pages_needed`` (or the tree is empty). Dropping a node only
        drops the TREE's reference — pages shared by live claimants
        survive. Returns pages evicted."""
        import heapq

        evicted = 0
        if self.node_count == 0 or pm.n_free >= pages_needed:
            return 0
        if self._tiers is not None:
            return self._evict_demote(pm, pages_needed)
        heap: List[tuple] = []
        stack = [self.root]
        while stack:
            nd = stack.pop()
            for lst in nd.children.values():
                stack.extend(lst)
            if nd is not self.root and not nd.children:
                heapq.heappush(heap, (nd.stamp, id(nd), nd))
        while heap and pm.n_free < pages_needed:
            stamp, _, nd = heapq.heappop(heap)
            if nd.children or nd.parent is None or nd.stamp != stamp:
                continue  # stale heap entry (touched or already removed)
            parent = nd.parent
            self._remove_leaf(pm, nd)
            evicted += 1
            if parent is not self.root and not parent.children:
                heapq.heappush(heap, (parent.stamp, id(parent), parent))
        self.evicted_pages += evicted
        return evicted

    def _demotion_victims(
        self, pm: PageManager, pages_needed: int, cap: int = 64
    ) -> List[_RadixNode]:
        """LRU-first demotion candidates: RESIDENT nodes none of whose
        children are resident (their subtree already left the device, so
        demoting them keeps the promotion chain claim-walkable top-down).
        Also removes childless holes opportunistically (free hygiene —
        no device page involved). Claim-protected nodes are excluded:
        a promotion's nested eviction must not eat the descent path."""
        import heapq

        heap: List[tuple] = []
        holes: List[_RadixNode] = []
        stack = [self.root]
        while stack:
            nd = stack.pop()
            for lst in nd.children.values():
                stack.extend(lst)
            if nd is self.root or id(nd) in self._protect:
                continue
            if nd.page is None:
                if nd.spill is None and not nd.children:
                    holes.append(nd)
                continue
            if all(
                c.page is None
                for lst in nd.children.values()
                for c in lst
            ):
                heapq.heappush(heap, (nd.stamp, id(nd), nd))
        for nd in holes:
            if nd.parent is not None:
                self._remove_leaf(pm, nd)
        victims: List[_RadixNode] = []
        projected = pm.n_free
        while heap and projected < pages_needed and len(victims) < cap:
            _, _, nd = heapq.heappop(heap)
            victims.append(nd)
            if pm.refcount[nd.page] == 1:
                projected += 1
        return victims

    def _evict_demote(self, pm: PageManager, pages_needed: int) -> int:
        """Tiered eviction: demote LRU leaves host-side instead of
        dropping them. Runs in rounds (each round one batched
        device→host gather) so a demoted layer's parents become the
        next round's candidates. Partial tails never spill (they are
        COW-only and their owner may still be writing the page) — they
        are removed as before. Returns pages that left the device."""
        bs = self.page_size
        evicted = 0
        while pm.n_free < pages_needed:
            victims = self._demotion_victims(pm, pages_needed)
            if not victims:
                break
            progress = 0
            to_demote: List[tuple] = []
            for nd in victims:
                if len(nd.tokens) < bs:
                    # partial tail: terminal by construction → a leaf
                    self._remove_leaf(pm, nd)
                    progress += 1
                elif self._tiers.has_pending(nd):
                    # an unflushed promotion: the page holds garbage
                    # until the scatter, so it can only be CANCELED
                    # (host copy re-filed for free), never snapshotted.
                    # And only when the tree is its sole holder — a
                    # claimant still referencing it is waiting on the
                    # flush to make the page real; canceling would hand
                    # it garbage (and free no page anyway).
                    if pm.refcount[nd.page] > 1:
                        continue
                    page = self._tiers.cancel_promotion(nd)
                    nd.page = None
                    self.resident_count -= 1
                    pm.release([page])
                    progress += 1
                elif self._tiers.can_store():
                    to_demote.append((nd, nd.page))
                elif not nd.children:
                    # degenerate capacity (one page exceeds the whole
                    # host budget, no disk): classic drop-eviction
                    self._remove_leaf(pm, nd)
                    progress += 1
            if to_demote:
                self._tiers.demote(to_demote)
                for nd, page in to_demote:
                    nd.page = None
                    self.resident_count -= 1
                    pm.release([page])
                progress += len(to_demote)
            evicted += progress
            if progress == 0:
                break
        self.evicted_pages += evicted
        return evicted

    def flush(self, pm: PageManager) -> None:
        """Drop everything (weight update → cached KV is stale),
        spill tiers included — host/disk replicas hold old-policy KV."""
        stack = [self.root]
        while stack:
            nd = stack.pop()
            for lst in nd.children.values():
                stack.extend(lst)
            if nd is not self.root and nd.page is not None:
                pm.release([nd.page])
        if self._tiers is not None:
            self._tiers.flush()
        self.root = _RadixNode(None, np.empty(0, np.int32), None)
        self.node_count = 0
        self.resident_count = 0
