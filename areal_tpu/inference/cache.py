"""Paged KV-block cache for continuous-batching generation.

TPU-native analog of the paged/radix KV cache the reference relies on via
SGLang (areal/api/cli_args.py:408 ``disable_radix_cache``; 27k-token
generation recipe blog/AReaL_v0_3.md:263-284): device memory is a pool of
fixed-size pages shared by every sequence; each slot owns a *page table*
(list of logical page ids). One logical page serves all layers (the pool's
leading layer dim), so allocation is per-sequence, not per-layer.

Host-side structures (this module) are pure bookkeeping — the device never
sees dynamic shapes:

- ``PageManager`` — refcounted allocator. Pages are *shared* between
  sequences (GRPO siblings share prompt pages; concurrent requests share
  any cached prefix), the radix-tree benefit without the tree.
- ``PrefixRegistry`` — freed sequences park their full pages here with the
  token string they cache; new requests claim the longest matching prefix
  by bumping refcounts (no copy). LRU-evicted when the pool runs short.

Capacity discipline: admission reserves only the pages a prompt needs now;
decode allocates pages as sequences grow. When the pool runs dry the engine
evicts the registry and, if needed, *preempts* the youngest running request
— its pages go to the registry, so the transparent resubmit usually
re-claims them for free (matching the reference's interruptible-generation
semantics, realhf/system/partial_rollout.py:181-250).
"""

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.models.config import ModelConfig
from areal_tpu.ops.paged_attention import packed_pool_shape


@dataclasses.dataclass
class CacheConfig:
    num_pages: int  # logical pages in the pool (shared across slots)
    page_size: int  # tokens per page
    max_model_len: int

    @property
    def max_pages_per_seq(self) -> int:
        return -(-self.max_model_len // self.page_size)

    def hbm_bytes(self, cfg: ModelConfig, dtype_bytes: int = 2) -> int:
        per_tok = 2 * cfg.num_kv_heads * cfg.head_dim * dtype_bytes
        return cfg.num_layers * self.num_pages * self.page_size * per_tok


def init_kv_pool(
    cfg: ModelConfig, ccfg: CacheConfig, dtype=jnp.bfloat16,
    head_merge: bool = False,
) -> Dict[str, jnp.ndarray]:
    """Packed page pool (see ops/paged_attention.py layout contract)."""
    shape = packed_pool_shape(
        cfg.num_layers,
        cfg.num_kv_heads,
        ccfg.num_pages,
        ccfg.page_size,
        cfg.head_dim,
        head_merge=head_merge,
    )
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


class PageManager:
    """Refcounted page allocator over the device pool (host bookkeeping).

    ``reserve_first`` permanently reserves page 0 as the device-side
    trash target for dropped row writes (dynamic_update_slice clamps
    out-of-range starts, so invalid merge rows are pointed at a page that
    never holds real data instead)."""

    def __init__(self, num_pages: int, reserve_first: bool = False):
        self.num_pages = num_pages
        self.refcount = np.zeros(num_pages, np.int32)
        first = 1 if reserve_first else 0
        if reserve_first:
            self.refcount[0] = 1
        self._free: List[int] = list(range(num_pages - 1, first - 1, -1))

    @property
    def n_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate n fresh pages (refcount 1 each) or None if short."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            assert self.refcount[p] == 0
            self.refcount[p] = 1
        return pages

    def share(self, pages: Sequence[int]) -> None:
        for p in pages:
            assert self.refcount[p] > 0
            self.refcount[p] += 1

    def release(self, pages: Sequence[int]) -> None:
        for p in pages:
            self.refcount[p] -= 1
            assert self.refcount[p] >= 0
            if self.refcount[p] == 0:
                self._free.append(p)


class PrefixRegistry:
    """Freed sequences' cached tokens → shareable full pages (radix analog).

    Each entry holds one reference on its pages; claiming shares them
    (refcount++), so many concurrent requests can ride one cached prefix.
    """

    def __init__(self, page_size: int, min_match: int):
        self.page_size = page_size
        self.min_match = min_match
        self._entries: List[Tuple[np.ndarray, Tuple[int, ...], float]] = []

    def __len__(self) -> int:
        return len(self._entries)

    def add(
        self, pm: PageManager, tokens: np.ndarray, pages: Sequence[int]
    ) -> None:
        """Park `pages` (full pages caching `tokens`); takes ownership of
        one reference per page (caller must NOT release them)."""
        n_full = min(len(pages), len(tokens) // self.page_size)
        if n_full == 0 or self.min_match <= 0:
            pm.release(pages)
            return
        keep = tuple(pages[:n_full])
        if n_full < len(pages):
            pm.release(pages[n_full:])
        self._entries.append(
            (np.asarray(tokens[: n_full * self.page_size], np.int32), keep,
             time.monotonic())
        )

    def claim(
        self, pm: PageManager, prompt: Sequence[int]
    ) -> Tuple[List[int], int]:
        """Longest full-page prefix match; shares the matched pages.
        Returns (pages, cached_tokens). At least one prompt token must
        remain uncached (to produce next-token logits)."""
        if self.min_match <= 0 or not self._entries:
            return [], 0
        prompt_arr = np.asarray(prompt, np.int32)
        limit = len(prompt_arr) - 1
        best, best_len, best_i = None, 0, -1
        for i, (tokens, pages, _) in enumerate(self._entries):
            n = min(len(tokens), limit)
            if n <= best_len:
                continue
            eq = tokens[:n] == prompt_arr[:n]
            match = n if eq.all() else int(np.argmin(eq))
            match = (match // self.page_size) * self.page_size
            if match > best_len:
                best_len, best, best_i = match, pages, i
        if best is None or best_len < max(self.min_match, 1):
            return [], 0
        # refresh the hit's LRU stamp: hot shared prefixes (system prompts)
        # must outlive cold one-off entries under eviction pressure
        tokens, pages, _ = self._entries[best_i]
        self._entries[best_i] = (tokens, pages, time.monotonic())
        shared = list(best[: best_len // self.page_size])
        pm.share(shared)
        return shared, best_len

    def evict(self, pm: PageManager, pages_needed: int) -> int:
        """LRU-evict entries until the allocator could satisfy
        `pages_needed` (or the registry is empty). Returns entries evicted.

        Eviction drops the registry's reference; pages still shared by live
        requests survive (their refcount stays > 0)."""
        evicted = 0
        self._entries.sort(key=lambda e: e[2])
        while self._entries and pm.n_free < pages_needed:
            _, pages, _ = self._entries.pop(0)
            pm.release(pages)
            evicted += 1
        return evicted

    def flush(self, pm: PageManager) -> None:
        """Drop everything (weight update → cached KV is stale)."""
        for _, pages, _ in self._entries:
            pm.release(pages)
        self._entries.clear()
