"""Generation engine: continuous batching over the paged KV-block pool.

Role of the SGLang server the reference drives over HTTP (areal/engine/
sglang_remote.py + realhf/system/generation_server.py), rebuilt TPU-native:
a single background loop thread owns the device state (params, page pool)
and interleaves admissions (prefill) with fused multi-step decode. Every
device program is compiled once per shape bucket — continuous batching
never recompiles.

Chunked prefill (r15, ``JaxGenConfig.chunked_prefill``): a long prompt's
admission is capped at ``prefill_chunk_tokens`` suffix tokens per wave;
the committed page-aligned prefix is published into the prefix cache at
chunk commit and the request requeues, so the NEXT wave's claim resumes
exactly there — later chunks are claims against the prompt's own
committed pages, decode dispatches interleave between chunks, and
time-to-first-token for a request admitted behind a bulk prompt is
bounded by ~one chunk's latency instead of the whole prefill. Chunk
boundaries double as cheap preemption points (deadline pressure defers
the next bulk chunk). Greedy streams are bit-identical chunked on/off;
off is a strict no-op.

Memory model (the radix prefix cache, inference/cache.py):
- prompts and generations live in refcounted pages; GRPO siblings *share*
  full prompt pages (one prefill, no copy) and copy at most one partial
  tail page. A refcounted RADIX TREE over the pool (r9 default) is
  populated at PREFILL COMMIT — the first sibling's prompt pages are
  claimable the moment its prefill dispatch lands, so siblings/turns
  arriving in later waves ride them while the owner is still decoding —
  and extended at free time with the full generated sequence. Claims
  descend the tree in O(prompt): full pages share by refcount; a match
  that diverges *within* a page (partial tails included) is served
  copy-on-write at the pool's row grain, and prefill resumes mid-page.
- decode allocates pages lazily as sequences grow. When the pool runs dry
  the engine evicts the tree leaf-LRU-first and then *preempts* the
  youngest running requests: their pages move to the tree and the
  request transparently re-queues (it usually re-claims its own pages, so
  preemption costs one partial-page re-prefill at most). This is what lets
  max_model_len be 16k+ without reserving 16k tokens per slot.

Interruption protocol (matches reference semantics
sglang_remote.py:186-234): ``pause()`` aborts in-flight requests — they
resolve with ``stop_reason="abort"`` and their tokens; the client
re-submits with accumulated tokens after ``continue_generation``; the
registry serves the already-cached prefix back without recompute.
"""

import dataclasses
import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.cli_args import JaxGenConfig
from areal_tpu.inference import model_runner
from areal_tpu.inference import precompile as precompile_lib
from areal_tpu.inference.cache import (
    CacheConfig,
    PageManager,
    PrefixRegistry,
    RadixPrefixCache,
    init_kv_pool,
)
from areal_tpu.inference.policies import PolicyRegistry, UnknownPolicyError
from areal_tpu.inference.weights import WeightStore
from areal_tpu.models import hf_io
from areal_tpu.models.config import ModelConfig, load_hf_config
from areal_tpu.models.transformer import Params
from areal_tpu.utils import data as data_utils
from areal_tpu.utils import goodput
from areal_tpu.utils import logging as logging_util
from areal_tpu.utils.tracing import Histogram, SpanTracer

logger = logging_util.getLogger("GenerationEngine")

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}

# the traffic plane's two scheduling classes (api/cli_args.TrafficConfig):
# interactive = latency-sensitive (eval, live agentic sessions), bulk =
# throughput rollouts. Unknown labels degrade to bulk — the shed/preempt
# machinery must never promote unlabeled traffic.
SCHED_CLASSES = ("interactive", "bulk")


class AdmissionRejectedError(RuntimeError):
    """The bounded admission queue is full and this request's class is
    being shed (load shedding, not failure). ``retry_after`` is the
    backpressure hint an HTTP shell forwards as ``429 + Retry-After`` —
    utils/http treats that as "back off and retry", so a shed never
    burns the client's episode-retry budget."""

    def __init__(
        self,
        message: str,
        retry_after: float = 1.0,
        sched_class: str = "bulk",
    ):
        super().__init__(message)
        self.retry_after = retry_after
        self.sched_class = sched_class


@dataclasses.dataclass
class _Request:
    rid: str
    input_ids: List[int]
    max_new_tokens: int
    min_new_tokens: int
    temperature: float
    top_p: float
    top_k: int
    greedy: bool
    stop_token_ids: List[int]
    future: Future
    slot: Optional[int] = None
    output_ids: List[int] = dataclasses.field(default_factory=list)
    output_logprobs: List[float] = dataclasses.field(default_factory=list)
    output_versions: List[int] = dataclasses.field(default_factory=list)
    submit_time: float = dataclasses.field(default_factory=time.monotonic)
    first_token_time: Optional[float] = None
    preemptions: int = 0
    # --- SLO traffic plane (r10) ---
    # scheduling class ("interactive" | "bulk") + tenant label, stamped
    # by workflows through engine/remote.py; unknown/absent = bulk
    priority: str = "bulk"
    tenant: str = ""
    # absolute soft deadline (monotonic clock); a queued interactive
    # request about to miss it may preempt a bulk request
    deadline_at: Optional[float] = None
    # a suffix-resume continuation of an in-flight episode request: it
    # already holds client-side progress, so admission never sheds it
    resumed: bool = False
    # --- chunked prefill (r15) ---
    # prefill chunks this request has committed so far (0 = never
    # chunk-capped); prefill_pos is the committed token position after
    # the last chunk — the next wave's prefix-cache claim resumes at or
    # beyond it, and a claim that regresses below it counts a stall
    # (chunk_stalls >= 2 admits the remainder whole: cache thrash must
    # never livelock a prompt). chunk_deferred marks an in-progress
    # deferral episode (the preemption counter records it ONCE, not
    # once per scheduler tick); first_dispatch_time is the wave that
    # first served this request (chunk 0) — queue-wait metrics end
    # there, not at the final chunk (being prefilled is not queueing).
    # All are reset at _install (one slot life per admission).
    chunk_index: int = 0
    prefill_pos: int = 0
    chunk_stalls: int = 0
    chunk_deferred: bool = False
    first_dispatch_time: Optional[float] = None
    # prompt tokens served from the prefix cache at the LAST install
    # (sibling fan-out counts the whole shared prompt) — surfaced in
    # the result's meta_info so clients (and the cross-server shipping
    # e2e test) can observe cache effectiveness per request
    cached_tokens: int = 0
    # weight version this request decodes under (and whose KV its pages
    # hold) — stamped at admission, left behind by a pin-policy flip so
    # the request drains on the buffer that prefilled it (the store
    # holds one pin per such request until it finishes/preempts)
    weight_version: int = 0
    # --- multi-policy plane (r19) ---
    # named policy line this request decodes on ("" = the default line:
    # self.params / WeightStore, exactly the pre-r19 engine). submit()
    # resolves the raw handle (name[@vN|@stable|@canary], canary split
    # applied ONCE there) to (policy, policy_version); admission
    # re-checks liveness and stamps weight_version = the line's version
    # (version ints are per-line, so every (policy, weight_version)
    # comparison must carry the name)
    policy: str = ""
    policy_version: int = 0
    # multimodal payload (VLM serving): pixel_values [P, Dp],
    # vis_seg/vis_pos_h/vis_pos_w [P], mm_index [plen] (-1 = text),
    # mrope_pos [plen, 3]; rope_delta shifts decode rope positions
    # (mrope compresses image blocks, so text positions lag cache length)
    mm: Optional[Dict[str, np.ndarray]] = None
    rope_delta: int = 0
    _mm_key: Optional[bytes] = None

    @property
    def mm_key(self) -> bytes:
        """Identity of the visual inputs: GRPO sibling grouping and page
        sharing must distinguish same-token prompts with different
        pixels."""
        if self.mm is None:
            return b""
        if self._mm_key is None:
            import hashlib

            h = hashlib.blake2b(digest_size=12)
            h.update(np.ascontiguousarray(self.mm["pixel_values"]).tobytes())
            self._mm_key = h.digest()
        return self._mm_key

    @property
    def all_tokens(self) -> List[int]:
        """Prompt for (re-)prefill: original prompt + everything generated
        (a preempted request resumes by re-prefilling its own output)."""
        return self.input_ids + self.output_ids

    @property
    def budget_left(self) -> int:
        return self.max_new_tokens - len(self.output_ids)

    @property
    def min_left(self) -> int:
        return self.min_new_tokens - len(self.output_ids)


_MM_KEYS = (
    "pixel_values", "vis_seg", "vis_pos_h", "vis_pos_w", "mm_index",
    "mrope_pos",
)
_MM_DTYPES = {"pixel_values": np.float32, "mrope_pos": np.int32}


def _parse_request(payload: Dict[str, Any], fut: Future) -> _Request:
    sp = payload.get("sampling_params", {})
    mm = None
    rope_delta = 0
    if payload.get("mm"):
        raw = dict(payload["mm"])
        if "pixel_values_b64" in raw:
            # binary transport (remote client): base64 float32 + shape
            import base64

            raw["pixel_values"] = np.frombuffer(
                base64.b64decode(raw.pop("pixel_values_b64")), np.float32
            ).reshape(raw.pop("pixel_values_shape"))
        required = ("pixel_values", "vis_seg", "vis_pos_h", "vis_pos_w",
                    "mm_index")
        missing = [k for k in required if k not in raw]
        if missing:
            # reject on the CALLER thread — a KeyError later inside the
            # engine loop would kill serving for every request
            raise ValueError(f"mm payload missing keys: {missing}")
        mm = {
            k: np.asarray(raw[k], _MM_DTYPES.get(k, np.int32))
            for k in _MM_KEYS
            if k in raw
        }
        if "rope_delta" in raw:
            rope_delta = int(raw["rope_delta"])
        elif "mrope_pos" in mm and len(mm["mrope_pos"]):
            # text token at sequence index i has rope position i + delta
            # (mrope compresses each image block to max(t, h/m, w/m) slots)
            rope_delta = int(mm["mrope_pos"].max()) + 1 - len(
                mm["mrope_pos"]
            )
    priority = str(payload.get("priority") or "bulk")
    if priority not in SCHED_CLASSES:
        priority = "bulk"
    deadline_s = payload.get("deadline_s")
    submit_time = time.monotonic()
    return _Request(
        rid=payload.get("rid", f"req-{time.time_ns()}"),
        priority=priority,
        tenant=str(payload.get("tenant") or ""),
        deadline_at=(
            submit_time + float(deadline_s)
            if deadline_s is not None and float(deadline_s) > 0
            else None
        ),
        resumed=bool(payload.get("resumed")),
        policy=str(payload.get("policy") or ""),
        submit_time=submit_time,
        input_ids=list(payload["input_ids"]),
        max_new_tokens=int(sp.get("max_new_tokens", 128)),
        min_new_tokens=int(sp.get("min_new_tokens", 0)),
        temperature=float(sp.get("temperature", 1.0)),
        top_p=float(sp.get("top_p", 1.0)),
        top_k=int(sp.get("top_k", 0)),
        greedy=bool(sp.get("greedy", False)),
        stop_token_ids=list(sp.get("stop_token_ids", [])),
        future=fut,
        mm=mm,
        rope_delta=rope_delta,
    )


class GenerationEngine:
    """In-process generation engine; the HTTP server is a thin shell."""

    def __init__(
        self,
        config: JaxGenConfig,
        model_config: Optional[ModelConfig] = None,
        params: Optional[Params] = None,
    ):
        self.config = config
        self.dtype = _DTYPES[config.dtype]
        if getattr(config, "compilation_cache_dir", ""):
            from areal_tpu.utils.compile_cache import (
                enable_compilation_cache,
            )

            enable_compilation_cache(config.compilation_cache_dir)
        if model_config is None:
            model_config = load_hf_config(config.model_path)
        self.model_config = model_config
        if params is None:
            params = hf_io.load_params(
                config.model_path, model_config, dtype=self.dtype
            )
        # --- tensor-parallel serving mesh (per-server tp, the analog of the
        # reference's SGLang tp inside one server, areal/api/cli_args.py:399;
        # required to fit 7B+ params on small-HBM chips) ---
        tp = max(1, config.tensor_parallel_size)
        if tp > 1:
            devs = jax.devices()
            if len(devs) < tp:
                raise ValueError(
                    f"tensor_parallel_size={tp} but only {len(devs)} devices"
                )
            if (
                model_config.num_kv_heads % tp != 0
                or model_config.num_heads % tp != 0
            ):
                raise ValueError(
                    f"tensor_parallel_size={tp} must divide num_heads="
                    f"{model_config.num_heads} and num_kv_heads="
                    f"{model_config.num_kv_heads}"
                )
            if model_config.is_moe and model_config.num_experts % tp != 0:
                raise ValueError(
                    f"tensor_parallel_size={tp} must divide num_experts="
                    f"{model_config.num_experts} for MoE serving"
                )
            from areal_tpu.models.transformer import param_logical_axes
            from areal_tpu.parallel import sharding as sharding_lib

            self.mesh = jax.sharding.Mesh(
                np.asarray(devs[:tp]), ("tensor",)
            )
            rules = {
                "embed": None, "heads": "tensor",
                # MoE serving: the expert dim shards over the per-server
                # axis (one PartitionSpec can't use the axis twice, so the
                # within-expert ffn dim stays replicated)
                "mlp": None if model_config.is_moe else "tensor",
                "expert": "tensor",
                "vocab": None, "layer": None,
            }
            self._param_shardings = sharding_lib.tree_shardings(
                self.mesh, param_logical_axes(model_config), rules
            )
            # paged pool [L, Hkv, NP, BS//f, f*D]: kv heads follow the
            # tensor axis
            self._kv_sharding = jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec(
                    None, "tensor", None, None, None
                )
            )
            self._replicated = sharding_lib.replicated(self.mesh)
        else:
            self.mesh = None
            self._param_shardings = None
            self._kv_sharding = None
            self._replicated = None
        self.params = self._place_params(params)

        # --- paged KV pool ---
        bs = config.page_size
        num_pages = config.num_pages
        if num_pages <= 0:
            # conservative auto: full provisioning (every slot can reach
            # max_model_len) — set num_pages explicitly to oversubscribe.
            # +1 for the permanently reserved trash page
            num_pages = (
                config.max_num_seqs * (-(-config.max_model_len // bs)) + 1
            )
        self.cache_config = CacheConfig(
            num_pages=num_pages,
            page_size=bs,
            max_model_len=config.max_model_len,
        )
        from areal_tpu.ops.paged_attention import (
            can_head_merge,
            resolve_pool_layout,
        )

        layout = getattr(config, "pool_layout", "auto")
        if layout not in ("auto", "token_packed", "head_merged"):
            raise ValueError(
                f"pool_layout={layout!r}: expected auto | token_packed | "
                "head_merged"
            )
        # "auto" resolves to head_merged where the geometry + placement
        # allow (the r6 default — built for the decode-DMA bottleneck and
        # parity-pinned in tests/test_pool_layout.py)
        layout = resolve_pool_layout(
            layout, model_config.num_kv_heads, model_config.head_dim,
            single_device=self.mesh is None,
        )
        if layout == "head_merged":
            if not can_head_merge(
                model_config.num_kv_heads, model_config.head_dim
            ):
                raise ValueError(
                    "pool_layout=head_merged needs Hkv*head_dim | 128 "
                    f"(got {model_config.num_kv_heads}x"
                    f"{model_config.head_dim})"
                )
            if self.mesh is not None:
                # TP shards the pool's kv-head dim, which merged collapses
                # — silently downgrading would make layout A/Bs bogus
                raise ValueError(
                    "pool_layout=head_merged is single-device only "
                    "(tensor parallelism shards the pool's kv-head dim)"
                )
        self._head_merge = layout == "head_merged"
        if self.mesh is None:
            self.cache = init_kv_pool(
                model_config, self.cache_config, self.dtype,
                head_merge=self._head_merge,
            )
        else:
            # allocate directly sharded — materializing on one device
            # first would OOM exactly the small-HBM configs TP exists for
            self.cache = jax.jit(
                lambda: init_kv_pool(
                    model_config, self.cache_config, self.dtype
                ),
                out_shardings={
                    "k": self._kv_sharding,
                    "v": self._kv_sharding,
                },
            )()
        # page 0 is the trash target for dropped merge rows — reserved
        self.pm = PageManager(num_pages, reserve_first=True)
        cache_mode = getattr(config, "prefix_cache_mode", "radix")
        if cache_mode not in ("radix", "flat"):
            raise ValueError(
                f"prefix_cache_mode={cache_mode!r}: expected radix | flat"
            )
        if cache_mode == "radix":
            # COW grain = the token-packed row (pack_factor tokens): a
            # multiple of BOTH layouts' tokens-per-row, so mid-page
            # claim resumes stay row-aligned (assemble_rows never reads
            # the pool) and cached-token counts are layout-independent
            from areal_tpu.ops.paged_attention import pack_factor

            self.registry = RadixPrefixCache(
                bs, config.prefix_reuse_min,
                grain=pack_factor(model_config.head_dim),
            )
        else:
            self.registry = PrefixRegistry(bs, config.prefix_reuse_min)
        self._radix = cache_mode == "radix"
        # --- hierarchical KV tiers (r16): host-RAM (optionally disk)
        # spill store under the radix tree. Strictly no-op when off:
        # no manager, no tree hook, no metric keys.
        self._kv_tiers = None
        if getattr(config, "kv_spill", False):
            if not self._radix:
                raise ValueError(
                    "kv_spill requires prefix_cache_mode='radix' "
                    "(the spill tier lives under the radix tree)"
                )
            from areal_tpu.inference.kv_tiers import KvTierManager

            self._kv_tiers = KvTierManager(
                host_bytes=int(getattr(config, "host_kv_bytes", 1 << 30)),
                gather_fn=self._gather_pages_host,
                disk_path=getattr(config, "kv_disk_path", "") or "",
            )
            self.registry.attach_tiers(self._kv_tiers)
        # cross-server prefix shipping (r16): /kv_export service + the
        # /generate-side fetch from a session's previous owner
        self._kv_ship = bool(getattr(config, "kv_ship", False))
        if self._kv_ship and not self._radix:
            raise ValueError(
                "kv_ship requires prefix_cache_mode='radix' (shipped "
                "pages enter through the radix publish/claim contract)"
            )
        s = config.max_num_seqs
        self._free_slots: List[int] = list(range(s - 1, -1, -1))
        self._tables = np.full(
            (s, self.cache_config.max_pages_per_seq), num_pages, np.int32
        )
        self._slot_pages: Dict[int, List[int]] = {}
        self._cached_len = np.zeros(s, np.int64)
        # attention backend: Pallas kernel on single-device TPU, jnp
        # gather fallback elsewhere (CPU tests, TP serving)
        if config.attn_impl == "auto":
            on_tpu = jax.devices()[0].platform == "tpu"
            self._attn_impl = "kernel" if (tp == 1 and on_tpu) else "jnp"
        else:
            self._attn_impl = config.attn_impl
        self.model_version = 0
        self._rng_key = jax.random.PRNGKey(config.seed)

        self._jit_cache: Dict[str, Any] = {}
        self._admit_queue: "queue.Queue[_Request]" = queue.Queue()
        self._command_queue: "queue.Queue" = queue.Queue()
        self._active: Dict[int, _Request] = {}  # slot -> request
        self._pending: List[_Request] = []  # drained but not yet admitted
        self._pending_since: Optional[float] = None
        # device-path weight staging (chunked receive — the LEGACY
        # paused path; streamed ingest stages in self.weights instead)
        self._staged: Dict[str, Any] = {}
        self._staging_key = None
        self._staged_chunks: set = set()
        # --- zero-pause weight plane (r13): versioned buffers + shadow
        # staging + the flip the loop applies between dispatches ---
        wt = getattr(config, "weights", None)
        if wt is None:
            from areal_tpu.api.cli_args import WeightTransferConfig

            wt = WeightTransferConfig()
        if wt.flip_policy not in ("pin", "resume"):
            raise ValueError(
                f"weights.flip_policy={wt.flip_policy!r}: expected "
                "pin | resume"
            )
        self._wt_cfg = wt
        self._weights_streaming = bool(wt.streaming)
        self.weights = WeightStore(staging_ttl_s=wt.staging_ttl_s)
        self._leaf_shardings: Optional[Dict[str, Any]] = None
        self._cohort_rr = 0  # round-robin cursor over version cohorts
        # --- multi-policy serving plane (r19): N named policy lines on
        # this one engine, each with its own buffers/pins/KV namespace.
        # Strictly no-op until the first named push: `active` stays
        # False (the hot-loop gate), no namespace caches exist, and
        # metrics() emits zero policy keys. Cold named buffers demote
        # to host RAM (LRU past max_resident; pinned = undemotable) and
        # reload on the next request that resolves to them.
        pol = getattr(config, "policy", None)
        self._policies = PolicyRegistry(
            to_host=jax.device_get,
            to_device=self._place_params,
            max_resident=int(getattr(pol, "max_resident", 2) or 0),
            staging_ttl_s=wt.staging_ttl_s,
        )
        # (name, version) -> that namespace's own prefix cache sharing
        # self.pm — a canary's pages can never be claimed by the stable
        # line because claims/publishes never cross namespaces. KV
        # tiers/shipping stay default-namespace-only (the spill store
        # and /kv_export are keyed by token content, not policy).
        self._policy_caches: Dict[tuple, Any] = {}
        self._sweep_tick = 0
        self._paused = threading.Event()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # device-resident decode state: the generation loop's only host
        # traffic per step is ONE result fetch (tokens+logprobs).
        # INVARIANT (decode tail compaction): a new per-slot array must
        # join _dispatch_chunk's `plain_attrs` gather spec (or its
        # special-case block for non-1-D/conditional arrays), and its
        # decode_multi-returned update must join the `updates` dict —
        # otherwise compacted dispatches silently diverge from
        # full-width ones. tests/test_decode_compaction.py pins parity
        # for the current set. (_align_base_dev is such a special case:
        # gathered per row with padding forced to 0, read-only on
        # device — r7 speculative canonical alignment.)
        self._cur_tokens = jnp.zeros(s, jnp.int32)
        # identity slot map for full-width dispatches (uncommitted, like
        # the arange decode_multi would otherwise build per dispatch)
        self._identity_slots = jnp.arange(s, dtype=jnp.int32)
        self._active_dev = jnp.zeros(s, bool)
        self._temp_dev = jnp.ones(s, jnp.float32)
        self._top_p_dev = jnp.ones(s, jnp.float32)
        self._top_k_dev = jnp.zeros(s, jnp.int32)
        self._greedy_dev = jnp.zeros(s, bool)
        self._remaining = jnp.zeros(s, jnp.int32)
        self._no_stop = jnp.zeros(s, jnp.int32)
        self._stop_tokens = jnp.full((s, 8), -1, jnp.int32)
        # device-resident cached length per slot: decode chunk N+1 can
        # dispatch before chunk N's results reach the host
        self._lens_dev = jnp.zeros(s, jnp.int32)
        # per-slot admission cache length — the canonical chunk-alignment
        # base for speculative serving (a partial draft accept leaves a
        # slot between decode_chunk boundaries; every later dispatch
        # replays boundary-to-now K/V from the pool so per-position
        # numerics stay bit-identical to a non-speculative run). Only
        # consulted when spec is configured
        self._align_base_dev = jnp.zeros(s, jnp.int32)
        self._align_base = np.zeros(s, np.int64)  # host mirror
        # VLM slots: mrope text positions lag the cache index by a
        # per-request constant; tracked per slot, passed to decode only
        # when some active slot is multimodal (text-only serving keeps
        # its compiled programs)
        self._rope_delta_dev = jnp.zeros(s, jnp.int32)
        self._slot_mm = np.zeros(s, bool)
        # per-slot last (partial) pool row — lets merges avoid reading the
        # pool (see model_runner.init_last_rows)
        from areal_tpu.inference.model_runner import init_last_rows

        # last-row buffers mirror the POOL's row layout
        _, hkv_pool, _, _, lane = self.cache["k"].shape
        self._last_rows = init_last_rows(
            model_config.num_layers, s, hkv_pool, lane, self.dtype
        )
        # pipelined decode: dispatched-but-unprocessed chunks, and page
        # releases deferred until the pipeline drains (an in-flight chunk
        # may still write to a host-finished slot's pages)
        self._inflight: List[Dict[str, Any]] = []
        self._deferred_release: List[tuple] = []
        # --- decode tail compaction (r6): dispatch over a pow2 bucket of
        # ACTIVE slots. Single-device only: under TP the per-slot state
        # is explicitly replicated on the mesh and the full-slot dispatch
        # is kept. Sampling is slot-keyed (model_runner._sample_impl), so
        # compaction never changes a request's token stream.
        self._compact_enabled = (
            bool(getattr(config, "decode_compact", True))
            and self.mesh is None
        )
        self._compact_rows: Optional[int] = None  # current sticky bucket
        self._compact_shrink_streak = 0
        # occupancy accounting: how many rows each decode chunk paid for
        # vs how many carried live requests (the compaction win, measured)
        self.total_decode_chunks = 0
        self.total_rows_dispatched = 0
        self.total_rows_active = 0
        self._decode_rows_dispatched = 0  # last chunk (gauge)
        self._decode_rows_active = 0  # last chunk (gauge)
        self.rows_dispatched_hist: Dict[int, int] = {}
        if self.mesh is not None:
            # small state must be explicitly replicated on the mesh so jit
            # doesn't mix committed single-device and sharded inputs
            for attr in (
                "_cur_tokens", "_active_dev", "_temp_dev", "_top_p_dev",
                "_top_k_dev", "_greedy_dev", "_remaining", "_no_stop",
                "_stop_tokens", "_lens_dev", "_rope_delta_dev",
                "_align_base_dev",
            ):
                setattr(
                    self, attr,
                    jax.device_put(getattr(self, attr), self._replicated),
                )
            self._last_rows = jax.device_put(
                self._last_rows, self._replicated
            )
        # --- speculative decoding (r7): host-side draft-free n-gram
        # proposals (inference/spec.py) verified by one multi-token
        # dispatch (model_runner.spec_verify). Single-device dense models
        # only: TP keeps the replicated full-slot dispatch, and MoE
        # capacity routing is batch-composition-dependent (a K-position
        # verify would route differently than K sequential steps).
        sc = getattr(config, "spec", None)
        spec_wanted = bool(sc is not None and sc.enabled)
        # decode_chunk < 2 leaves no room for even one draft inside the
        # canonical window (_propose_drafts trims to decode_chunk-1-rl),
        # so speculation could never verify anything — but the
        # drain-for-drafts branch would still fire on raw n-gram
        # candidates, silently destroying pipelining forever
        self._spec_configured = (
            spec_wanted
            and self.mesh is None
            and not model_config.is_moe
            and config.decode_chunk >= 2
        )
        if spec_wanted and not self._spec_configured:
            logger.warning(
                "speculative decoding requested but unavailable: needs "
                "single-device serving, a dense model, and "
                "decode_chunk >= 2 — running without speculation"
            )
        if self._spec_configured:
            from areal_tpu.inference.spec import (
                AcceptRateGate,
                NgramProposer,
            )

            self._proposer = NgramProposer(sc.ngram_min, sc.ngram_max)
            self._spec_gate = AcceptRateGate(
                sc.accept_floor, sc.disable_patience
            )
        else:
            self._proposer = None
            self._spec_gate = None
        self._spec_disable_logged = False
        # set once the gate has sticky-disabled AND every active slot is
        # back on a canonical boundary: later dispatches skip the
        # alignment-replay machinery entirely (plain spec-off program)
        self._spec_replay_off = False
        self.total_spec_chunks = 0
        self.spec_draft_tokens_total = 0
        self.spec_accepted_tokens_total = 0
        self._step_counter = 0
        # metrics
        self.total_generated_tokens = 0
        self.total_prompt_tokens = 0
        self.total_cached_prompt_tokens = 0  # prompt tokens served from KV reuse
        self.total_cow_copies = 0  # COW page copies for mid-page claims
        # r16 KV tiers: cached prompt tokens that came back from the
        # HOST tier (a subset of total_cached_prompt_tokens — the rest
        # were device-resident hits); ship counters cover the
        # cross-server /kv_export import/export traffic
        self.total_host_cached_tokens = 0
        self.kv_ship_exports_total = 0
        self.kv_ship_imports_total = 0
        self.kv_ship_pages_out_total = 0
        self.kv_ship_pages_in_total = 0
        self.kv_ship_failures_total = 0
        self.total_requests = 0
        self.total_aborted = 0
        self.total_preemptions = 0
        # --- SLO traffic plane (r10) ---
        # admission-queue composition: per-class count of requests
        # sitting in _admit_queue (submit increments on handler threads,
        # _admit decrements on the loop thread); _pending composition is
        # scanned directly at metrics time
        self._aq_lock = threading.Lock()
        self._aq_class = {c: 0 for c in SCHED_CLASSES}
        self._aq_resumed = 0  # resumed (bound-exempt) entries in-queue
        self._class_submitted = {c: 0 for c in SCHED_CLASSES}
        self.requests_shed_total = 0
        self.deadline_preemptions_total = 0
        self.deadline_misses_total = 0
        # --- chunked prefill (r15): bounded interactive TTFT ---
        # resolved per-dispatch prefill token budget (0 = off). A long
        # prompt's admission is capped at this many suffix tokens; the
        # committed page-aligned prefix is published into the prefix
        # cache at chunk commit and the request requeues — the next
        # wave's claim resumes exactly there, so later chunks are
        # claims against the prompt's own committed pages and every
        # admission dispatch stays ~one chunk wide. Chunk boundaries
        # are the new preemption points: deadline pressure defers bulk
        # chunks instead of killing whole prefills.
        self._chunk_budget = precompile_lib.resolve_chunk_budget(config)
        if (
            getattr(config, "chunked_prefill", False)
            and self._chunk_budget <= 0
        ):
            logger.warning(
                "chunked prefill requested but unavailable: needs a "
                "prefix cache (0 < prefix_reuse_min <= the page-aligned "
                "chunk budget — committed chunks resume via claims) and "
                "a budget below max_model_len — admitting prompts whole"
            )
        self.prefill_chunks_total = 0
        self.prefill_chunk_preemptions_total = 0
        # stall-escape admissions (uncapped dispatches under cache
        # thrash): ttft_bounded reports whether the chunk bound has
        # held for EVERY admission dispatch so far — a gauge that
        # echoed the config while an escape re-created the
        # head-of-line block would lie to the CI gate reading it
        self.prefill_chunk_stall_escapes = 0
        # unsynced chunk-wave dispatch handles: chunk waves never fetch
        # logits, so without a bound the loop could queue an entire
        # prompt's chunks on device ahead of a just-arrived interactive
        # request — recreating the head-of-line block chunking removes
        self._prefill_inflight: List[Any] = []
        # request-lifecycle spans (strict no-op unless config.tracing is
        # enabled — the scheduler loop only ever pays an attribute read)
        self.tracer = SpanTracer(getattr(config, "tracing", None))
        # --- goodput attribution plane (r11) ---
        # every XLA compile is attributed to the dispatch that triggered
        # it (phase + shape signature → compile_events.jsonl + the
        # shape_ladder_coverage gauge readiness consumes), and the loop
        # below books its wall time into exclusive buckets whose
        # fractions sum to 1.0 of observed wall. The coverage
        # denominator is the EXACT enumerated shape ladder (r14,
        # inference/precompile.py) — the same rung list the AOT
        # precompiler drives — so a fully-precompiled engine reads
        # coverage 1.0 and latches ready with zero traffic.
        gp = getattr(config, "goodput", None)
        self._ladder = precompile_lib.enumerate_ladder(
            config, model_config, single_device=self.mesh is None
        )
        self._ladder_fingerprint = precompile_lib.ladder_fingerprint(
            config, model_config, single_device=self.mesh is None,
            attn_impl=self._attn_impl,
        )
        self.compiles = goodput.CompileTracker(
            events_path=getattr(gp, "compile_events_path", "") or "",
            ladder_size=len(self._ladder),
            fingerprint=self._ladder_fingerprint,
            max_events_bytes=int(
                getattr(gp, "compile_events_max_bytes", 8_000_000)
            ),
        )
        self.ledger = goodput.GoodputLedger(
            "engine", goodput.ENGINE_BUCKETS, remainder="idle",
            productive=goodput.ENGINE_PRODUCTIVE,
            jsonl_path=getattr(gp, "jsonl_path", "") or "",
            compile_tracker=self.compiles,
        )
        self._ready_quiet_s = float(getattr(gp, "ready_quiet_s", 3.0))
        self._ready_min_requests = int(
            getattr(gp, "ready_min_requests", 1)
        )
        self._started_at = time.monotonic()
        self._ready_latched = False
        self._completed_requests = 0  # non-abort finishes (readiness)
        # native latency histograms per scheduling class (always on —
        # span-derived percentiles only exist while tracing is enabled
        # AND the spans haven't been drained; these are the durable
        # latency source the fleet rollup consumes)
        self._hists = {
            name: {cls: Histogram() for cls in SCHED_CLASSES}
            for name in (
                "queue_wait_seconds", "ttft_seconds",
                "request_latency_seconds",
            )
        }
        # EWMA throughput gauges (tokens/s), updated by the loop thread
        self._decode_tps = 0.0
        self._prefill_tps = 0.0
        self._last_decode_mark: Optional[float] = None
        # pause-window bookkeeping: pause() stamps, continue_generation()
        # records the span (the weight-update window the client sits out)
        self._pause_start: Optional[float] = None
        # on-demand jax.profiler capture (POST /profile → request_profile):
        # (n_busy_steps, PhaseProfiler) armed here, consumed on the loop
        # thread — the profiler must bracket the device dispatches, which
        # only the loop thread issues. The lock makes arm-vs-arm (HTTP
        # handler threads) and arm-vs-consume (loop thread) atomic.
        self._profile_lock = threading.Lock()
        self._profile_pending: Optional[tuple] = None
        self._profile_stack = None
        self._profile_left = 0

    def _place_params(self, params: Params) -> Params:
        """Host or device pytree → this engine's param placement."""
        if self.mesh is None:
            return jax.device_put(params)
        return jax.device_put(params, self._param_shardings)

    def _copy_params_placed(self, params: Params) -> Params:
        """Fresh, correctly-placed COPY of a (possibly device-resident)
        pytree — the source may later be donated by its owner, so aliasing
        is never acceptable."""
        if self.mesh is None:
            return jax.tree_util.tree_map(
                lambda p: jnp.array(p, dtype=self.dtype, copy=True), params
            )
        key = "copy_params"
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(
                lambda t: jax.tree_util.tree_map(
                    lambda p: jnp.copy(p.astype(self.dtype)), t
                ),
                out_shardings=self._param_shardings,
            )
        placed = jax.device_put(params, self._param_shardings)
        return self._jit_cache[key](placed)

    def _place_leaf(self, name: str, arr) -> Any:
        """Host array → this engine's placement for ONE named parameter
        leaf. The streamed ingest path places per chunk on the HTTP
        handler thread, so h2d transfer overlaps live decode instead of
        bursting at the flip."""
        x = jnp.asarray(arr, dtype=self.dtype)
        if self.mesh is None:
            return x
        if self._leaf_shardings is None:
            from areal_tpu.utils.weight_transfer import flatten_params

            # the shardings tree mirrors the params tree, so flattening
            # it yields the same '/'-joined leaf names the wire uses
            self._leaf_shardings = dict(
                flatten_params(self._param_shardings)
            )
        sh = self._leaf_shardings.get(name)
        return jax.device_put(x, sh) if sh is not None else x

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    def start(self):
        if self._running:
            raise RuntimeError("engine already started")
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # a flip queued after (or racing) the loop's last iteration
        # would leave its waiter blocked out the full timeout — close
        # the store: the pending flip fails now and later queue_flip
        # calls fail fast
        self.weights.close()
        self._policies.close()
        # non-HTTP deployments: drain remaining spans to the configured
        # JSONL sink (the server path drains via GET /trace instead)
        self.tracer.flush()
        # final goodput snapshot to the configured stream (no-op
        # without a path; live deployments scrape /metrics instead)
        self.ledger.export_jsonl()

    # ------------------------------------------------------------------
    # Public API (thread-safe)
    # ------------------------------------------------------------------
    def submit(self, payload: Dict[str, Any]) -> Future:
        fut: Future = Future()
        req = _parse_request(payload, fut)
        trace_ctx = payload.get("trace_ctx")
        if trace_ctx:
            # incoming cross-process trace context (X-Areal-Trace): every
            # span this engine records for the rid joins the originating
            # episode's timeline
            self.tracer.bind_trace(req.rid, str(trace_ctx))
        if req.policy:
            # resolve the handle on the CALLER thread — an unknown name
            # or dead selector is the client's mistake, rejected as a
            # typed 4xx (never retried) before the request touches the
            # queue. The canary split advances HERE, exactly once per
            # request (admission only re-checks liveness).
            try:
                name, ver = self._policies.resolve(req.policy)
            except UnknownPolicyError as e:
                self.tracer.unbind_trace(req.rid)
                fut.set_exception(e)
                return fut
            req.policy, req.policy_version = name, ver
        bs = self.cache_config.page_size
        if len(req.input_ids) >= self.config.max_model_len:
            fut.set_exception(
                ValueError(
                    f"prompt length {len(req.input_ids)} >= max_model_len "
                    f"{self.config.max_model_len}"
                )
            )
            return fut
        if -(-len(req.input_ids) // bs) + 1 > self.cache_config.num_pages:
            fut.set_exception(
                ValueError(
                    f"prompt needs more pages than the pool has "
                    f"({self.cache_config.num_pages} x {bs} tokens)"
                )
            )
            return fut
        # bounded admission queue (traffic plane): overflow sheds BULK at
        # the bound and interactive only past twice the bound — queueing
        # unboundedly behind max_num_seqs turns saturation into silent
        # multi-minute tail latency for everyone. Suffix-resume
        # continuations are never shed (they carry client-side progress
        # a 429 would strand). The queue-depth read is racy vs the loop
        # thread, which only makes the bound soft by one or two entries.
        bound = int(getattr(self.config, "max_queued_requests", 0) or 0)
        if bound > 0 and not req.resumed:
            pending_snapshot = list(self._pending)
            queued = self._admit_queue.qsize() + len(pending_snapshot)
            if req.priority != "bulk":
                # the interactive limit must not count RESUMED entries:
                # they bypass the bound themselves (a post-pause resume
                # storm would otherwise shed the protected class while
                # admitting unlimited exempt bulk — priority inversion)
                with self._aq_lock:
                    resumed_q = self._aq_resumed
                resumed_q += sum(
                    r.resumed for r in pending_snapshot
                )
                queued = max(0, queued - resumed_q)
            limit = bound if req.priority == "bulk" else 2 * bound
            if queued >= limit:
                retry_after = float(
                    getattr(self.config, "shed_retry_after_s", 1.0)
                )
                with self._aq_lock:  # handler threads race here too
                    self.requests_shed_total += 1
                self.tracer.instant(
                    "shed", req.rid, sched_class=req.priority,
                    tenant=req.tenant, queued=queued,
                )
                self.tracer.unbind_trace(req.rid)
                fut.set_exception(
                    AdmissionRejectedError(
                        f"admission queue full ({queued} >= {limit} for "
                        f"class {req.priority}); retry after "
                        f"{retry_after}s",
                        retry_after=retry_after,
                        sched_class=req.priority,
                    )
                )
                return fut
        with self._aq_lock:
            # both counters under the lock: concurrent handler threads
            # must not lose submitted_total increments
            self._class_submitted[req.priority] += 1
            self._aq_class[req.priority] += 1
            if req.resumed:
                self._aq_resumed += 1
        self._admit_queue.put(req)
        return fut

    def generate(self, payload: Dict[str, Any], timeout: float = 3600.0) -> Dict:
        return self.submit(payload).result(timeout=timeout)

    def pause(self):
        """Abort in-flight requests; stop admitting until continue."""
        done = Future()
        if not self._paused.is_set():
            self._pause_start = time.monotonic()
        self._paused.set()
        self._command_queue.put(("abort_all", None, done))
        done.result(timeout=60)

    def continue_generation(self):
        self._paused.clear()
        t0, self._pause_start = self._pause_start, None
        if t0 is not None:
            self.tracer.record(
                "pause_window", "__engine__", t0, time.monotonic(),
                model_version=self.model_version,
            )

    def streams_weight_updates(self, method: str = "chunk") -> bool:
        """True when ``method`` ("chunk" | "disk" | "tensors") takes the
        zero-pause streamed route on this engine. The tensors path needs
        single-device serving (its donation-safe copy would race the
        loop thread's jit cache under TP); chunk/disk stream anywhere.
        A stopped engine always uses the legacy command path — there is
        no loop to apply a flip."""
        if not (self._weights_streaming and self._running):
            return False
        if method == "tensors":
            return self.mesh is None
        return True

    def update_weights_from_disk(self, path: str, version: Optional[int] = None):
        if self.streams_weight_updates("disk"):
            # load + place on THIS (handler) thread while decode runs;
            # the loop applies the flip between dispatches and the
            # future resolves once the new version serves
            host = hf_io.load_params(
                path, self.model_config, dtype=self.dtype
            )
            placed = self._place_params(host)
            # a half-streamed chunked push is now obsolete: drop its
            # staged leaves (same supersede rule as the legacy path) so
            # they don't sit pinned until the TTL — and so its straggler
            # chunks can't later queue a stale flip
            self.weights.abort_staging("superseded by disk update")
            v = version if version is not None else self.model_version + 1
            out = self.weights.queue_flip(v, placed).result(timeout=600)
            logger.info(
                f"weights streamed from {path} → v{out} (no pause)"
            )
            return out
        done = Future()
        self._command_queue.put(("update_weights", (path, version), done))
        return done.result(timeout=600)

    def update_weights_from_tensors(
        self, params: Params, version: Optional[int] = None
    ):
        """Colocated path: swap in an already-materialized param pytree
        (role of the reference's NCCL broadcast receive path). The
        caller may later DONATE the source buffers, so both routes copy."""
        if self.streams_weight_updates("tensors"):
            # single-device only (streams_weight_updates gates it), so
            # this is the jit-cache-free branch of the placed copy —
            # safe off the loop thread
            copied = self._copy_params_placed(params)
            self.weights.abort_staging("superseded by tensor update")
            v = version if version is not None else self.model_version + 1
            return self.weights.queue_flip(v, copied).result(timeout=600)
        done = Future()
        self._command_queue.put(("update_weights_tensors", (params, version), done))
        return done.result(timeout=600)

    def update_weights_chunk(self, header: Dict, arrays: Dict[str, Any]):
        """Device-path receive: stage one FFD chunk of host tensors; the
        final chunk assembles + swaps the full pytree (reference NCCL
        receive side, areal/engine/sglang_remote.py:411). Streaming
        engines stage into the WeightStore's shadow buffer on this
        thread — decode never stops — and flip at a dispatch boundary;
        legacy engines stage on the loop thread under the pause."""
        if self.streams_weight_updates("chunk"):
            t0 = time.monotonic()
            self.weights.sweep()
            out = self.weights.ingest_chunk(
                header, arrays, self._place_leaf
            )
            if self.tracer.enabled:
                self.tracer.record(
                    "weight_stream_chunk", "__engine__", t0,
                    time.monotonic(),
                    chunk_index=int(header["chunk_index"]),
                    n_chunks=int(header["n_chunks"]),
                    leaves=len(arrays),
                    bytes=sum(
                        int(spec.get("nbytes", 0))
                        for spec in header.get("params", [])
                    ),
                    model_version=int(header["version"]),
                )
            if out is None:
                return {"staged": self.weights.staged_chunks}
            version, tree = out
            v = self.weights.queue_flip(version, tree).result(timeout=600)
            return {"version": v, "complete": True}
        done = Future()
        self._command_queue.put(("update_weights_chunk", (header, arrays), done))
        return done.result(timeout=600)

    # ------------------------------------------------------------------
    # Multi-policy plane (r19): named-handle weight pushes + lifecycle.
    # All of these run on HTTP handler threads — the registry is
    # thread-safe and a push never touches self.params, so there is no
    # flip, no pipeline drain, and NO pause span by construction: a new
    # named version simply starts serving at its next admission wave.
    # ------------------------------------------------------------------
    def _check_policy_capable(self):
        if not self._compact_enabled:
            # named cohorts ride the row-gathered (compact) decode
            # dispatch; the full-slot TP dispatch cannot split params
            # per cohort
            raise RuntimeError(
                "multi-policy serving needs the compacted decode "
                "dispatch (single-device serving with "
                "decode_compact=true)"
            )

    def update_policy_from_disk(
        self,
        name: str,
        path: str,
        version: Optional[int] = None,
        canary_fraction: float = 0.0,
    ) -> int:
        """Install checkpoint ``path`` on named line ``name`` (register
        on first push; ``canary_fraction > 0`` stages it as the line's
        canary at that traffic split). Load + place happen on THIS
        handler thread while decode runs."""
        self._check_policy_capable()
        host = hf_io.load_params(path, self.model_config, dtype=self.dtype)
        placed = self._place_params(host)
        v = self._policies.push(
            name, placed, version=version,
            canary_fraction=canary_fraction,
        )
        self.tracer.instant(
            "policy_push", "__engine__", policy=name, version=v,
            canary_fraction=canary_fraction,
        )
        return v

    def update_policy_chunk(
        self, name: str, header: Dict, arrays: Dict[str, Any]
    ):
        """Streamed FFD-chunk push targeting a named line (the wire
        format of ``update_weights_chunk`` plus a policy name; the
        final chunk's header may carry ``canary_fraction``)."""
        self._check_policy_capable()
        out = self._policies.ingest_chunk(
            name, header, arrays, self._place_leaf
        )
        if out is None:
            return {"staged": int(header["chunk_index"]) + 1}
        self.tracer.instant(
            "policy_push", "__engine__", policy=name, version=out,
            canary_fraction=float(header.get("canary_fraction", 0.0)),
        )
        return {"version": out, "complete": True, "policy": name}

    def promote_policy(self, name: str) -> int:
        """Canary → stable on line ``name``. Registry state only: no
        buffer movement, no pause span, and the promoted version's KV
        namespace survives (its version int is unchanged)."""
        v = self._policies.promote(name)
        self.tracer.instant(
            "policy_promote", "__engine__", policy=name, version=v
        )
        return v

    def retire_policy(self, name: str):
        self._policies.retire(name)
        self.tracer.instant(
            "policy_retire", "__engine__", policy=name
        )

    def set_policy_split(self, name: str, canary_fraction: float):
        self._policies.set_split(name, canary_fraction)

    def policy_status(self) -> Dict[str, Any]:
        return self._policies.stats()

    def precompile(self) -> Optional[Dict[str, Any]]:
        """AOT-precompile the shape ladder per ``config.precompile``
        (off | ladder | replay). Safe to run concurrently with serving
        — /health reports ``warming`` with rising coverage until the
        ladder lands, then latches ready with zero traffic. Returns the
        precompiler summary (None when mode is off); a mismatched
        replay stream raises ``precompile_lib.ReplayMismatchError``."""
        pc = getattr(self.config, "precompile", None)
        mode = getattr(pc, "mode", "off") if pc is not None else "off"
        if mode == "off":
            return None
        return precompile_lib.Precompiler(self).run(
            mode, replay_path=getattr(pc, "replay_path", "")
        )

    def readiness(self) -> Dict[str, Any]:
        """Server readiness for /health: ``warming`` while the initial
        compile storm runs, ``ready`` after.

        Warming begins at the FIRST observed XLA compile (an idle fresh
        server is ready — it has nothing to warm yet, and reporting
        warming before any traffic would deadlock it out of rotation
        forever) and ends when the shape ladder is covered, the engine
        goes ``ready_quiet_s`` without compiling, or it has COMPLETED
        ``ready_min_requests`` requests end-to-end (under sustained
        traffic a serving engine may never see a compile-quiet window —
        successfully finishing requests is the stronger proof). Ready
        LATCHES: a long-serving engine compiling one incremental shape
        must not drop out of fleet rotation mid-run — readiness answers
        "is the cold-start storm over", not "did anything ever compile
        again". An AOT-precompiled or warmup-driven engine therefore
        reports warming from its first startup compile until its
        ladder lands or its first real completions prove it serves."""
        now = time.monotonic()
        cov = self.compiles.coverage()
        quiet = self.compiles.quiet_s(now)  # inf before the 1st compile
        served = (
            self._ready_min_requests > 0
            and self._completed_requests >= self._ready_min_requests
        )
        ready = (
            self._ready_latched
            or cov >= 1.0
            or served
            or quiet >= self._ready_quiet_s
        )
        if ready and (cov >= 1.0 or served or quiet != float("inf")):
            # latch only once a real warmup ran its course — an idle
            # fresh server is *servable* but still cold, and its first
            # compile storm must still read as warming
            if not self._ready_latched:
                self._ready_latched = True
                # cold-start timeline mark for trace_report --coldstart:
                # the events stream now spans header → compiles → ready
                self.compiles.append_event(
                    {
                        "kind": "lifecycle",
                        "event": "ready",
                        "ladder_coverage": round(cov, 4),
                        "compiles_total": self.compiles.compiles_total,
                        "uncached_total": (
                            self.compiles.uncached_compiles_total
                        ),
                        "cache_hits_total": (
                            self.compiles.cache_hits_total
                        ),
                    }
                )
        return {
            "state": "ready" if ready else "warming",
            "ladder_coverage": round(cov, 4),
            "compiled_shapes": self.compiles.compiled_shapes(),
            "shape_ladder_size": self.compiles.ladder_size,
            "warmup_eta_s": self.compiles.warmup_eta_s(),
            "quiet_s": round(min(quiet, now - self._started_at), 3),
        }

    def latency_histograms(self) -> Dict[str, Histogram]:
        """Per-class native Prometheus histograms keyed the way
        ``render_prometheus(histograms=...)`` wants them."""
        return {
            f'{name}{{sched_class="{cls}"}}': h
            for name, per_cls in self._hists.items()
            for cls, h in per_cls.items()
        }

    def metrics(self) -> Dict[str, float]:
        num_pages = max(1, self.cache_config.num_pages)
        m = dict(
            running_requests=len(self._active),
            queued_requests=self._admit_queue.qsize() + len(self._pending),
            free_slots=len(self._free_slots),
            free_pages=self.pm.n_free,
            # fraction of the pool holding live KV (active slots + parked
            # prefix-registry pages + the reserved trash page)
            kv_page_utilization=1.0 - self.pm.n_free / num_pages,
            registry_entries=len(self.registry),
            # EWMA throughput over recent dispatches (0 while idle-fresh)
            decode_tokens_per_sec=round(self._decode_tps, 2),
            prefill_tokens_per_sec=round(self._prefill_tps, 2),
            # decode tail compaction occupancy: rows the last chunk
            # dispatched vs rows carrying live requests, plus lifetime
            # totals (rows_active/rows_dispatched → mean occupancy)
            decode_rows_dispatched=self._decode_rows_dispatched,
            decode_rows_active=self._decode_rows_active,
            total_decode_chunks=self.total_decode_chunks,
            total_rows_dispatched=self.total_rows_dispatched,
            total_rows_active=self.total_rows_active,
            decode_occupancy=round(
                self.total_rows_active
                / max(1, self.total_rows_dispatched), 4
            ),
            total_generated_tokens=self.total_generated_tokens,
            total_prompt_tokens=self.total_prompt_tokens,
            total_cached_prompt_tokens=self.total_cached_prompt_tokens,
            # prefix-cache observability (radix and flat modes alike):
            # token-level hit rate is the sibling-dedup + claim signal,
            # claim-level is the tree's match success rate
            prefix_cache_hit_rate=round(
                self.total_cached_prompt_tokens
                / max(1, self.total_prompt_tokens), 4
            ),
            prefix_cached_tokens_total=self.total_cached_prompt_tokens,
            prefix_claim_hit_rate=round(
                getattr(self.registry, "hits", 0)
                / max(1, getattr(self.registry, "claims", 0)), 4
            ),
            prefix_cache_nodes=len(self.registry),
            prefix_cache_pages=getattr(
                self.registry, "pages", len(self.registry)
            ),
            prefix_cow_copies_total=self.total_cow_copies,
            prefix_evicted_pages_total=getattr(
                self.registry, "evicted_pages", 0
            ),
            total_requests=self.total_requests,
            total_aborted=self.total_aborted,
            total_preemptions=self.total_preemptions,
            requests_shed_total=self.requests_shed_total,
            deadline_preemptions_total=self.deadline_preemptions_total,
            deadline_misses_total=self.deadline_misses_total,
            model_version=self.model_version,
            paused=float(self._paused.is_set()),
            # zero-pause weight plane (r13): shadow staging + pinned
            # old-version buffers + applied flips
            weight_staging_bytes=self.weights.staging_bytes,
            weight_staging_aborts_total=float(
                self.weights.staging_aborts_total
            ),
            weight_pinned_requests=float(self.weights.pinned_requests()),
            weight_buffer_versions=float(
                len(self.weights.buffer_versions())
            ),
            weight_flips_total=float(self.weights.flips_total),
            trace_spans=len(self.tracer) if self.tracer.enabled else 0,
            # ring-buffer overflow count: a truncated trace must be
            # VISIBLY truncated, not silently missing its oldest spans
            tracing_dropped_spans_total=float(self.tracer.dropped),
        )
        # goodput attribution (r11): exclusive wall-time bucket
        # fractions + duty cycle + effective tok/s, recompile bill, and
        # the readiness gauge the fleet plane mirrors from /health
        m.update(self.ledger.metrics())
        m.update(self.compiles.metrics())
        m["server_ready"] = float(self.readiness()["state"] == "ready")
        # per-class composition (traffic plane): running from an active
        # snapshot, queued = admit-queue class counters + a pending-list
        # scan (both metrics-grade racy reads — the loop thread owns the
        # structures)
        active_reqs = list(self._active.values())
        pending_reqs = list(self._pending)
        with self._aq_lock:
            aq = dict(self._aq_class)
        for cls in SCHED_CLASSES:
            m[f"sched_class_{cls}_running"] = sum(
                r.priority == cls for r in active_reqs
            )
            m[f"sched_class_{cls}_queued"] = max(0, aq[cls]) + sum(
                r.priority == cls for r in pending_reqs
            )
            m[f"sched_class_{cls}_submitted_total"] = (
                self._class_submitted[cls]
            )
        if self._chunk_budget > 0:
            # chunked-prefill surface (r15): present ONLY when chunking
            # resolved on — chunking off is a strict no-op, metric keys
            # included
            m.update(
                prefill_chunks_total=self.prefill_chunks_total,
                prefill_chunk_preemptions_total=(
                    self.prefill_chunk_preemptions_total
                ),
                # 1 while EVERY admission dispatch so far stayed within
                # ~one chunk of prefill — a stall-escape admission
                # (uncapped dispatch under cache thrash) zeroes it, so
                # the gauge is a measurement of the serving history,
                # not a config echo
                ttft_bounded=float(
                    self.prefill_chunk_stall_escapes == 0
                ),
            )
        if self._spec_configured:
            # spec gauges exist ONLY when speculation is configured —
            # spec off is a strict no-op, metric surface included
            gate = self._spec_gate
            m.update(
                spec_enabled=float(not gate.disabled),
                spec_chunks_total=self.total_spec_chunks,
                spec_draft_tokens_total=self.spec_draft_tokens_total,
                spec_accepted_tokens_total=self.spec_accepted_tokens_total,
                spec_accept_rate=round(
                    self.spec_accepted_tokens_total
                    / max(1, self.spec_draft_tokens_total), 4
                ),
                spec_accept_rate_ewma=round(gate.ewma or 0.0, 4),
            )
        if self._kv_tiers is not None:
            # KV tier surface (r16): present ONLY with kv_spill on —
            # spill off is a strict no-op, metric keys included
            t = self._kv_tiers
            m.update(
                kv_tier_host_pages=t.host_pages,
                kv_tier_host_bytes=t.host_bytes_used,
                kv_tier_host_capacity_bytes=t.host_capacity,
                kv_tier_pending_pages=t.pending_pages,
                kv_tier_spilled_pages_total=t.spilled_pages_total,
                kv_tier_spilled_bytes_total=t.spilled_bytes_total,
                kv_tier_promoted_pages_total=t.promoted_pages_total,
                kv_tier_promoted_bytes_total=t.promoted_bytes_total,
                kv_tier_dropped_pages_total=t.dropped_pages_total,
                kv_tier_dropped_bytes_total=t.dropped_bytes_total,
                kv_tier_host_claim_hits_total=t.claims_promoted_total,
                # fraction of claims that touched the host tier — the
                # "returning session saved by spill" signal
                kv_tier_host_claim_hit_rate=round(
                    t.claims_promoted_total
                    / max(1, getattr(self.registry, "claims", 0)), 4
                ),
                kv_tier_host_cached_tokens_total=(
                    self.total_host_cached_tokens
                ),
                kv_tier_disk_pages=t.disk_pages,
                kv_tier_disk_bytes=t.disk_bytes_used,
                kv_tier_disk_spilled_pages_total=t.disk_spilled_pages_total,
                kv_tier_disk_loaded_pages_total=t.disk_loaded_pages_total,
            )
        if self._kv_ship:
            # shipping surface (r16): present ONLY with kv_ship on
            m.update(
                kv_ship_exports_total=self.kv_ship_exports_total,
                kv_ship_imports_total=self.kv_ship_imports_total,
                kv_ship_pages_out_total=self.kv_ship_pages_out_total,
                kv_ship_pages_in_total=self.kv_ship_pages_in_total,
                kv_ship_failures_total=self.kv_ship_failures_total,
            )
        if self._policies.active:
            # multi-policy surface (r19): present ONLY once a named
            # policy has been pushed — single-policy mode is a strict
            # no-op, metric keys included. Literal kwargs (not a blind
            # dict merge) so ARL003's static extraction sees every name.
            pstats = self._policies.metrics()
            m.update(
                policy_lines=pstats["policy_lines"],
                policy_buffers_resident=pstats["policy_buffers_resident"],
                policy_buffers_host=pstats["policy_buffers_host"],
                policy_pinned_requests=pstats["policy_pinned_requests"],
                policy_pushes_total=pstats["policy_pushes_total"],
                policy_promotes_total=pstats["policy_promotes_total"],
                policy_demotions_total=pstats["policy_demotions_total"],
                policy_reloads_total=pstats["policy_reloads_total"],
                policy_staging_bytes=pstats["policy_staging_bytes"],
                policy_cache_namespaces=float(len(self._policy_caches)),
            )
        return m

    # ------------------------------------------------------------------
    # Engine loop (single owner of device state)
    # ------------------------------------------------------------------
    def _loop(self):
        # compiles fired outside an explicit dispatch_scope (helper jits
        # like pack_host) still attribute to this engine's tracker
        goodput.set_thread_tracker(self.compiles, phase="engine")
        led = self.ledger
        while self._running:
            self._maybe_start_profile()
            did_flip = False
            if self.weights.flip_pending:
                # the atomic weight flip — what remains of the old pause
                # window; booking it to weight_pause keeps the ledger
                # honest about how little that is (one pipeline drain)
                with led.bucket("weight_pause"):
                    did_flip = self._maybe_flip_weights()
            self._sweep_tick += 1
            if self._sweep_tick >= 256:
                # abandoned-staging TTL sweep (cheap, amortized): a
                # client that died mid-stream must not pin staging
                self._sweep_tick = 0
                self.weights.sweep()
                if self._policies.active:
                    self._policies.sweep()
            if self._policies.dirty:
                # a push/promote/retire superseded a (policy, version):
                # its KV namespace is garbage for future claimants —
                # flush it here because the loop thread owns the
                # namespace map (the registry only signals)
                self._flush_retired_policies()
            if self._paused.is_set() or not self._command_queue.empty():
                # command work (weight swaps, aborts) and every paused
                # moment book to weight_pause — the capacity a weight
                # update takes from serving, measured from the server's
                # own clock
                with led.bucket("weight_pause"):
                    did_work = self._drain_commands() or did_flip
            else:
                did_work = self._drain_commands() or did_flip
            if not self._paused.is_set():
                if (
                    self._pending
                    or self._active
                    or not self._admit_queue.empty()
                ):
                    with led.bucket("prefill"):
                        did_work |= self._admit()
                else:
                    did_work |= self._admit()
                did_work |= self._decode()  # buckets decode/spec inside
            self._maybe_stop_profile(did_work)
            if not did_work:
                # idle/pause gap: the decode-rate EWMA must not absorb it
                # (the next chunk's dt would span the whole quiet period
                # and crater the gauge)
                self._last_decode_mark = None
                with led.bucket(
                    "weight_pause" if self._paused.is_set() else "idle"
                ):
                    time.sleep(0.001)
        self._maybe_stop_profile(did_work=True, force=True)
        goodput.set_thread_tracker(None)

    # ------------------------------------------------------------------
    # On-demand profiler capture (POST /profile)
    # ------------------------------------------------------------------
    def request_profile(self, steps: int, out_dir: Optional[str] = None) -> str:
        """Arm a jax.profiler capture of the next ``steps`` BUSY engine
        loop iterations (admission/decode/command work; idle spins don't
        count). Returns the directory the XPlane trace will land in.
        One capture at a time — a second request while armed/running is
        an error, not a silent re-arm."""
        from areal_tpu.api.cli_args import ProfilingConfig
        from areal_tpu.utils.profiling import PhaseProfiler

        if steps <= 0:
            raise ValueError(f"profile steps must be positive, got {steps}")
        if out_dir is None:
            import tempfile

            out_dir = tempfile.mkdtemp(prefix="areal_tpu_profile_")
        prof = PhaseProfiler(
            ProfilingConfig(enabled=True, steps=[0]), out_dir, "", ""
        )
        trace_dir = os.path.join(prof.trace_root, "step0")
        with self._profile_lock:
            # check-and-arm atomically: concurrent POST /profile handler
            # threads must not silently overwrite each other's capture
            if (
                self._profile_pending is not None
                or self._profile_stack is not None
            ):
                raise RuntimeError(
                    "a profile capture is already in progress"
                )
            self._profile_pending = (int(steps), prof)
        return trace_dir

    def _maybe_start_profile(self):
        with self._profile_lock:
            if (
                self._profile_pending is None
                or self._profile_stack is not None
            ):
                return
            steps, prof = self._profile_pending
            # pending → running in one critical section: request_profile
            # sees exactly one of the two slots occupied at all times
            import contextlib

            stack = contextlib.ExitStack()
            self._profile_stack = stack
            self._profile_pending = None
            self._profile_left = steps
        stack.enter_context(prof.step(0))

    def _maybe_stop_profile(self, did_work: bool, force: bool = False):
        if self._profile_stack is None:  # loop thread owns the stack
            return
        if did_work:
            self._profile_left -= 1
        if force or self._profile_left <= 0:
            with self._profile_lock:
                stack, self._profile_stack = self._profile_stack, None
            try:
                stack.close()
            except Exception as e:  # profiling must never kill serving
                logger.warning(f"profiler stop failed: {e}")

    def _maybe_flip_weights(self) -> bool:
        """Apply a pending streamed weight flip at a dispatch boundary
        (loop thread). The pipeline is drained first — bounded by
        ``decode_pipeline`` in-flight chunks, milliseconds, with no
        client-visible abort — so chunk version attribution stays exact;
        then the swap is a pointer flip plus a registry flush. Under
        ``flip_policy="pin"`` the requests in flight keep decoding on
        the outgoing buffer (one store pin each; the decode loop
        dispatches each version cohort with its own params); under
        ``"resume"`` they resolve with ``stop_reason="abort"`` and the
        client's suffix-resume loop continues them on the new version —
        either way every token's recorded weight version is exact."""
        flip = self.weights.take_flip()
        if flip is None:
            return False
        version, params, fut = flip
        t0 = time.monotonic()
        try:
            if version < self.model_version:
                raise ValueError(
                    f"stale weight flip: v{version} < served "
                    f"v{self.model_version}"
                )
            self._drain_pipeline()
            policy = self._wt_cfg.flip_policy
            if policy == "pin" and not self._compact_enabled:
                # pinning needs the compacted (cohort-capable) decode
                # dispatch; full-slot engines abort-and-resume instead
                policy = "resume"
            old_version, old_params = self.model_version, self.params
            pinned = 0
            if policy == "resume":
                # a default-line flip only aborts DEFAULT-line requests:
                # named policy cohorts decode on their own registry
                # buffers and are untouched (a canary push on `actor`
                # must not disturb `opponent` traffic — same rule)
                for slot in list(self._active):
                    if not self._active[slot].policy:
                        self._finish(slot, "abort")
            elif version != old_version:
                for req in self._active.values():
                    if not req.policy and req.weight_version == old_version:
                        self.weights.retain(old_version, old_params)
                        pinned += 1
            self.params = params
            self.model_version = version
            # cached KV (radix tree included) is old-policy: a new
            # claimant must never ride it. Active slots' own pages are
            # request-owned and survive the flush.
            self.registry.flush(self.pm)
            self.weights.flips_total += 1
            now = time.monotonic()
            self.tracer.record(
                "weight_update", "__engine__", t0, now, cmd="flip",
                model_version=version,
            )
            self.tracer.instant(
                "weight_flip", "__engine__", model_version=version,
                policy=policy, pinned=pinned,
                flip_ms=round((now - t0) * 1e3, 3),
            )
            logger.info(
                f"weights flipped → v{version} (policy={policy}, "
                f"{pinned} request(s) pinned to v{old_version}, "
                f"{(now - t0) * 1e3:.1f} ms, no pause)"
            )
            fut.set_result(version)
        except Exception as e:
            fut.set_exception(e)
        return True

    def _fence_unpaused_swap(self) -> None:
        """Guard the LEGACY (command-path) weight swaps against a live
        engine: a streamed client never pauses, so a
        ``--no-weight-streaming`` server can receive a swap mid-decode —
        silently continuing in-flight slots on old KV + new weights
        (unpinned, mis-stamped) would corrupt the version fence. Abort
        them into the suffix-resume contract instead; under the legacy
        paused protocol the pause already aborted everything, so this
        is a no-op there."""
        default_slots = [
            sl for sl, r in self._active.items() if not r.policy
        ]
        if default_slots and not self._paused.is_set():
            logger.warning(
                f"legacy weight swap on an unpaused engine: aborting "
                f"{len(default_slots)} in-flight request(s) into "
                f"suffix-resume (enable weights.streaming for "
                f"zero-pause flips)"
            )
            # named policy cohorts keep decoding: the swap replaces the
            # DEFAULT line's params only, and their KV namespaces are
            # (policy, version)-keyed
            for slot in default_slots:
                self._finish(slot, "abort")

    def _drain_commands(self) -> bool:
        did = False
        while True:
            try:
                cmd, arg, done = self._command_queue.get_nowait()
            except queue.Empty:
                return did
            did = True
            t_cmd = time.monotonic()
            try:
                # every command needs a quiesced device pipeline: aborts
                # must not race in-flight chunks, and weight swaps would
                # mis-attribute in-flight tokens to the new version
                self._drain_pipeline()
                if cmd == "abort_all":
                    for slot in list(self._active):
                        self._finish(slot, "abort")
                    done.set_result(True)
                elif cmd == "update_weights":
                    path, version = arg
                    self._fence_unpaused_swap()
                    host = hf_io.load_params(
                        path, self.model_config, dtype=self.dtype
                    )
                    self.params = self._place_params(host)
                    # cached KV is from the old policy — never reuse it;
                    # drop any abandoned device-path staging too
                    self.registry.flush(self.pm)
                    self._staged = {}
                    self._staging_key = None
                    self.weights.abort_staging("superseded by disk update")
                    self.model_version = (
                        version
                        if version is not None
                        else self.model_version + 1
                    )
                    logger.info(
                        f"weights updated from {path} → v{self.model_version}"
                    )
                    done.set_result(self.model_version)
                elif cmd == "update_weights_chunk":
                    header, arrays = arg
                    version = int(header["version"])
                    # key staging on (version, n_chunks): a retry with a
                    # different FFD grouping must not merge stale leaves
                    stage_key = (version, int(header["n_chunks"]))
                    if getattr(self, "_staging_key", None) != stage_key:
                        self._staging_key = stage_key
                        self._staged: Dict[str, Any] = {}
                        self._staged_chunks = set()
                    self._staged.update(arrays)
                    self._staged_chunks.add(int(header["chunk_index"]))
                    if len(self._staged_chunks) < int(header["n_chunks"]):
                        done.set_result({"staged": len(self._staged_chunks)})
                        continue
                    from areal_tpu.utils.weight_transfer import (
                        unflatten_params,
                    )

                    self._fence_unpaused_swap()
                    host = jax.tree_util.tree_map(
                        lambda a: jnp.asarray(a, dtype=self.dtype),
                        unflatten_params(self._staged),
                    )
                    self.params = self._place_params(host)
                    self._staged = {}
                    self._staged_chunks = set()
                    self._staging_key = None
                    self.model_version = version
                    self.registry.flush(self.pm)
                    logger.info(
                        f"weights updated via device path → v{version}"
                    )
                    done.set_result({"version": version, "complete": True})
                elif cmd == "update_weights_tensors":
                    params, version = arg
                    self._fence_unpaused_swap()
                    # the caller may later DONATE these buffers — copy
                    self.params = self._copy_params_placed(params)
                    self.registry.flush(self.pm)
                    self._staged = {}
                    self._staging_key = None
                    self.weights.abort_staging(
                        "superseded by tensor update"
                    )
                    self.model_version = (
                        version
                        if version is not None
                        else self.model_version + 1
                    )
                    done.set_result(self.model_version)
                elif cmd == "kv_export":
                    done.set_result(self._kv_export(arg))
                elif cmd == "kv_import":
                    done.set_result(self._kv_import(*arg))
                else:  # pragma: no cover
                    done.set_exception(ValueError(f"unknown command {cmd}"))
                if cmd.startswith("update_weights"):
                    self.tracer.record(
                        "weight_update", "__engine__", t_cmd,
                        time.monotonic(), cmd=cmd,
                        model_version=self.model_version,
                    )
            except Exception as e:  # surface errors to the caller
                done.set_exception(e)

    # ------------------------------------------------------------------
    # Page accounting
    # ------------------------------------------------------------------
    def _alloc_pages(self, n: int) -> Optional[List[int]]:
        """Allocate n pages, evicting the prefix registry if needed
        (default namespace first — it is the hot one — then the named
        policy namespaces)."""
        pages = self.pm.alloc(n)
        if pages is None:
            self.registry.evict(self.pm, n)
            for cache in self._policy_caches.values():
                if self.pm.n_free >= n:
                    break
                cache.evict(self.pm, n - self.pm.n_free)
            pages = self.pm.alloc(n)
        return pages

    # ------------------------------------------------------------------
    # Multi-policy plane (r19): per-(policy, version) KV namespaces
    # ------------------------------------------------------------------
    def _policy_cache(self, name: str, version: int):
        """The prefix cache for one (policy, version) namespace, built
        lazily on first admission. Same mode/grain as the default
        registry, same page pool — isolation is by construction: claims
        and publishes never cross namespaces, so a canary's pages can
        never serve the stable line (or vice versa)."""
        key = (name, version)
        cache = self._policy_caches.get(key)
        if cache is None:
            bs = self.cache_config.page_size
            if self._radix:
                from areal_tpu.ops.paged_attention import pack_factor

                cache = RadixPrefixCache(
                    bs, self.config.prefix_reuse_min,
                    grain=pack_factor(self.model_config.head_dim),
                )
            else:
                cache = PrefixRegistry(bs, self.config.prefix_reuse_min)
            self._policy_caches[key] = cache
        return cache

    def _flush_retired_policies(self):
        """Flush KV namespaces whose (policy, version) no longer serves
        (superseded by a push, or the line retired). Loop thread only —
        it owns the namespace map. Active slots' pages are request-owned
        and survive (same contract as the default registry flush at a
        weight flip)."""
        for key in self._policies.drain_retired():
            cache = self._policy_caches.pop(key, None)
            if cache is not None:
                cache.flush(self.pm)

    # ------------------------------------------------------------------
    # Hierarchical KV tiers (r16): demotion gather / promotion scatter,
    # and the cross-server prefix shipping export/import pair
    # ------------------------------------------------------------------
    def _gather_pages_host(self, pages: List[int]):
        """Blocking device→host read of ``pages``: [L, Hp, n, rows,
        lane] per tensor in the pool's native layout. The device_get
        orders after every dispatched write to those pages, so demotion
        snapshots and exports always see committed content."""
        n = len(pages)
        pad = data_utils.next_bucket_size(n, 8)
        idx = np.zeros(pad, np.int32)  # padding reads the trash page
        idx[:n] = pages
        idx_dev = jnp.asarray(idx)
        with goodput.dispatch_scope(
            self.compiles, "kv_gather", precompile_lib.kv_gather_sig(pad)
        ):
            k, v = model_runner.gather_pages(self.cache, idx_dev)
        k = np.asarray(jax.device_get(k))[:, :, :n]
        v = np.asarray(jax.device_get(v))[:, :, :n]
        return k, v

    def _scatter_pages(self, pages: List[int], k_pool, v_pool) -> None:
        """One batched host→device write of pool-layout page data into
        ``pages`` (promotion flush and shipping import share it)."""
        n = len(pages)
        pad = data_utils.next_bucket_size(n, 8)
        num_pages = self.cache_config.num_pages
        nl, hp, _, rows, lane = self.cache["k"].shape
        dt = self.cache["k"].dtype
        dst = np.full(pad, num_pages, np.int32)
        dst[:n] = pages
        k_np = np.zeros((nl, hp, pad, rows, lane), dt)
        v_np = np.zeros_like(k_np)
        k_np[:, :, :n] = k_pool
        v_np[:, :, :n] = v_pool
        dst_dev = jnp.asarray(dst)
        k_dev, v_dev = jnp.asarray(k_np), jnp.asarray(v_np)
        with goodput.dispatch_scope(
            self.compiles, "kv_scatter", precompile_lib.kv_scatter_sig(pad)
        ):
            self.cache = model_runner.scatter_pages(
                self.cache, dst_dev, k_dev, v_dev
            )

    def _flush_kv_promotions(self) -> None:
        """Dispatch every queued spill-tier promotion as one batched
        scatter. MUST run after a claim loop and before any dispatch
        that could read the promoted pages (the wave prefill and the
        COW copies attend through them); flushing when the wave later
        defers is harmless — the pages are tree-owned and resident."""
        if self._kv_tiers is None:
            return
        pend = self._kv_tiers.drain_pending()
        if not pend:
            return
        nl, hp, _, rows, lane = self.cache["k"].shape
        dt = self.cache["k"].dtype
        n = len(pend)
        k_pool = np.zeros((nl, hp, n, rows, lane), dt)
        v_pool = np.zeros_like(k_pool)
        for i, (_page, sp) in enumerate(pend):
            k_pool[:, :, i] = sp.k
            v_pool[:, :, i] = sp.v
        self._scatter_pages([p for p, _ in pend], k_pool, v_pool)

    def _kv_export(self, tokens: List[int]) -> Dict[str, Any]:
        """Loop-thread kv_export command: the longest committed
        full-page prefix of ``tokens``, in the layout-independent
        canonical form ([L, Hkv, T, D] token-major) shipping needs.
        Reads replicas only — no refcount or LRU effects; spilled pages
        are served straight from the host/disk tier."""
        from areal_tpu.inference import kv_tiers as kv_tiers_lib

        bs = self.cache_config.page_size
        out: Dict[str, Any] = {
            "pages": 0,
            "tokens_matched": 0,
            "page_size": bs,
            "model_version": self.model_version,
        }
        if not self._radix:
            return out
        # promoted-but-unflushed pages hold garbage on device and truth
        # in the pending queue — flush first so resident means readable
        self._flush_kv_promotions()
        nodes = self.registry.match_pages(np.asarray(tokens, np.int32))
        if not nodes:
            return out
        nl, hp, _, rows, lane = self.cache["k"].shape
        dt = self.cache["k"].dtype
        n = len(nodes)
        k_all = np.zeros((nl, hp, n, rows, lane), dt)
        v_all = np.zeros_like(k_all)
        res_idx = [i for i, nd in enumerate(nodes) if nd.page is not None]
        if res_idx:
            k_res, v_res = self._gather_pages_host(
                [nodes[i].page for i in res_idx]
            )
            for j, i in enumerate(res_idx):
                k_all[:, :, i] = k_res[:, :, j]
                v_all[:, :, i] = v_res[:, :, j]
        for i, nd in enumerate(nodes):
            if nd.page is None:
                k_sp, v_sp = self._kv_tiers.export_data(nd)
                k_all[:, :, i] = k_sp
                v_all[:, :, i] = v_sp
        canon_k = kv_tiers_lib.canonical_from_pool(
            k_all, self.model_config.num_kv_heads,
            self.model_config.head_dim,
        )
        canon_v = kv_tiers_lib.canonical_from_pool(
            v_all, self.model_config.num_kv_heads,
            self.model_config.head_dim,
        )
        out.update(
            pages=n,
            tokens_matched=n * bs,
            dtype=canon_k.dtype.name,
            k=canon_k,
            v=canon_v,
        )
        self.kv_ship_exports_total += 1
        self.kv_ship_pages_out_total += n
        return out

    def _kv_import(
        self, tokens: List[int], k, v, src_version: Optional[int]
    ) -> int:
        """Loop-thread kv_import command: re-pack shipped canonical
        pages into this pool's layout, scatter them into freshly
        allocated pages, and hand them to the radix tree as an
        ownership transfer (``add``) — the very next claim serves them
        like any locally-cached prefix. Soft-fails (returns 0) on
        version/geometry mismatch or a dry pool: shipping is an
        optimization, never a correctness dependency."""
        from areal_tpu.inference import kv_tiers as kv_tiers_lib

        if not self._radix:
            return 0
        if (
            src_version is not None
            and int(src_version) != int(self.model_version)
        ):
            # the exporter prefilled under different weights: its KV is
            # another policy's cache, not ours
            self.kv_ship_failures_total += 1
            return 0
        bs = self.cache_config.page_size
        k = np.asarray(k)
        v = np.asarray(v)
        mc = self.model_config
        if (
            k.ndim != 4
            or k.shape[0] != mc.num_layers
            or k.shape[1] != mc.num_kv_heads
            or k.shape[3] != mc.head_dim
            or k.shape[2] % bs
            or k.shape != v.shape
        ):
            self.kv_ship_failures_total += 1
            return 0
        n = min(k.shape[2] // bs, len(tokens) // bs)
        if n <= 0:
            return 0
        dt = self.cache["k"].dtype
        k_pool = kv_tiers_lib.pool_from_canonical(
            np.ascontiguousarray(k[:, :, : n * bs]).astype(dt),
            self.cache["k"].shape,
        )
        v_pool = kv_tiers_lib.pool_from_canonical(
            np.ascontiguousarray(v[:, :, : n * bs]).astype(dt),
            self.cache["v"].shape,
        )
        pages = self._alloc_pages(n)
        if pages is None:
            return 0  # pool dry: the turn just re-prefills
        self._scatter_pages(pages, k_pool, v_pool)
        # ownership transfer: the tree becomes the prefix's only holder
        # (pages duplicating existing tree content are freed by add)
        self.registry.add(
            self.pm, np.asarray(tokens[: n * bs], np.int32), pages
        )
        self.kv_ship_imports_total += 1
        self.kv_ship_pages_in_total += n
        return n * bs

    @property
    def kv_ship_enabled(self) -> bool:
        return self._kv_ship

    def export_prefix(
        self, tokens: List[int], timeout: float = 120.0
    ) -> Dict[str, Any]:
        """Cross-thread kv export (server /kv_export): runs on the loop
        thread behind a pipeline drain, like every engine command."""
        done = Future()
        self._command_queue.put(("kv_export", list(tokens), done))
        return done.result(timeout=timeout)

    def import_prefix(
        self,
        tokens: List[int],
        k,
        v,
        src_version: Optional[int] = None,
        timeout: float = 120.0,
    ) -> int:
        """Cross-thread kv import (server /kv_import and the
        /generate-side ship fetch). Returns tokens entered into the
        prefix cache (0 = soft-dropped)."""
        done = Future()
        self._command_queue.put(
            ("kv_import", (list(tokens), k, v, src_version), done)
        )
        return done.result(timeout=timeout)

    def _preempt_youngest(
        self,
        victims: Optional[tuple] = None,
        reason: str = "pool pressure",
    ) -> bool:
        """Preempt the most recently submitted active request: its pages
        go to the registry (the transparent re-queue usually re-claims
        them) and the request returns to the FRONT of the pending list.
        ``victims`` restricts candidates to those scheduling classes
        (deadline preemption may only evict bulk; pool pressure prefers
        bulk but may fall back to anyone)."""
        candidates = [
            sl for sl, r in self._active.items()
            if victims is None or r.priority in victims
        ]
        if not candidates:
            return False
        slot = max(
            candidates, key=lambda sl: self._active[sl].submit_time
        )
        req = self._active.pop(slot)
        # a pinned victim's pages hold OLD-version KV: parking them in
        # the (already-flushed) registry would let a new-version request
        # claim stale state — release outright, and drop the store pin
        # (the request re-prefills under the current weights). A NAMED
        # victim parks into its own (policy, version) namespace while
        # that pair still serves, and always drops its registry pin.
        if req.policy:
            self._release_slot(
                slot,
                park_tokens=(
                    req.all_tokens
                    if self._policies.is_live(
                        req.policy, req.weight_version
                    )
                    else None
                ),
                ns=(req.policy, req.weight_version),
            )
            self._policies.release(req.policy, req.weight_version)
        else:
            self._release_slot(
                slot,
                park_tokens=(
                    req.all_tokens
                    if req.weight_version == self.model_version
                    else None
                ),
            )
            if req.weight_version != self.model_version:
                self.weights.release(req.weight_version)
        req.slot = None
        req.preemptions += 1
        self.total_preemptions += 1
        self.tracer.instant(
            "preempt", req.rid, tokens_in=len(req.output_ids),
            sched_class=req.priority, reason=reason,
        )
        self._pending.insert(0, req)
        logger.info(
            f"preempted {req.rid} ({len(req.output_ids)} tokens in) — "
            f"{reason}"
        )
        return True

    def _deadline_waiter(self) -> Optional[_Request]:
        """The first queued INTERACTIVE request about to miss its soft
        deadline: inside ``deadline_margin_s`` of it, or having burned
        half its deadline budget waiting. This one predicate drives
        BOTH deadline preemption (evict a running bulk victim) and the
        chunked-prefill scheduler's chunk-boundary deferral (hold the
        next bulk chunk so the wave belongs to the waiter)."""
        margin = float(getattr(self.config, "deadline_margin_s", 0.25))
        now = time.monotonic()
        for r in self._pending:
            if r.priority != "interactive" or r.deadline_at is None:
                continue
            budget = r.deadline_at - r.submit_time
            if (
                now >= r.deadline_at - margin
                or now - r.submit_time >= 0.5 * budget
            ):
                return r
        return None

    def _maybe_deadline_preempt(self) -> bool:
        """Deadline-aware preemption: a queued INTERACTIVE request that
        would miss its soft deadline — already inside the margin, or
        having burned half its deadline budget waiting with no free slot
        — evicts the youngest BULK request. The victim re-queues through
        the existing preemption path (its KV parks in the prefix cache,
        so resuming costs at most one partial-page re-prefill): bulk
        loses latency, never work."""
        now = time.monotonic()
        waiter = self._deadline_waiter()
        if waiter is None:
            return False
        if not any(
            r.priority == "bulk" for r in self._active.values()
        ):
            return False  # nothing shed-able holds a slot
        # preemption needs a quiesced pipeline (in-flight chunks may
        # still write the victim's pages) — and draining may itself
        # finish a request, making the eviction unnecessary
        self._drain_pipeline()
        if self._free_slots:
            return False
        if not self._preempt_youngest(
            victims=("bulk",), reason="deadline"
        ):
            return False
        self.deadline_preemptions_total += 1
        self.tracer.instant(
            "deadline_preempt", waiter.rid,
            deadline_in_s=round(waiter.deadline_at - now, 4),
            waited_s=round(now - waiter.submit_time, 4),
        )
        return True

    def _release_slot(
        self,
        slot: int,
        park_tokens: Optional[List[int]],
        ns: Optional[tuple] = None,
    ):
        """Free a slot; its pages go to the registry (shared-prefix pool)
        or straight back to the allocator. While decode chunks are in
        flight the release is DEFERRED — an in-flight chunk may still
        write into these pages (host-backstop stops finish a slot the
        device considers active). ``ns`` = the (policy, version) KV
        namespace the pages belong to (None = the default registry);
        carried by KEY through the deferral so a namespace retired
        while the release waits degrades to a plain free, never a park
        into an orphaned cache."""
        pages = self._slot_pages.pop(slot, [])
        cached = int(self._cached_len[slot])
        if self._proposer is not None:
            self._proposer.drop(slot)
        if self._slot_mm[slot]:
            # pixel-conditioned KV must not enter the token-keyed prefix
            # registry (a text request with the same tokens would claim it)
            park_tokens = None
            self._slot_mm[slot] = False
            # a later text request reusing this slot may be admitted while
            # the delta scatter is gated off — never leave a stale shift
            self._rope_delta_dev = self._rope_delta_dev.at[slot].set(0)
        self._active_dev = self._active_dev.at[slot].set(False)
        # the device-side length must be zeroed too: a stale length with a
        # reset table row would make the next decode dispatch DMA pages at
        # the table fill value (one past the pool)
        self._lens_dev = self._lens_dev.at[slot].set(0)
        self._tables[slot] = self.cache_config.num_pages
        self._cached_len[slot] = 0
        self._free_slots.append(slot)
        tokens = (
            np.asarray(park_tokens[:cached], np.int32)
            if park_tokens is not None and cached > 0
            else None
        )
        if self._inflight:
            self._deferred_release.append((pages, tokens, ns))
        else:
            self._do_release(pages, tokens, ns)

    def _do_release(
        self,
        pages: List[int],
        tokens: Optional[np.ndarray],
        ns: Optional[tuple] = None,
    ):
        cache = (
            self.registry if ns is None else self._policy_caches.get(ns)
        )
        if tokens is not None and cache is not None:
            cache.add(self.pm, tokens, pages)
        else:
            self.pm.release(pages)

    def _flush_deferred(self):
        if not self._inflight:
            for pages, tokens, ns in self._deferred_release:
                self._do_release(pages, tokens, ns)
            self._deferred_release.clear()

    def _drain_pipeline(self):
        """Process every in-flight decode chunk (and release deferrals)."""
        while self._inflight:
            self._process_chunk(self._inflight.pop(0))
        self._flush_deferred()

    # ------------------------------------------------------------------
    # Admission
    # ------------------------------------------------------------------
    def _prefill_bucket(self, n: int) -> int:
        quantum = min(self.config.prefill_chunk, self.config.max_model_len)
        b = data_utils.next_bucket_size(n, quantum)
        return min(b, self.config.max_model_len)

    def _has_chunkable_pending(self) -> bool:
        """Some pending request's next wave is expected to be a
        SLOTLESS chunk dispatch (remaining suffix beyond the budget):
        admission can make prefill progress even with zero free decode
        slots. Preempted requests are excluded — their re-admission
        usually re-claims its cached prefix whole and needs a slot
        immediately (a wrong guess here only costs one deferred claim
        per loop iteration, never correctness)."""
        if self._chunk_budget <= 0:
            return False
        return any(
            r.mm is None
            and r.preemptions == 0
            and len(r.all_tokens) - r.prefill_pos > self._chunk_budget
            for r in self._pending
        )

    def _prefill_backlog_ok(self) -> bool:
        """Bound the UNSYNCED chunk-wave dispatches in flight (chunked
        prefill only). Chunk waves never fetch logits — there is no
        first token yet — so without this gate the admission loop could
        queue an entire long prompt's chunks on device ahead of a
        just-arrived interactive request, recreating exactly the
        head-of-line blocking chunking exists to break. Completed
        dispatches are pruned via ``Array.is_ready``; a jax without it
        degrades to an unbounded backlog (never a stall)."""
        keep = []
        for h in self._prefill_inflight:
            try:
                ready = bool(h.is_ready())
            except AttributeError:
                ready = True
            if not ready:
                keep.append(h)
        self._prefill_inflight = keep
        return len(keep) <= max(1, self.config.decode_pipeline)

    def _admit(self) -> bool:
        """Admit queued requests: identical prompts (GRPO siblings) group
        behind ONE prefill row, sharing full prompt pages and copying at
        most one partial tail page; unique prompts prefill as one batched
        [N, Tp] dispatch, each row resuming from its registry-claimed
        prefix (offset)."""
        got_new = 0
        while True:
            try:
                req = self._admit_queue.get_nowait()
            except queue.Empty:
                break
            with self._aq_lock:
                self._aq_class[req.priority] -= 1
                if req.resumed:
                    self._aq_resumed -= 1
            self._pending.append(req)
            got_new += 1
        if (
            self._pending
            and not self._free_slots
            and getattr(self.config, "deadline_preemption", True)
        ):
            self._maybe_deadline_preempt()
        if any(r.priority == "interactive" for r in self._pending):
            # priority admission: interactive requests jump every queued
            # bulk request — including a just-preempted victim re-queued
            # at the front, so the slot a deadline preemption freed goes
            # to the interactive waiter THIS wave, not back to its
            # victim (stable within each class, so bulk FIFO is
            # preserved)
            self._pending.sort(key=lambda r: r.priority != "interactive")
        # chunked prefill: capture deadline pressure BEFORE wave
        # selection moves the waiter out of _pending — a
        # deadline-critical interactive request defers this wave's bulk
        # chunks, so its first token rides an interactive-only dispatch
        # instead of sharing the wave with a bulk chunk
        deadline_pressed = (
            self._chunk_budget > 0
            and self._deadline_waiter() is not None
        )
        if not self._pending:
            return False
        if not self._free_slots and not self._has_chunkable_pending():
            # slotless chunk work may still proceed: a mid-prefill
            # prompt's next chunk needs no slot until its FINAL chunk,
            # so a fully-occupied decode house must not stall bulk
            # prefill (that would serialize the prefill behind decode
            # completions — exactly the head-of-line coupling chunking
            # exists to break)
            return False
        if self._pending_since is None:
            self._pending_since = time.monotonic()
        # hold while the queue is still filling (or decode has work) so
        # admission waves arrive full — every distinct wave shape compiles
        # its own XLA program
        wave = max(1, self.config.admit_wave)
        age = time.monotonic() - self._pending_since
        saturated = (
            len(self._pending) >= len(self._free_slots)
            or len({tuple(r.all_tokens) for r in self._pending}) >= wave
            # chunk continuations bypass the wave-filling hold: a
            # mid-prefill prompt's next chunk must dispatch this
            # iteration, not admit_hold_s from now (prefill_pos > 0 is
            # the mid-chunk marker — it resets at install, so a
            # once-chunked request that later re-queues does not
            # disable wave batching forever)
            or any(r.prefill_pos > 0 for r in self._pending)
        )
        if (
            not saturated
            and age < self.config.admit_hold_s
            and (got_new or self._active)
        ):
            return False
        self._pending_since = None
        # --- one modality per wave: mm waves carry an embeds tensor the
        # text prefill program doesn't, so mixing would recompile ---
        later: List[_Request] = []
        if self.model_config.vision is not None and any(
            r.mm is not None for r in self._pending
        ):
            kind_mm = self._pending[0].mm is not None
            later = [
                r for r in self._pending if (r.mm is not None) != kind_mm
            ]
            self._pending = [
                r for r in self._pending if (r.mm is not None) == kind_mm
            ]
        # --- one policy cohort per wave (r19): each wave prefills under
        # ONE param buffer, so mixed-policy pendings split across waves
        # (the modality-split deferral pattern above). Named requests
        # re-resolve to their line's CURRENT effective version — a push
        # that dropped the version they resolved at submit redirects
        # them to the new stable instead of failing them; a line
        # retired while they queued fails them typed. The whole block
        # is gated on `active`, so the single-policy path never runs it.
        wave_params = self.params
        wave_ns: Optional[tuple] = None
        if self._policies.active and self._pending:
            keep: List[_Request] = []
            have_key = False
            wave_key: Optional[tuple] = None
            for r in self._pending:
                try:
                    key = (
                        (
                            r.policy,
                            self._policies.effective_version(
                                r.policy, r.policy_version
                            ),
                        )
                        if r.policy
                        else None
                    )
                except UnknownPolicyError as e:
                    self.tracer.unbind_trace(r.rid)
                    if not r.future.done():
                        r.future.set_exception(e)
                    continue
                if not have_key:
                    wave_key, have_key = key, True
                if key == wave_key:
                    if r.policy:
                        r.policy_version = key[1]
                    keep.append(r)
                else:
                    later.append(r)
            if wave_key is not None and keep:
                try:
                    # fetch (and, for a host-demoted buffer, reload)
                    # the cohort's params now — the wave's prefill and
                    # mm-embed dispatches both run under this buffer
                    wave_params = self._policies.params_for(*wave_key)
                    wave_ns = wave_key
                except UnknownPolicyError:
                    # the version died between resolve and fetch (push
                    # race): requeue — next tick re-resolves to the
                    # line's new stable or fails typed
                    later.extend(keep)
                    keep = []
            self._pending = keep
            if not self._pending:
                self._pending = later
                return False
        wave_cache = (
            self.registry
            if wave_ns is None
            else self._policy_cache(*wave_ns)
        )
        # --- select: group identical prompts; <= wave unique prompts,
        # total admitted <= free slots ---
        groups: Dict[tuple, List[_Request]] = {}
        rest: List[_Request] = []
        budget = len(self._free_slots)
        for req in self._pending:
            key = (tuple(req.all_tokens), req.mm_key)
            # a request whose next wave is expected to be a SLOTLESS
            # chunk dispatch may open a group without consuming slot
            # budget — chunk prefill progresses through a fully-busy
            # decode house (the claim loop defers it back if its claim
            # turns out to leave a one-wave suffix needing a slot)
            chunkable = (
                self._chunk_budget > 0
                and req.mm is None
                and req.preemptions == 0
                and len(req.all_tokens) - req.prefill_pos
                > self._chunk_budget
            )
            if budget > 0 and key in groups:
                groups[key].append(req)
                budget -= 1
            elif len(groups) < wave and (budget > 0 or chunkable):
                groups[key] = [req]
                if budget > 0:
                    budget -= 1
            else:
                rest.append(req)
        self._pending = rest + later
        if not groups:
            return False

        m = self.config.max_model_len
        bs = self.cache_config.page_size
        num_pages = self.cache_config.num_pages
        s = self.config.max_num_seqs
        reps = [g[0] for g in groups.values()]
        # --- chunked prefill (r15): one chunk-capped row per wave (the
        # dispatch wall stays ~one chunk even with several long prompts
        # queued — they alternate chunks across waves), gated on the
        # unsynced-chunk backlog; deadline pressure defers BULK chunks
        # entirely, so the wave belongs to the interactive waiter
        # (chunk boundaries are the preemption points) ---
        budget_c = self._chunk_budget
        chunk_quota = (
            1 if budget_c > 0 and self._prefill_backlog_ok() else 0
        )
        pressure = deadline_pressed
        deferred: List[_Request] = []
        # --- prefix claim + page allocation per representative ---
        rep_slots: List[int] = []  # s = slotless chunk-capped row
        offsets: List[int] = []
        # cache-served tokens NET of the request's own chunk commits: a
        # continuation re-claiming the prefix it committed last wave is
        # not a cache hit — counting it would inflate the hit-rate
        # gauges quadratically in chunk count (only tokens beyond the
        # request's own committed position are cross-request reuse)
        novel_offs: List[int] = []
        host_offs: List[int] = []  # claim tokens served from host tier
        rep_pages: List[List[int]] = []
        admitted_groups: List[List[_Request]] = []
        chunk_ends: List[int] = []  # committed end (== plen: complete)
        cow_src: List[int] = []
        cow_dst: List[int] = []
        for rep, group in zip(reps, groups.values()):
            prompt = rep.all_tokens
            plen = len(prompt)
            src = None
            host_toks = 0
            if (
                budget_c > 0
                and rep.mm is None
                and rep.chunk_stalls < 2
                and plen - rep.prefill_pos > budget_c
                and (
                    chunk_quota <= 0
                    or (pressure and rep.priority == "bulk")
                )
            ):
                # chunk-boundary deferral BEFORE the claim: a group
                # expected to need a chunk this wave (remaining suffix
                # beyond the budget) defers under quota/deadline
                # pressure without touching the prefix cache — a
                # deferred group re-forms every scheduler tick, and
                # paying a claim per tick would make
                # prefix_claim_hit_rate measure ticks, refresh LRU
                # stamps spuriously, and churn refcounts. Committed
                # chunks stay published; nothing is lost. The deferral
                # is counted ONCE per episode (chunk_deferred), not
                # once per tick.
                if pressure and rep.priority == "bulk":
                    if not rep.chunk_deferred:
                        rep.chunk_deferred = True
                        self.prefill_chunk_preemptions_total += 1
                        self.tracer.instant(
                            "prefill_chunk_preempt", rep.rid,
                            committed=rep.prefill_pos,
                            prompt_tokens=plen,
                        )
                deferred.extend(group)
                continue
            if rep.mm is not None:
                # pixel-conditioned KV: no token-keyed prefix reuse
                shared, off = [], 0
            elif self._radix:
                shared, off, src, _cow_n = wave_cache.claim_cow(
                    self.pm, prompt
                )
                if self._kv_tiers is not None and wave_ns is None:
                    # pages the descent promoted from the host tier —
                    # the hit-rate split between device and host tiers
                    # (tiers attach to the DEFAULT tree only; a named
                    # wave's claim never touches the spill store)
                    host_toks = self._kv_tiers.last_claim_promoted * bs
            else:
                shared, off = wave_cache.claim(self.pm, prompt)
            end = plen
            stalled = escaped = False
            if budget_c > 0 and rep.mm is None and plen - off > budget_c:
                if chunk_quota <= 0 or (
                    pressure and rep.priority == "bulk"
                ):
                    # the pre-claim expectation missed (the claim
                    # regressed below prefill_pos, so the suffix is
                    # chunk-sized after all): same deferral, same
                    # once-per-episode counting, claim refs returned
                    self.pm.release(shared)
                    if src is not None:
                        self.pm.release([src])
                    if pressure and rep.priority == "bulk":
                        if not rep.chunk_deferred:
                            rep.chunk_deferred = True
                            self.prefill_chunk_preemptions_total += 1
                            self.tracer.instant(
                                "prefill_chunk_preempt", rep.rid,
                                committed=rep.prefill_pos,
                                prompt_tokens=plen,
                            )
                    deferred.extend(group)
                    continue
                # stall escape: a continuation whose claims regressed
                # on two DISPATCHED waves (eviction keeps eating the
                # committed prefix) admits in full — chunking must
                # never livelock a prompt under cache thrash. Both the
                # strike and the escape's side effects are recorded
                # only when this row actually dispatches (below), so
                # deferrals/alloc failures can neither double-count a
                # single regression nor spam the counter per loop tick.
                stalled = rep.chunk_index > 0 and off < rep.prefill_pos
                if rep.chunk_stalls + (1 if stalled else 0) >= 2:
                    escaped = True  # end stays plen: uncapped dispatch
                else:
                    # cap this row at a PAGE-ALIGNED end: commits must
                    # publish full pages so both cache modes (and the
                    # flat registry's full-page claims) resume exactly
                    # here. budget >= page_size guarantees end > off.
                    end = ((off + budget_c) // bs) * bs
                    chunk_quota -= 1
            if end == plen and not self._free_slots:
                # selected on chunk eligibility, but the claim leaves a
                # suffix that fits one wave — the FINAL chunk samples a
                # first token and needs a decode slot; wait for one
                self.pm.release(shared)
                if src is not None:
                    self.pm.release([src])
                deferred.extend(group)
                continue
            need = -(-end // bs) - len(shared)
            fresh = self._alloc_pages(need)
            if fresh is None:
                # pool exhausted — return the whole group to pending
                self.pm.release(shared)
                if src is not None:
                    self.pm.release([src])
                self._pending = group + self._pending
                continue
            if stalled:
                rep.chunk_stalls += 1
            if escaped:
                # the uncapped dispatch is now certain: the TTFT bound
                # is violated for this wave and ttft_bounded reports it
                self.prefill_chunk_stall_escapes += 1
                self.tracer.instant(
                    "prefill_chunk_stall_escape", rep.rid,
                    committed=rep.prefill_pos,
                    prompt_tokens=plen,
                )
                logger.warning(
                    f"chunked prefill stall escape for {rep.rid}: "
                    f"claims regressed twice (cache thrash) — "
                    f"admitting {plen - off} suffix tokens whole"
                )
            if src is not None:
                # COW claim: the match extends into a cached page (a
                # partial tail, or divergence within a full page) —
                # copy it into the claimant's first fresh page and
                # resume prefill mid-page from the row-aligned offset
                cow_src.append(src)
                cow_dst.append(fresh[0])
            pages = shared + fresh
            if end < plen:
                # chunk-capped: the row rides the wave SLOTLESS — no
                # first token is sampled yet, so slot/sampling state
                # and the install wait for the final chunk's wave
                rep_slots.append(s)
            else:
                rep_slots.append(self._free_slots.pop())
            offsets.append(off)
            novel_offs.append(off - min(off, rep.prefill_pos))
            host_offs.append(min(host_toks, off))
            rep_pages.append(pages)
            admitted_groups.append(group)
            chunk_ends.append(end)
            # the deferral episode (if any) ended in a dispatch: the
            # next pressure deferral is a new episode and counts again
            rep.chunk_deferred = False
        # flush claim-time promotions NOW, before anything downstream
        # can read the promoted pages: the COW copy dispatch below and
        # the wave prefill both attend through shared pages, and a page
        # promoted this loop holds garbage until its scatter lands.
        # Deferred/failed claims above may also have queued promotions —
        # their pages are tree-owned and resident, so flushing them
        # unconditionally is correct (and keeps them claimable).
        self._flush_kv_promotions()
        if deferred:
            self._pending = deferred + self._pending
        if not admitted_groups:
            # a COW claim with no admitted rep cannot happen (the claim
            # only survives when its rep allocates), but release holds
            # defensively if a future edit changes that
            if cow_src:
                raise RuntimeError(
                    "COW source pages on a non-allocating claim path"
                )
            return False
        if cow_src:
            # dispatch the COW copies BEFORE the wave prefill: the
            # claimants' prefix-window attention reads the copied pages.
            # Device program order also protects the sources against
            # reallocation — any later write lands after this copy.
            pad = data_utils.next_bucket_size(len(cow_src), 8)
            src_np = np.zeros(pad, np.int32)
            dst_np = np.full(pad, num_pages, np.int32)
            src_np[: len(cow_src)] = cow_src
            dst_np[: len(cow_dst)] = cow_dst
            src_dev, dst_dev = jnp.asarray(src_np), jnp.asarray(dst_np)
            with goodput.dispatch_scope(
                self.compiles, "copy", precompile_lib.copy_sig(pad)
            ):
                self.cache = model_runner.copy_pages(
                    self.cache, src_dev, dst_dev
                )
            self.total_cow_copies += len(cow_src)
            # the claim's protective refs on the sources: the copy is
            # now ordered before any later pool write, so registry
            # eviction can no longer race it
            self.pm.release(cow_src)

        # suffix bucket (offsets are pool-ROW-aligned — page-aligned for
        # full-page claims, mid-page for COW claims — and < prompt len).
        # Chunk-capped rows contribute their CHUNK's suffix, so with
        # chunking on every admission dispatch is bounded by ~one chunk
        tp = self._prefill_bucket(
            max(
                end - off
                for end, off in zip(chunk_ends, offsets)
            )
        )
        # rows whose suffix exceeds the bucket fall back to offset 0?
        # cannot happen: offset <= len(prompt)-1 and bucket >= max suffix.
        self.total_cached_prompt_tokens += sum(novel_offs)
        self.total_host_cached_tokens += sum(host_offs)
        pf_prefix_bound = 0
        if max(offsets) > 0:
            pf_prefix_bound = min(
                m,
                data_utils.next_bucket_size(
                    max(offsets), self.config.kv_bucket
                ),
            )
        # page window covers each row's COMMITTED end (chunk-capped rows
        # only write/attend up to their chunk), not the full prompt
        pps_pf = max(
            1,
            -(-data_utils.next_bucket_size(
                max(chunk_ends),
                self.config.kv_bucket,
            ) // bs),
        )
        pps_pf = min(pps_pf, self.cache_config.max_pages_per_seq)
        # pow2 row bucket: a lone unique prompt (a GRPO group) doesn't pay
        # for wave-1 padding rows of compute
        n_rows = (
            1 << (len(rep_slots) - 1).bit_length() if len(rep_slots) > 1 else 1
        )
        tokens = np.zeros((n_rows, tp), np.int32)
        true_lens = np.zeros(n_rows, np.int32)
        row_offsets = np.zeros(n_rows, np.int32)
        row_tables = np.full((n_rows, pps_pf), num_pages, np.int32)
        for i, (group, slot, off, pages) in enumerate(
            zip(admitted_groups, rep_slots, offsets, rep_pages)
        ):
            prompt = group[0].all_tokens
            suffix = prompt[off : chunk_ends[i]]
            tokens[i, : len(suffix)] = suffix
            true_lens[i] = len(suffix)
            row_offsets[i] = off
            row_tables[i, : len(pages)] = pages
        row_slots = np.zeros(n_rows, np.int32)
        for i, slot in enumerate(rep_slots):
            row_slots[i] = slot
        # --- VLM wave: splice vision embeds once, build mrope positions
        # (offsets are 0 for mm rows — no prefix reuse — so the suffix IS
        # the full prompt + any accumulated text) ---
        pf_embeds = pf_pos3 = None
        if (
            self.model_config.vision is not None
            and any(g[0].mm is not None for g in admitted_groups)
        ):
            vc = self.model_config.vision
            p_pad = data_utils.next_bucket_size(
                max(
                    g[0].mm["pixel_values"].shape[0]
                    for g in admitted_groups
                    if g[0].mm is not None
                ),
                64,
            )
            pix = np.zeros((n_rows, p_pad, vc.patch_dim), np.float32)
            seg = np.zeros((n_rows, p_pad), np.int32)
            ph = np.zeros((n_rows, p_pad), np.int32)
            pw = np.zeros((n_rows, p_pad), np.int32)
            ords = np.full((n_rows, tp), -1, np.int32)
            pos3 = np.zeros((n_rows, tp, 3), np.int32)
            for i, group in enumerate(admitted_groups):
                mm = group[0].mm
                if mm is None:
                    continue
                p_n = mm["pixel_values"].shape[0]
                pix[i, :p_n] = mm["pixel_values"]
                seg[i, :p_n] = mm["vis_seg"][:p_n]
                ph[i, :p_n] = mm["vis_pos_h"][:p_n]
                pw[i, :p_n] = mm["vis_pos_w"][:p_n]
                L = min(len(group[0].all_tokens), tp)
                n_ord = min(len(mm["mm_index"]), L)
                ords[i, :n_ord] = mm["mm_index"][:n_ord]
                mp = mm.get("mrope_pos")
                n_p = min(len(mp), L) if mp is not None else 0
                if n_p:
                    pos3[i, :n_p] = mp[:n_p]
                if n_p < L:  # accumulated text continues at idx + delta
                    ext = np.arange(n_p, L, dtype=np.int32) + np.int32(
                        group[0].rope_delta
                    )
                    pos3[i, n_p:L] = ext[:, None]
            pf_embeds = model_runner.mm_prompt_embeds(
                wave_params, self.model_config, jnp.asarray(tokens),
                jnp.asarray(pix), jnp.asarray(seg), jnp.asarray(ph),
                jnp.asarray(pw), jnp.asarray(ords),
            )
            pf_pos3 = jnp.asarray(pos3)
        t_pf_start = time.monotonic()
        # host→device conversions hoisted OUT of the dispatch scope:
        # their tiny eager-op compiles belong to the ("engine", "")
        # catch-all rung, so the prefill rung's compile bill is exactly
        # the programs the AOT precompiler covers
        tokens_dev = jnp.asarray(tokens)
        offsets_dev = jnp.asarray(row_offsets)
        lens_dev = jnp.asarray(true_lens)
        tables_dev = jnp.asarray(row_tables)
        slots_dev = jnp.asarray(row_slots)
        with goodput.dispatch_scope(
            self.compiles, "prefill",
            precompile_lib.prefill_sig(
                n_rows, tp, pps_pf, pf_prefix_bound,
                int(pf_embeds is not None),
            ),
        ):
            self.cache, wave_logits, pf_last = model_runner.prefill_batch(
                wave_params, self.model_config, self.cache,
                tokens_dev, offsets_dev,
                lens_dev, tables_dev,
                prefix_bound=pf_prefix_bound,
                last_rows=self._last_rows,
                slot_ids=slots_dev,
                embeds=pf_embeds,
                pos3=pf_pos3,
            )
        if self._radix:
            # publish-at-prefill-commit: the wave's prompt pages enter
            # the radix tree NOW (the merge dispatch is already ordered
            # on device), so siblings/turns arriving in later waves
            # claim them while these owners are still decoding — the
            # flat registry only ever parked pages at free time.
            # Chunk-capped rows are handled below (publish-at-CHUNK-
            # commit is an ownership transfer, not a share)
            for i, (group, pages) in enumerate(
                zip(admitted_groups, rep_pages)
            ):
                if group[0].mm is None and chunk_ends[i] == len(
                    group[0].all_tokens
                ):
                    wave_cache.publish(
                        self.pm,
                        np.asarray(group[0].all_tokens, np.int32),
                        pages,
                    )

        # --- publish-at-chunk-commit (r15): a chunk-capped row's
        # committed page-aligned prefix enters the prefix cache as an
        # OWNERSHIP TRANSFER (`add` publishes, then releases this
        # wave's claim+alloc refs — between chunks the cache is the
        # prefix's only holder), and the group requeues at the front of
        # pending. The next wave's claim resumes exactly here; GRPO
        # siblings and overlapping prompts already ride the finished
        # chunks while the owner is still prefilling. ---
        requeue: List[_Request] = []
        if budget_c > 0:
            t_commit = time.monotonic()
            for i, (group, pages) in enumerate(
                zip(admitted_groups, rep_pages)
            ):
                end = chunk_ends[i]
                rep = group[0]
                plen = len(rep.all_tokens)
                if end == plen:
                    continue
                wave_cache.add(
                    self.pm,
                    np.asarray(rep.all_tokens[:end], np.int32),
                    pages,
                )
                rep.chunk_index += 1
                rep.prefill_pos = end
                if rep.first_dispatch_time is None:
                    # the wave that first served this request: queue
                    # wait ends HERE — the later chunk waves are the
                    # prompt being prefilled, not queued
                    rep.first_dispatch_time = t_pf_start
                self.prefill_chunks_total += 1
                if self.tracer.enabled:
                    # chunk spans measure DISPATCH wall (the wave is
                    # not synced — no first token to fetch); the final
                    # chunk's span carries end-to-end timing as usual
                    self.tracer.record(
                        "prefill", rep.rid, t_pf_start, t_commit,
                        slot=-1, wave_rows=len(rep_slots),
                        prompt_tokens=plen,
                        cached_offset=int(offsets[i]),
                        cached_tokens=int(novel_offs[i]),
                        chunk_index=rep.chunk_index - 1,
                        chunk_count=rep.chunk_index
                        + max(1, -(-(plen - end) // budget_c)),
                        committed=end,
                        partial=1,
                        **(
                            {"host_cached_tokens": int(host_offs[i])}
                            if self._kv_tiers is not None
                            else {}
                        ),
                    )
                requeue.extend(group)
            if requeue:
                # keep one unsynced-dispatch handle per chunk wave so
                # _prefill_backlog_ok can bound device queue depth
                self._prefill_inflight.append(wave_logits)
                self._pending = requeue + self._pending

        # --- sibling fan-out: share full prompt pages, copy the partial
        # tail page (if any) — chunk-capped rows skip (their installs
        # and sibling fan-out wait for the final chunk's wave) ---
        copy_src: List[int] = []
        copy_dst: List[int] = []
        admitted: List[tuple] = []  # (req, slot, logits_row)
        adm_cached: List[int] = []  # cache-served prompt tokens per req
        adm_host: List[int] = []  # of those, tokens from the host tier
        # (chunk_index, first_dispatch_time) captured BEFORE _install
        # resets them: the final chunk's span attrs and the queue-wait
        # end need this admission's values, not the fresh slot life's
        adm_meta: List[tuple] = []
        for i, (group, slot, pages) in enumerate(
            zip(admitted_groups, rep_slots, rep_pages)
        ):
            plen = len(group[0].all_tokens)
            if chunk_ends[i] < plen:
                continue
            adm_meta.append(
                (group[0].chunk_index, group[0].first_dispatch_time)
            )
            group[0].cached_tokens = int(novel_offs[i])
            self._install(group[0], slot, pages, plen)
            admitted.append((group[0], slot, i))
            adm_cached.append(int(novel_offs[i]))
            adm_host.append(int(min(host_offs[i], novel_offs[i])))
            n_full = plen // bs
            for sib in group[1:]:
                if not self._free_slots:
                    self._pending.insert(0, sib)
                    continue
                shared = pages[:n_full]
                sib_pages = list(shared)
                self.pm.share(shared)
                if plen % bs:
                    tail = self._alloc_pages(1)
                    if tail is None:
                        # pool dry mid-fanout: requeue the sibling
                        self.pm.release(shared)
                        self._pending.insert(0, sib)
                        continue
                    copy_src.append(pages[n_full])
                    copy_dst.append(tail[0])
                    sib_pages += tail
                sslot = self._free_slots.pop()
                adm_meta.append((0, None))
                sib.cached_tokens = plen
                self._install(sib, sslot, sib_pages, plen)
                admitted.append((sib, sslot, i))
                adm_cached.append(plen)
                # siblings ride the representative's DEVICE pages —
                # their cache hit never touches the host tier
                adm_host.append(0)
                self.total_cached_prompt_tokens += plen
        if copy_src:
            pad = data_utils.next_bucket_size(len(copy_src), 8)
            src = np.zeros(pad, np.int32)
            dst = np.full(pad, num_pages, np.int32)
            src[: len(copy_src)] = copy_src
            dst[: len(copy_dst)] = copy_dst
            src_dev, dst_dev = jnp.asarray(src), jnp.asarray(dst)
            with goodput.dispatch_scope(
                self.compiles, "copy", precompile_lib.copy_sig(pad)
            ):
                self.cache = model_runner.copy_pages(
                    self.cache, src_dev, dst_dev
                )

        # --- batched per-slot state update (one scatter per state array) ---
        if not admitted:
            # chunk-only wave: nothing installed, no first token to
            # fetch — the dispatch stays unsynced (the backlog handle
            # above bounds device queue depth) and the loop proceeds
            # straight to decode, which is the whole point: decode
            # dispatches interleave between a long prompt's chunks
            return True
        n = len(admitted)
        slots_np = np.zeros(n, np.int32)
        deltas = np.zeros(n, np.int32)
        temps = np.zeros(n, np.float32)
        top_ps = np.zeros(n, np.float32)
        top_ks = np.zeros(n, np.int32)
        greedys = np.zeros(n, bool)
        remainings = np.zeros(n, np.int32)
        no_stops = np.zeros(n, np.int32)
        plens = np.zeros(n, np.int32)
        stops = np.full((n, 8), -1, np.int32)
        for j, (req, slot, _) in enumerate(admitted):
            plen = len(req.all_tokens)
            self.total_prompt_tokens += plen
            self.total_requests += 1
            slots_np[j] = slot
            temps[j] = req.temperature
            top_ps[j] = req.top_p
            top_ks[j] = req.top_k
            greedys[j] = req.greedy
            plens[j] = plen
            # the first token is sampled at admission (below), so the
            # device-side budget starts at allowed − 1
            remainings[j] = min(req.budget_left, m - plen) - 1
            no_stops[j] = req.min_left - 1
            deltas[j] = req.rope_delta
            ids = np.asarray(req.stop_token_ids[:8], np.int32)
            stops[j, : len(ids)] = ids
        sl = jnp.asarray(slots_np)
        self._lens_dev = self._lens_dev.at[sl].set(jnp.asarray(plens))
        if self._spec_configured:
            # canonical alignment base = cache length at admission (the
            # off-run's chunk boundaries are multiples of decode_chunk
            # from here)
            self._align_base_dev = self._align_base_dev.at[sl].set(
                jnp.asarray(plens)
            )
        self._temp_dev = self._temp_dev.at[sl].set(jnp.asarray(temps))
        self._top_p_dev = self._top_p_dev.at[sl].set(jnp.asarray(top_ps))
        self._top_k_dev = self._top_k_dev.at[sl].set(jnp.asarray(top_ks))
        self._greedy_dev = self._greedy_dev.at[sl].set(jnp.asarray(greedys))
        self._active_dev = self._active_dev.at[sl].set(True)
        self._remaining = self._remaining.at[sl].set(jnp.asarray(remainings))
        self._no_stop = self._no_stop.at[sl].set(jnp.asarray(no_stops))
        self._stop_tokens = self._stop_tokens.at[sl].set(jnp.asarray(stops))
        if any(self._slot_mm) or deltas.any():
            self._rope_delta_dev = self._rope_delta_dev.at[sl].set(
                jnp.asarray(deltas)
            )

        # --- last-row state for every admitted slot (siblings share the
        # representative's prefill row content) ---
        adm_rows = np.asarray([r for (_, _, r) in admitted], np.int32)
        adm_slots = np.asarray([sl_ for (_, sl_, _) in admitted], np.int32)
        onehot = jnp.asarray(
            (adm_slots[:, None]
             == np.arange(self.config.max_num_seqs)[None, :]).astype(
                np.float32
            )
        )
        sel = {
            k_: jnp.take(v_, jnp.asarray(adm_rows), axis=1)
            for k_, v_ in pf_last.items()
        }
        mask = (onehot.sum(0) > 0)[None, :, None, None]
        self._last_rows = {
            k_: jnp.where(
                mask,
                jnp.einsum(
                    "lnhf,ns->lshf", sel[k_].astype(jnp.float32), onehot
                ).astype(v_.dtype),
                v_,
            )
            for k_, v_ in self._last_rows.items()
        }

        # --- first token for every admitted slot: siblings share the
        # representative's last-token logits row ---
        rows = jnp.asarray([r for (_, _, r) in admitted])
        full = jnp.zeros(
            (self.config.max_num_seqs, wave_logits.shape[-1]),
            wave_logits.dtype,
        ).at[sl].set(wave_logits[rows])
        self._sample_and_append(full, only_slots=[int(x) for x in slots_np])
        t_pf_end = time.monotonic()
        pf_tokens = int(true_lens.sum())
        if t_pf_end > t_pf_start:
            # EWMA over waves: the dispatch wall time includes the logits
            # fetch in _sample_and_append, so this is end-to-end prefill
            # throughput as a client would see it
            inst = pf_tokens / (t_pf_end - t_pf_start)
            self._prefill_tps = (
                inst if self._prefill_tps == 0.0
                else 0.8 * self._prefill_tps + 0.2 * inst
            )
        for (req, _, _), (_, first_disp) in zip(admitted, adm_meta):
            # native queue-wait histogram per class: the durable latency
            # source (span percentiles vanish with every /trace drain).
            # A chunked prompt's wait ends at its FIRST chunk wave —
            # the later waves are the prompt being prefilled, and
            # counting them as queueing would corrupt the bulk class's
            # priority-isolation SLO signal
            self._hists["queue_wait_seconds"][req.priority].observe(
                (first_disp or t_pf_start) - req.submit_time
            )
        if self.tracer.enabled:
            for (req, slot, row), ctok, htok, (
                chunk_idx, first_disp,
            ) in zip(admitted, adm_cached, adm_host, adm_meta):
                self.tracer.record(
                    "queue_wait", req.rid, req.submit_time,
                    first_disp or t_pf_start,
                    preemptions=req.preemptions,
                    # per-class queue-wait is THE priority-isolation SLO
                    # signal (trace_report --slo aggregates it)
                    sched_class=req.priority,
                    **({"tenant": req.tenant} if req.tenant else {}),
                )
                chunk_attrs = {}
                if self._chunk_budget > 0:
                    # chunked engines stamp every prefill span with its
                    # chunk position (final chunk = index chunk_index of
                    # chunk_index+1) — trace_report --ttft builds the
                    # chunks-per-prompt histogram from these
                    chunk_attrs = dict(
                        chunk_index=chunk_idx,
                        chunk_count=chunk_idx + 1,
                    )
                self.tracer.record(
                    "prefill", req.rid, t_pf_start, t_pf_end,
                    slot=slot, wave_rows=len(rep_slots),
                    # _sample_and_append already appended this wave's first
                    # token, so the prefilled length is one shy of all_tokens
                    prompt_tokens=len(req.all_tokens) - 1,
                    cached_offset=int(offsets[row]),
                    # prompt tokens THIS request served from cache (a
                    # sibling's whole prompt rode the representative's
                    # prefill; a claimant's = its claim offset) —
                    # trace_report --cache aggregates these
                    cached_tokens=int(ctok),
                    **chunk_attrs,
                    **(
                        {"host_cached_tokens": int(htok)}
                        if self._kv_tiers is not None
                        else {}
                    ),
                )
        return True

    def _install(
        self, req: _Request, slot: int, pages: List[int], cached: int
    ):
        req.slot = slot
        # (re-)admission decodes under the CURRENT weights: a preempted
        # pin-policy request re-prefills here on the new version (its
        # already-emitted tokens keep their old per-token version stamps
        # — the recorded-switch half of the fence invariant). A NAMED
        # request decodes under its line's resolved version instead,
        # and holds one registry pin for this slot life — the buffer is
        # undemotable and undroppable until _finish/_preempt releases.
        if req.policy:
            req.weight_version = req.policy_version
            self._policies.retain(req.policy, req.policy_version)
        else:
            req.weight_version = self.model_version
        self._active[slot] = req
        self._slot_pages[slot] = pages
        self._cached_len[slot] = cached
        self._tables[slot] = self.cache_config.num_pages
        self._tables[slot, : len(pages)] = pages
        self._slot_mm[slot] = req.mm is not None
        self._align_base[slot] = cached
        # a fresh slot life resets the chunk bookkeeping: a preempted
        # request's next life may legitimately re-claim less (no stall
        # strike), and its re-claims of its own PARKED pages count as
        # cache hits again (pre-chunking accounting — prefill_pos only
        # discounts a still-prefilling prompt's own chunk commits)
        req.chunk_stalls = 0
        req.prefill_pos = 0
        req.chunk_index = 0
        req.chunk_deferred = False
        req.first_dispatch_time = None
        if self._proposer is not None:
            # full history (resumed/preempted requests re-enter with
            # their accumulated output): the n-gram index rebuilds here
            self._proposer.begin(slot, req.all_tokens)

    # ------------------------------------------------------------------
    # Decode
    # ------------------------------------------------------------------
    def _ensure_decode_pages(self, margin_tokens: int) -> bool:
        """Grow every active slot's page table to cover its cached length
        plus ``margin_tokens`` (the host view lags in-flight chunks, so
        the margin covers pipeline depth × chunk). Preempts under pool
        pressure ONLY when the pipeline is empty — an in-flight chunk may
        still write to a victim's pages. Returns False if nothing can be
        dispatched right now."""
        bs = self.cache_config.page_size
        while self._active:
            shortfall = 0
            grow: List[tuple] = []
            for slot, req in self._active.items():
                cached = int(self._cached_len[slot])
                need = -(
                    -min(cached + margin_tokens, self.config.max_model_len)
                    // bs
                )
                have = len(self._slot_pages[slot])
                if need > have:
                    grow.append((slot, need - have))
                    shortfall += need - have
            if shortfall == 0:
                return True
            if shortfall > self.pm.n_free:
                if self._inflight:
                    # drain before evicting: deferred releases (blocked on
                    # the in-flight pipeline) may cover the shortfall, and
                    # evicting the registry now would destroy parked KV of
                    # preempted requests — forcing full re-prefills with
                    # fresh shape compiles (the r4 catastrophic-round
                    # mechanism at decode_pipeline=2)
                    return False
                self.registry.evict(self.pm, shortfall)
                for cache in self._policy_caches.values():
                    if shortfall <= self.pm.n_free:
                        break
                    cache.evict(self.pm, shortfall - self.pm.n_free)
            if shortfall <= self.pm.n_free:
                for slot, n in grow:
                    pages = self.pm.alloc(n)
                    if pages is None:
                        raise RuntimeError(
                            "page allocation failed after preemption "
                            "freed the pool"
                        )
                    sp = self._slot_pages[slot]
                    self._tables[slot, len(sp) : len(sp) + n] = pages
                    sp.extend(pages)
                return True
            if len(self._active) == 1:
                # a lone request larger than the whole pool cannot be
                # preempted into progress — truncate it
                slot = next(iter(self._active))
                logger.warning(
                    f"pool smaller than one request; truncating "
                    f"{self._active[slot].rid}"
                )
                self._finish(slot, "length")
                return False
            # pool pressure prefers BULK victims (priority isolation);
            # an all-interactive batch still preempts its youngest
            if not (
                self._preempt_youngest(victims=("bulk",))
                or self._preempt_youngest()
            ):
                return False
        return False

    def _pages_bound(
        self, margin_tokens: int, slots: Optional[List[int]] = None
    ) -> int:
        """Static page-window bound: bucketed longest cached length plus
        the in-flight margin (over ``slots`` when a cohort dispatch
        passes one, else over every active slot)."""
        bs = self.cache_config.page_size
        max_len = (
            max(int(self._cached_len[s]) for s in (slots or self._active))
            + margin_tokens
        )
        tokens = min(
            self.config.max_model_len,
            data_utils.next_bucket_size(max_len, self.config.kv_bucket),
        )
        return min(-(-tokens // bs), self.cache_config.max_pages_per_seq)

    def _sampling_mode(self) -> int:
        """Static topk_bound for the sampling kernel, from the live mix of
        requests: -1 (pure categorical) when nothing truncates, else a
        lax.top_k bound covering every slot's top_k."""
        reqs = self._active.values()
        if all(r.top_p >= 1.0 and r.top_k <= 0 for r in reqs):
            return -1
        if self.config.sample_topk_bound <= 0:
            return 0  # exact full-vocab sort requested
        mx = max((r.top_k for r in reqs), default=0)
        return data_utils.next_bucket_size(
            max(self.config.sample_topk_bound, mx),
            self.config.sample_topk_bound,
        )

    def _spec_on(self) -> bool:
        """Speculation configured and not auto-disabled by the gate."""
        return self._spec_configured and not self._spec_gate.disabled

    def _spec_has_candidates(self) -> bool:
        """Cheap probe: would any active slot propose a draft? (Used to
        decide whether draining the pipeline for fresh drafts pays.)
        Applies the same boundary trim as _propose_drafts — a slot whose
        next token lands ON its canonical boundary cannot carry a draft
        this round, so its n-gram candidates must not trigger a drain."""
        cq = max(1, self.config.decode_chunk)
        for slot in self._active:
            rl = int(
                (self._cached_len[slot] - self._align_base[slot]) % cq
            )
            if cq - 1 - rl <= 0:
                continue
            if self._proposer.has_candidate(slot):
                return True
        return False

    def _propose_drafts(self) -> Dict[int, List[int]]:
        """Per-slot n-gram drafts from CURRENT host history (callers
        guarantee the pipeline is empty, so the history is exact).

        Drafts are trimmed to the slot's canonical-boundary distance:
        acceptance can never run past the boundary (the verify would
        need this window's own pre-boundary K/V as merged pool entries
        — model_runner._spec_verify_forward caps there), so proposing
        past it only lowers the measured accept rate."""
        kd = max(1, self.config.spec.max_draft)
        cq = max(1, self.config.decode_chunk)
        out: Dict[int, List[int]] = {}
        for slot in self._active:
            rl = int(
                (self._cached_len[slot] - self._align_base[slot]) % cq
            )
            kd_s = min(kd, cq - 1 - rl)
            if kd_s <= 0:
                continue
            d = self._proposer.propose(slot, kd_s)
            if d:
                out[slot] = d
        return out

    def _margin(self, new_steps: int) -> int:
        """Page-growth margin for a new dispatch: its own worst case plus
        every in-flight chunk's (the host view lags the device by the
        pipeline; verify chunks may grow by K, regular chunks by
        decode_chunk — sizes can mix)."""
        return new_steps + sum(c["max_tokens"] for c in self._inflight)

    def _decode(self) -> bool:
        """Pipelined decode: dispatch chunk N+1, then process chunk N's
        results while N+1 executes on device — the result fetch (a full
        round-trip over a driver tunnel) overlaps device compute.

        Speculation composition (r7): a verify dispatch needs drafts, and
        drafts need CURRENT host history — so verify chunks are only
        dispatched on an empty pipeline, and when the proposer has
        candidates the loop drains in-flight chunks instead of stacking
        more regular ones ("drain-for-drafts": speculation trades
        pipeline overlap for multi-token verify; the accept-rate gate
        auto-disables it when that trade loses). Slots with no candidate
        n-grams ride along in the verify dispatch with draft_len 0 (a
        plain single-token step for them); when NO slot has a candidate
        the regular pipelined path runs untouched."""
        depth = max(0, self.config.decode_pipeline)
        led = self.ledger
        did = False
        dispatched = False
        drafts: Optional[Dict[int, List[int]]] = None
        # version cohorts (r13 pin-policy flips): while ANY active
        # request is pinned off the current version — including the tail
        # case where only the pinned cohort remains — each dispatch
        # covers ONE cohort with its own params (round-robin so neither
        # starves); speculation sits out the transient — its
        # drain-for-drafts scheduling assumes one dispatch serves every
        # active slot
        # cohort keys are (policy, version) — version ints are per-LINE
        # (actor@v12 and opponent@v12 are different buffers), so the
        # bare int the r13 flip machinery used would collide across
        # lines. The default line's key is ("", model_version).
        versions = (
            {(r.policy, r.weight_version) for r in self._active.values()}
            if self._active
            else set()
        )
        mixed = bool(versions - {("", self.model_version)})
        if self._spec_on() and self._active and not mixed:
            if not self._inflight:
                drafts = self._propose_drafts() or None
            elif self._spec_has_candidates():
                # drain-for-drafts (see docstring); the drained chunk
                # may itself be a verify chunk — attribute its wall
                # time to the bucket that dispatched it
                chunk = self._inflight.pop(0)
                spec_chunk = chunk.get("spec_draft_lens") is not None
                with led.bucket(
                    "spec_verify" if spec_chunk else "decode"
                ):
                    self._process_chunk(chunk)
                    self._flush_deferred()
                return True
        if self._active and len(self._inflight) <= depth:
            if drafts:
                # drafts are trimmed to <= decode_chunk-1 tokens and the
                # verify boundary cap makes positions past that
                # unemittable — clamp the window (and the page margin)
                # to what can actually land
                k = min(
                    max(1, self.config.spec.max_draft),
                    max(1, self.config.decode_chunk) - 1,
                ) + 1
                margin = self._margin(k)
                with led.bucket("spec_verify"):
                    if self._ensure_decode_pages(margin):
                        self._dispatch_chunk(k, margin, drafts=drafts)
                        dispatched = did = True
            else:
                steps = max(1, self.config.decode_chunk)
                margin = self._margin(steps)
                with led.bucket("decode"):
                    if self._ensure_decode_pages(margin):
                        # recompute the cohort picture AFTER the page
                        # walk: _ensure_decode_pages may have preempted
                        # (or truncated) the last pinned request, which
                        # releases its pin and drops the old buffer — a
                        # stale pre-walk snapshot would dispatch an
                        # empty cohort against a freed buffer and kill
                        # the loop thread
                        versions = {
                            (r.policy, r.weight_version)
                            for r in self._active.values()
                        }
                        mixed = bool(
                            versions - {("", self.model_version)}
                        )
                        if mixed:
                            order = sorted(versions)
                            ck = order[self._cohort_rr % len(order)]
                            self._cohort_rr += 1
                            cohort_slots = sorted(
                                sl
                                for sl, r in self._active.items()
                                if (r.policy, r.weight_version) == ck
                            )
                            if cohort_slots:
                                self._dispatch_chunk(
                                    steps, margin,
                                    cohort=(cohort_slots, ck),
                                )
                                dispatched = did = True
                        elif self._active:
                            self._dispatch_chunk(steps, margin)
                            dispatched = did = True
        if self._inflight and (
            len(self._inflight) > depth or not dispatched
        ):
            chunk = self._inflight.pop(0)
            spec_chunk = chunk.get("spec_draft_lens") is not None
            with led.bucket("spec_verify" if spec_chunk else "decode"):
                self._process_chunk(chunk)
                self._flush_deferred()
            did = True
        return did

    def _decode_rows_bucket(self, n_active: int) -> int:
        """Pow2 row bucket for a compacted decode dispatch: grows
        immediately (correctness — every active slot needs a row),
        shrinks only after ``decode_compact_hysteresis`` consecutive
        chunks below the current bucket (each distinct row count is its
        own compiled program; ragged finishes must not thrash the
        compile cache)."""
        s = self.config.max_num_seqs
        floor = max(1, self.config.decode_compact_min_rows)
        target = max(n_active, floor)
        target = min(1 << (target - 1).bit_length(), s)
        cur = self._compact_rows
        if cur is None or target > cur:
            self._compact_rows = target
            self._compact_shrink_streak = 0
        elif target < cur:
            self._compact_shrink_streak += 1
            if self._compact_shrink_streak >= max(
                1, self.config.decode_compact_hysteresis
            ):
                self._compact_rows = target
                self._compact_shrink_streak = 0
        else:
            self._compact_shrink_streak = 0
        return self._compact_rows

    def _dispatch_chunk(
        self,
        steps: int,
        margin: int,
        drafts: Optional[Dict[int, List[int]]] = None,
        cohort: Optional[tuple] = None,
    ):
        """One decode dispatch over the (possibly compacted) row bucket.

        With ``drafts`` (slot -> proposed tokens) this is a speculative
        VERIFY dispatch: ``steps`` is the verify window K = max_draft + 1
        and the device scores all K positions in one forward
        (model_runner.spec_verify) — otherwise it is the regular fused
        ``steps``-iteration decode. Both return the same state/result
        contract, so everything downstream (row→slot scatter, packed
        fetch, _process_chunk) is shared.

        ``cohort`` = ``(slots, (policy, version))`` restricts the
        dispatch to one weight cohort — a pin-policy flip's survivors
        decode with the store's retained buffer, a NAMED policy's
        requests with its registry buffer, while current default slots
        decode with ``self.params`` — interleaved dispatches, each with
        exact per-token version attribution.
        Cohort dispatches always take the compact gather path (a
        full-width dispatch would run the other cohort's rows under the
        wrong params)."""
        self._step_counter += 1
        key = jax.random.fold_in(self._rng_key, self._step_counter)
        s = self.config.max_num_seqs
        if cohort is None:
            slots = sorted(self._active)
            params = self.params
            version = self.model_version
        else:
            slots, (cname, version) = cohort
            if cname:
                # named cohort: the registry holds the buffer (the
                # cohort's requests pin it, so it cannot have been
                # demoted or dropped; a host reload here is impossible
                # while pins are held but would be correct anyway)
                params = self._policies.params_for(cname, version)
            else:
                params = (
                    self.params
                    if version == self.model_version
                    else self.weights.params_for(version)
                )
            if params is None:
                # cannot happen while the cohort exists (its requests
                # hold pins) — decoding them on the wrong weights would
                # silently corrupt the version fence, so fail loudly
                raise RuntimeError(
                    f"no weight buffer for pinned version "
                    f"{cname or 'default'}@v{version}"
                )
        pps = self._pages_bound(margin, slots)
        n_active = len(slots)
        rows = self._decode_rows_bucket(n_active) if self._compact_enabled else s
        want_rope = bool(self._slot_mm.any())
        # after the gate's STICKY auto-disable, slots realign to their
        # canonical boundaries within one chunk each (emission caps
        # there) and full regular chunks preserve alignment forever —
        # once every active slot sits on a boundary, latch the replay
        # machinery off so every later dispatch runs the plain spec-off
        # program instead of paying the boundary-to-now pool gather per
        # chunk. In-flight REGULAR full chunks are tolerated (the host
        # length view lags them, but they advance every surviving slot
        # by exactly decode_chunk — or cap it at its boundary — so
        # alignment mod decode_chunk is unchanged when they land); an
        # in-flight verify chunk (partial accepts move slots off
        # boundaries) defers the latch to a later dispatch.
        if (
            self._spec_configured
            and not self._spec_replay_off
            and self._spec_gate.disabled
        ):
            cq = max(1, self.config.decode_chunk)
            if all(
                c["spec_draft_lens"] is None and c["steps"] == cq
                for c in self._inflight
            ) and all(
                (self._cached_len[sl] - self._align_base[sl]) % cq == 0
                for sl in self._active
            ):
                self._spec_replay_off = True
        spec_align = self._spec_configured and not self._spec_replay_off
        # plain per-slot 1-D arrays: listed ONCE, gathered/aliased by the
        # loop below. Arrays with extra semantics (active &valid, stops
        # axis=0, lens zeroed on padding, rope conditional, last_rows) are
        # handled explicitly after.
        plain_attrs = (
            "_cur_tokens", "_temp_dev", "_top_p_dev", "_top_k_dev",
            "_greedy_dev", "_remaining", "_no_stop",
        )
        # full-width = identity row map (row r IS slot r). Cohort
        # dispatches never take it — the identity map would cover the
        # OTHER cohort's slots too — and BOTH the gather below and the
        # post-dispatch scatter key off this one flag (a rows==s cohort
        # dispatch is still row-gathered, so assigning its row-space
        # results as slot-space state would corrupt the other cohort)
        full_width = rows >= s and cohort is None
        if full_width:
            rows = s
            row_slots = np.arange(s, dtype=np.int32)
            tables_dev = jnp.asarray(self._tables[:, :pps])
            st = {a: getattr(self, a) for a in plain_attrs}
            active = self._active_dev
            stops, lens = self._stop_tokens, self._lens_dev
            rope = self._rope_delta_dev if want_rope else None
            # identity row→slot map, built ONCE (letting decode_multi
            # default it would re-create the arange eagerly inside the
            # dispatch scope — a stray compile on the rung's bill)
            slot_ids_dev = self._identity_slots
            align_dev = self._align_base_dev if spec_align else None
        else:
            # compact dispatch: gather per-slot state into the row space.
            # Padding rows carry slot id `s` — their gathers CLIP to slot
            # s-1 but `valid` forces them inactive (no emission, no KV
            # write), and the post-dispatch scatter DROPS them.
            row_slots = np.full(rows, s, np.int32)
            row_slots[:n_active] = slots
            clipped = jnp.asarray(np.minimum(row_slots, s - 1))
            valid = jnp.asarray(row_slots < s)
            tables_np = np.full(
                (rows, pps), self.cache_config.num_pages, np.int32
            )
            tables_np[:n_active] = self._tables[slots, :pps]
            tables_dev = jnp.asarray(tables_np)
            st = {
                a: jnp.take(getattr(self, a), clipped)
                for a in plain_attrs
            }
            active = jnp.take(self._active_dev, clipped) & valid
            stops = jnp.take(self._stop_tokens, clipped, axis=0)
            lens = jnp.where(valid, jnp.take(self._lens_dev, clipped), 0)
            rope = (
                jnp.take(self._rope_delta_dev, clipped) if want_rope
                else None
            )
            slot_ids_dev = jnp.asarray(row_slots)
            align_dev = (
                jnp.where(valid, jnp.take(self._align_base_dev, clipped), 0)
                if spec_align else None
            )
        # canonical-alignment replay width (spec engines only): partial
        # draft accepts leave slots mid-chunk; the program replays
        # boundary-to-now K/V so numerics never depend on dispatch
        # boundaries (rl = 0 everywhere reduces to the plain program)
        replay = max(1, self.config.decode_chunk) - 1 if spec_align else 0
        spec_draft_lens: Optional[np.ndarray] = None
        if drafts is not None:
            # draft rows in ROW space (compact or full-width alike):
            # rows without a proposal carry draft_len 0 — a plain
            # single-token step for them inside the same dispatch
            kd = steps - 1
            draft_np = np.zeros((rows, kd), np.int32)
            spec_draft_lens = np.zeros(rows, np.int32)
            for r_ in range(rows):
                sl_ = int(row_slots[r_])
                toks_d = drafts.get(sl_) if sl_ < s else None
                if toks_d:
                    m_ = min(len(toks_d), kd)
                    draft_np[r_, :m_] = toks_d[:m_]
                    spec_draft_lens[r_] = m_
            # hoisted eager conversions (see the prefill dispatch note)
            draft_dev = jnp.asarray(draft_np)
            draft_lens_dev = jnp.asarray(spec_draft_lens)
            with goodput.dispatch_scope(
                self.compiles, "spec_verify",
                precompile_lib.spec_sig(rows, steps, pps, replay),
            ):
                (
                    self.cache, toks, logps, emitted, active_after,
                    remaining_a, no_stop_a, lens_a, new_last, cur_next,
                ) = model_runner.spec_verify(
                    params, self.model_config, self.cache,
                    tables_dev, lens,
                    st["_cur_tokens"], draft_dev,
                    draft_lens_dev, active, st["_remaining"],
                    st["_no_stop"], stops, key,
                    st["_temp_dev"], st["_top_p_dev"], st["_top_k_dev"],
                    st["_greedy_dev"], k=steps,
                    topk_bound=self._sampling_mode(),
                    attn_impl=self._attn_impl,
                    ppcb=self.config.pages_per_compute_block,
                    spb=self.config.slots_per_block,
                    last_rows=self._last_rows,
                    rope_delta=rope,
                    slot_ids=slot_ids_dev,
                    align_base=align_dev,
                    replay=replay,
                )
        else:
            with goodput.dispatch_scope(
                self.compiles, "decode",
                precompile_lib.decode_sig(rows, steps, pps, replay),
            ):
                out = model_runner.decode_multi(
                    params, self.model_config, self.cache,
                    tables_dev, lens,
                    st["_cur_tokens"], active, st["_remaining"],
                    st["_no_stop"], stops, key,
                    st["_temp_dev"], st["_top_p_dev"], st["_top_k_dev"],
                    st["_greedy_dev"], steps=steps,
                    topk_bound=self._sampling_mode(),
                    attn_impl=self._attn_impl,
                    ppcb=self.config.pages_per_compute_block,
                    spb=self.config.slots_per_block,
                    last_rows=self._last_rows,
                    rope_delta=rope,
                    slot_ids=slot_ids_dev,
                    align_base=align_dev,
                    replay=replay,
                )
            (
                self.cache, toks, logps, emitted, active_after,
                remaining_a, no_stop_a, lens_a, new_last, cur_next,
            ) = out
            # next_tokens is the device-computed next input per row: a
            # replay-mode row that hit its chunk boundary mid-dispatch
            # resumes from its LAST emitted token; for plain chunks it
            # equals step steps-1's sample for every live row
        # updated per-slot state: ONE dict drives both the full-width
        # assignment and the compact row→slot scatter (padding rows drop)
        updates = {
            "_cur_tokens": cur_next,
            "_active_dev": active_after,
            "_remaining": remaining_a,
            "_no_stop": no_stop_a,
            "_lens_dev": lens_a,
        }
        if full_width:
            for a, v in updates.items():
                setattr(self, a, v)
            self._last_rows = new_last
        else:
            scat = jnp.asarray(row_slots)
            for a, v in updates.items():
                setattr(
                    self, a,
                    getattr(self, a).at[scat].set(v, mode="drop"),
                )
            self._last_rows = {
                k_: v_.at[:, scat].set(new_last[k_], mode="drop")
                for k_, v_ in self._last_rows.items()
            }
        self.total_decode_chunks += 1
        self.total_rows_dispatched += rows
        self.total_rows_active += n_active
        self._decode_rows_dispatched = rows
        self._decode_rows_active = n_active
        self.rows_dispatched_hist[rows] = (
            self.rows_dispatched_hist.get(rows, 0) + 1
        )
        if self.tracer.enabled:
            span_attrs = dict(
                rows_dispatched=rows, rows_active=n_active, steps=steps,
            )
            if spec_draft_lens is not None:
                span_attrs["spec_draft_tokens"] = int(
                    spec_draft_lens.sum()
                )
                span_attrs["spec_draft_rows"] = int(
                    (spec_draft_lens > 0).sum()
                )
            self.tracer.instant("decode_chunk", "__engine__", **span_attrs)
        # ONE packed fetch per chunk (lazy: np.asarray in _process_chunk
        # blocks; until then the device crunches the next chunk). The
        # pack program's shape follows the dispatch's (rows, steps), so
        # its compile is attributed to the same ladder rung — the AOT
        # precompiler compiles it alongside the forward + merge.
        if drafts is not None:
            pack_scope = goodput.dispatch_scope(
                self.compiles, "spec_verify",
                precompile_lib.spec_sig(rows, steps, pps, replay),
            )
        else:
            pack_scope = goodput.dispatch_scope(
                self.compiles, "decode",
                precompile_lib.decode_sig(rows, steps, pps, replay),
            )
        with pack_scope:
            packed = model_runner.pack_host(
                toks, logps, emitted, active_after
            )
        self._inflight.append(
            {
                "packed": packed,
                "steps": steps,
                # worst-case token growth of this chunk (for later
                # dispatches' page margins — verify and regular chunk
                # sizes can mix in the pipeline)
                "max_tokens": steps,
                # per-row draft lengths of a verify chunk (accept-rate
                # accounting happens at process time, None = regular)
                "spec_draft_lens": spec_draft_lens,
                # dispatch-time row→slot snapshot + slot→request snapshot:
                # a slot finished and re-admitted between dispatch and
                # processing must not absorb this chunk's stale results
                "row_slots": row_slots,
                "reqs": dict(self._active),
                "version": version,
            }
        )

    def _process_chunk(self, chunk: Dict[str, Any]):
        steps = chunk["steps"]
        row_slots = chunk["row_slots"]
        s = self.config.max_num_seqs
        r = len(row_slots)
        packed = np.asarray(chunk["packed"])  # blocks on the device here
        n = steps * r
        h_toks = packed[:n].reshape(steps, r).astype(np.int64)
        h_logps = packed[n : 2 * n].reshape(steps, r)
        h_emitted = packed[2 * n : 3 * n].reshape(steps, r) > 0.5
        h_active = packed[3 * n : 3 * n + r] > 0.5
        now = time.monotonic()
        n_emitted = int(h_emitted.sum())
        if self._last_decode_mark is not None and n_emitted:
            dt = now - self._last_decode_mark
            if dt > 0:
                inst = n_emitted / dt
                self._decode_tps = (
                    inst if self._decode_tps == 0.0
                    else 0.8 * self._decode_tps + 0.2 * inst
                )
        self._last_decode_mark = now
        # per-row emitted prefix length (device emission is a prefix —
        # `emitted` is the step-entry active flag, which only falls)
        n_emit = np.where(
            h_emitted.all(axis=0), steps, h_emitted.argmin(axis=0)
        )
        dl = chunk.get("spec_draft_lens")
        # verify-chunk acceptance accounting runs AFTER the row loop on
        # the HOST-truncated emit counts: the device buffer only holds
        # the first 8 stop ids, so a stop landing inside an accepted
        # draft is caught below — those positions are never delivered
        # and must not count as accepted (they would inflate the gate's
        # EWMA and delay auto-disable)
        n_emit_host = n_emit.copy() if dl is not None else None
        for row in range(r):
            slot = int(row_slots[row])
            if slot >= s:
                continue  # compaction padding row
            req = chunk["reqs"].get(slot)
            if req is None or self._active.get(slot) is not req:
                continue  # finished/preempted since dispatch
            k = int(n_emit[row])
            stopped_host = False
            if k:
                # host backstop over the FULL stop list (the device buffer
                # only holds the first 8 stop ids), honoring
                # min_new_tokens: the token at step t is output index
                # len(output_ids) + t + 1
                if req.stop_token_ids:
                    hits = np.isin(
                        h_toks[:k, row],
                        np.asarray(req.stop_token_ids, np.int64),
                    )
                    t0 = req.min_new_tokens - len(req.output_ids) - 1
                    if t0 > 0:
                        hits[:t0] = False
                    if hits.any():
                        k = int(np.argmax(hits)) + 1
                        stopped_host = True
                        if n_emit_host is not None:
                            n_emit_host[row] = k
                if req.first_token_time is None:
                    req.first_token_time = now
                req.output_ids.extend(int(t) for t in h_toks[:k, row])
                req.output_logprobs.extend(
                    float(x) for x in h_logps[:k, row]
                )
                req.output_versions.extend([chunk["version"]] * k)
                if self._proposer is not None:
                    self._proposer.extend(
                        slot, [int(t) for t in h_toks[:k, row]]
                    )
                # each emitted step cached the slot's previous input token
                self._cached_len[slot] += k
                self.total_generated_tokens += k
                self.ledger.note_tokens(k)
            if stopped_host:
                self._finish(slot, "stop")
            elif not h_active[row]:
                self._finish(slot, "length")
        if dl is not None:
            # per-row accepted drafts = delivered - 1 (the bonus token is
            # free, not a draft), capped by what was actually drafted
            self._observe_spec(
                int(dl.sum()),
                int(np.minimum(np.maximum(n_emit_host - 1, 0), dl).sum()),
                rows=int((n_emit_host > 0).sum()),
            )

    def _observe_spec(
        self, drafted: int, accepted: int, rows: int = 0
    ) -> None:
        """Accept-rate accounting for one verify chunk + the auto-disable
        gate (sustained accept rates below the floor make drafting pure
        overhead — the gate turns speculation off sticky)."""
        self.total_spec_chunks += 1
        self.spec_draft_tokens_total += drafted
        self.spec_accepted_tokens_total += accepted
        gate = self._spec_gate
        still_on = gate.observe(drafted, accepted)
        if not still_on and not self._spec_disable_logged:
            self._spec_disable_logged = True
            logger.warning(
                f"speculative decoding auto-disabled: accept-rate EWMA "
                f"{gate.ewma:.3f} stayed below floor {gate.floor} for "
                f"{gate.patience} verify chunks"
            )
        if self.tracer.enabled:
            # rows = rows that emitted this round (each contributes one
            # guaranteed base token on top of its accepted drafts —
            # trace_report --spec needs it for verified tok/s)
            self.tracer.instant(
                "spec_verify", "__engine__",
                drafted=drafted, accepted=accepted, rows=rows,
            )

    def _sample_and_append(
        self, logits: jnp.ndarray, only_slots: List[int]
    ):
        """Sample one token per slot from a full [S, V] stack (one static
        shape for every admission/decode step) and handle stops for
        `only_slots`."""
        self._step_counter += 1
        key = jax.random.fold_in(self._rng_key, self._step_counter)
        mode = self._sampling_mode()
        with goodput.dispatch_scope(
            self.compiles, "sample", precompile_lib.sample_sig(mode)
        ):
            toks, logps = model_runner.sample_tokens(
                logits, key, self._temp_dev, self._top_p_dev,
                self._top_k_dev, self._greedy_dev,
                topk_bound=mode,
            )
            # the packed fetch's program shape is fixed ([S]+[S]) — it
            # rides the sample rung so its compile never lands untagged
            fetched = model_runner.pack_host(toks, logps)
        # record sampled tokens as the next decode inputs for these slots
        sl = jnp.asarray(np.asarray(only_slots, np.int32))
        self._cur_tokens = self._cur_tokens.at[sl].set(toks[sl])
        s = self.config.max_num_seqs
        packed = np.asarray(fetched)
        host_toks = packed[:s].astype(np.int64)
        host_logps = packed[s:]
        self._append_sampled(host_toks, host_logps, only_slots)

    def _append_sampled(
        self, toks: np.ndarray, logps: np.ndarray, only_slots: List[int]
    ):
        for slot in sorted(only_slots):
            i = slot
            req = self._active[slot]
            if req.first_token_time is None:
                req.first_token_time = time.monotonic()
            req.output_ids.append(int(toks[i]))
            req.output_logprobs.append(float(logps[i]))
            # the admission-time first token: _install just stamped
            # weight_version (== model_version on the default line, the
            # resolved line version on a named one) — exact either way
            req.output_versions.append(req.weight_version)
            if self._proposer is not None:
                self._proposer.extend(slot, [int(toks[i])])
            self.total_generated_tokens += 1
            self.ledger.note_tokens(1)
            out_len = len(req.output_ids)
            total_len = len(req.input_ids) + out_len
            stop_hit = (
                int(toks[i]) in req.stop_token_ids
                and out_len >= req.min_new_tokens
            )
            if stop_hit:
                self._finish(slot, "stop")
            elif (
                out_len >= req.max_new_tokens
                or total_len >= self.config.max_model_len
            ):
                self._finish(slot, "length")

    def _finish(self, slot: int, reason: str):
        req = self._active.pop(slot)
        if reason == "abort":
            self.total_aborted += 1
        elif req.deadline_at is not None:
            # soft-deadline outcome, counted only on real completions
            # (an abort is a pause-window resume, not a final answer)
            if time.monotonic() > req.deadline_at:
                self.deadline_misses_total += 1
                self.tracer.instant(
                    "deadline_miss", req.rid,
                    sched_class=req.priority,
                    late_s=round(
                        time.monotonic() - req.deadline_at, 4
                    ),
                )
        # the slot's pages hold the prompt plus all generated tokens
        # except the last sampled one (it was never fed back). A request
        # that finished pinned to a pre-flip version holds OLD-version
        # KV: never park it for new-version claimants. A NAMED request
        # parks into its own (policy, version) namespace — but only
        # while that pair still serves (no future claimants otherwise)
        # — and always drops its registry pin.
        if req.policy:
            self._release_slot(
                slot,
                park_tokens=(
                    req.all_tokens
                    if self.config.prefix_reuse_min > 0
                    and self._policies.is_live(
                        req.policy, req.weight_version
                    )
                    else None
                ),
                ns=(req.policy, req.weight_version),
            )
            self._policies.release(req.policy, req.weight_version)
            self._policies.note_tokens(req.policy, len(req.output_ids))
        else:
            self._release_slot(
                slot,
                park_tokens=(
                    req.all_tokens
                    if self.config.prefix_reuse_min > 0
                    and req.weight_version == self.model_version
                    else None
                ),
            )
            if req.weight_version != self.model_version:
                # last pin out drops the old buffer (HBM back)
                self.weights.release(req.weight_version)
        now = time.monotonic()
        if reason != "abort":
            # aborts are pause-window resumes, not client-visible
            # completions — they'd poison the latency distributions
            # (and must not count toward serving-readiness either)
            self._completed_requests += 1
            self._hists["ttft_seconds"][req.priority].observe(
                (req.first_token_time or now) - req.submit_time
            )
            self._hists["request_latency_seconds"][req.priority].observe(
                now - req.submit_time
            )
        if self.tracer.enabled:
            # decode covers first-token → finish; request is the full
            # submit → finish lifecycle (what a client timeline wants)
            self.tracer.record(
                "decode", req.rid, req.first_token_time or now, now,
                completion_tokens=len(req.output_ids), reason=reason,
                preemptions=req.preemptions,
            )
            self.tracer.record(
                "request", req.rid, req.submit_time, now,
                prompt_tokens=len(req.input_ids),
                completion_tokens=len(req.output_ids), reason=reason,
                model_version=self.model_version,
            )
        # drop the rid's trace binding (an aborted request that resumes
        # re-binds from its next /generate call's header)
        self.tracer.unbind_trace(req.rid)
        result = {
            "output_ids": req.output_ids,
            "output_logprobs": req.output_logprobs,
            "output_versions": req.output_versions,
            "meta_info": {
                "finish_reason": {"type": reason},
                "prompt_tokens": len(req.input_ids),
                "completion_tokens": len(req.output_ids),
                "latency": now - req.submit_time,
                "ttft": (req.first_token_time or now) - req.submit_time,
                "model_version": self.model_version,
                "preemptions": req.preemptions,
                "cached_tokens": req.cached_tokens,
                # named requests carry their handle resolution; the
                # default line adds NO new keys (strict no-op contract)
                **(
                    {
                        "policy": req.policy,
                        "policy_version": req.weight_version,
                    }
                    if req.policy
                    else {}
                ),
            },
        }
        if not req.future.done():
            req.future.set_result(result)
