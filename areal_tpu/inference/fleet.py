"""Fleet resilience plane: health-aware membership over N generation servers.

The async architecture assumes a long-lived disaggregated fleet behind the
trainer; at the scale the north star names, server loss is a *when*. This
module is the piece every layer consults before trusting an address:

- **Per-server state machine** — ``HEALTHY → SUSPECT → DEAD →
  RECOVERING (→ HEALTHY)``, driven by active ``/health`` probes AND
  passive failure/success reports from clients (``engine/remote.py``
  reports every request outcome, so a crash is noticed at the first
  failed call, not the next probe tick).
- **Circuit breaker with half-open probes** — a DEAD server stops
  receiving traffic and is probed at most every
  ``halfopen_interval_s``; one success moves it to RECOVERING, where
  ``recover_threshold`` consecutive successes must land before it is
  schedulable again (a flapping server cannot re-enter the fleet on one
  lucky probe).
- **Graceful drain** — ``drain(addr)`` marks a server DRAINING
  (unschedulable, but not a failure); a server whose ``/health`` body
  says ``draining`` is classified the same way, so a server-initiated
  drain propagates without any control-plane call.
- **Dynamic membership** — when constructed with a name_resolve
  ``membership_key``, the monitor polls the gen_servers subtree and
  joins/leaves servers live (discovered entries only: explicitly seeded
  or ``/register``-ed servers are never removed by the watch).

The monitor never *chooses* servers — ``engine/remote.choose_server``
and ``inference/router.RouterState.schedule`` own policy — it answers
``is_schedulable`` and fires ``on_dead/on_join/on_leave`` callbacks so
owners can evict affinity and reclaim capacity. Scheduling semantics:
HEALTHY and SUSPECT take traffic (one failed probe must not drain a
server that is merely slow); DEAD, RECOVERING, and DRAINING do not.

Everything is injectable (``probe_fn``, ``time_fn``) so the state
machine is unit-testable without sockets or sleeps; the chaos harness
(``utils/chaos.py``) covers the integration side.
"""

import enum
import json
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from areal_tpu.api.cli_args import FleetConfig
from areal_tpu.utils import logging as logging_util
from areal_tpu.utils import name_resolve

logger = logging_util.getLogger("FleetMonitor")


class ServerState(str, enum.Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    DEAD = "dead"
    RECOVERING = "recovering"
    DRAINING = "draining"


# states that may receive new work
_SCHEDULABLE = (ServerState.HEALTHY, ServerState.SUSPECT)


class ServerHealth:
    __slots__ = (
        "addr", "state", "fails", "successes", "probe_latency_s",
        "last_probe", "last_transition", "source",
    )

    def __init__(self, addr: str, source: str = "seed",
                 t: float = 0.0):
        self.addr = addr
        self.state = ServerState.HEALTHY
        self.fails = 0  # consecutive failures (probe or passive)
        self.successes = 0  # consecutive successes
        self.probe_latency_s = 0.0
        self.last_probe = -float("inf")
        self.last_transition = t
        self.source = source  # seed | registered | discovered


def default_probe(addr: str, timeout: float) -> Tuple[str, float]:
    """GET /health → ("ok" | "draining" | "fail", latency_s)."""
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(
            f"http://{addr}/health", timeout=timeout
        ) as r:
            latency = time.monotonic() - t0
            if r.status != 200:
                return "fail", latency
            try:
                status = json.loads(r.read()).get("status", "ok")
            except Exception:
                status = "ok"
            return ("draining" if status == "draining" else "ok"), latency
    except Exception:
        return "fail", time.monotonic() - t0


class FleetMonitor:
    def __init__(
        self,
        addresses: List[str],
        config: Optional[FleetConfig] = None,
        probe_fn: Optional[Callable[[str], Tuple[str, float]]] = None,
        time_fn: Callable[[], float] = time.monotonic,
        membership_key: Optional[str] = None,
        on_join: Optional[Callable[[str], None]] = None,
        on_leave: Optional[Callable[[str], None]] = None,
        on_dead: Optional[Callable[[str], None]] = None,
        on_recover: Optional[Callable[[str], None]] = None,
        seed_source: str = "seed",
        service: str = "gen",
    ):
        self.config = config or FleetConfig()
        # which plane this monitor watches ("gen" | "env" | "verifier"):
        # log lines and per_server() carry it so one process fronting
        # several fleets stays debuggable
        self.service = service
        self._probe_fn = probe_fn or (
            lambda a: default_probe(a, self.config.probe_timeout_s)
        )
        self._time = time_fn
        self.membership_key = membership_key
        self.on_join = on_join
        self.on_leave = on_leave
        self.on_dead = on_dead
        # fired when a server RE-ENTERS rotation after being out of it
        # (DEAD→RECOVERING→HEALTHY or DRAINING→HEALTHY) — owners verify
        # the server didn't miss weight updates while it was gone
        self.on_recover = on_recover
        self._lock = threading.RLock()
        now = self._time()
        # owners that DISCOVERED their fleet from name_resolve seed with
        # source="discovered", so the membership watch may remove the
        # initial servers too when their registrations vanish; explicit
        # "seed" servers are never watched away
        self._servers: Dict[str, ServerHealth] = {
            a: ServerHealth(a, source=seed_source, t=now)
            for a in addresses
        }
        # fleet-wide counters (owners feed failovers via record_failover)
        self.failovers_total = 0
        self.requests_migrated_total = 0
        self.probes_total = 0
        self.probe_failures_total = 0
        self._last_membership_poll = -float("inf")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # Membership
    # ------------------------------------------------------------------
    def addresses(self) -> List[str]:
        with self._lock:
            return list(self._servers)

    def add_server(self, addr: str, source: str = "registered") -> bool:
        """Join a server (idempotent). New members start HEALTHY — the
        next probe demotes them if they lied."""
        with self._lock:
            if addr in self._servers:
                return False
            self._servers[addr] = ServerHealth(addr, source, self._time())
        logger.info(f"{self.service} fleet join: {addr} ({source})")
        if self.on_join:
            self.on_join(addr)
        return True

    def remove_server(self, addr: str) -> bool:
        with self._lock:
            if self._servers.pop(addr, None) is None:
                return False
        logger.info(f"{self.service} fleet leave: {addr}")
        if self.on_leave:
            self.on_leave(addr)
        return True

    def poll_membership(self) -> None:
        """Diff the name_resolve gen_servers subtree against the fleet:
        new registrations join, vanished DISCOVERED entries leave."""
        if not self.membership_key:
            return
        try:
            current = set(name_resolve.get_subtree(self.membership_key))
        except Exception as e:  # rendezvous hiccup ≠ fleet change
            logger.warning(f"membership poll failed: {e}")
            return
        with self._lock:
            known = set(self._servers)
            discovered_gone = [
                a for a, h in self._servers.items()
                if h.source == "discovered" and a not in current
            ]
        for addr in current - known:
            self.add_server(addr, source="discovered")
        for addr in discovered_gone:
            self.remove_server(addr)

    # ------------------------------------------------------------------
    # State machine
    # ------------------------------------------------------------------
    def state(self, addr: str) -> Optional[ServerState]:
        with self._lock:
            h = self._servers.get(addr)
            return h.state if h else None

    def is_schedulable(self, addr: str) -> bool:
        with self._lock:
            h = self._servers.get(addr)
            return h is not None and h.state in _SCHEDULABLE

    def schedulable_addresses(self) -> List[str]:
        with self._lock:
            return [
                a for a, h in self._servers.items()
                if h.state in _SCHEDULABLE
            ]

    def _transition(self, h: ServerHealth, to: ServerState) -> Optional[str]:
        """Returns the addr to fire on_dead for (outside the lock)."""
        if h.state is to:
            return None
        logger.info(
            f"{self.service} fleet: {h.addr} "
            f"{h.state.value} -> {to.value}"
        )
        h.state = to
        h.last_transition = self._time()
        return h.addr if to is ServerState.DEAD else None

    def _apply_failure(self, h: ServerHealth) -> Optional[str]:
        h.fails += 1
        h.successes = 0
        cfg = self.config
        if h.state is ServerState.DRAINING:
            return None  # draining servers are already out of rotation
        if h.state is ServerState.RECOVERING:
            # a half-open failure re-opens the circuit immediately
            return self._transition(h, ServerState.DEAD)
        if (
            h.state is ServerState.HEALTHY
            and h.fails >= cfg.suspect_threshold
        ):
            self._transition(h, ServerState.SUSPECT)
        if (
            h.state is ServerState.SUSPECT
            and h.fails >= cfg.dead_threshold
        ):
            return self._transition(h, ServerState.DEAD)
        return None

    def _apply_success(
        self, h: ServerHealth, from_probe: bool = False
    ) -> Optional[str]:
        """Returns the addr to fire on_recover for (outside the lock)
        when the server RE-ENTERED rotation from an out-of-rotation
        state; SUSPECT→HEALTHY is not a recovery (it never left)."""
        h.fails = 0
        h.successes += 1
        if h.state is ServerState.HEALTHY:
            return None
        if h.state is ServerState.DRAINING:
            # only a PROBE may undo a drain (the server's own /health no
            # longer says draining — drain cancelled or it restarted
            # admission); a passive success is just in-flight work from
            # before the drain finishing, not a rejoin signal
            if from_probe:
                self._transition(h, ServerState.HEALTHY)
                return h.addr
        elif h.state is ServerState.SUSPECT:
            self._transition(h, ServerState.HEALTHY)
        elif h.state is ServerState.DEAD:
            # first half-open success: circuit half-closes
            self._transition(h, ServerState.RECOVERING)
        elif h.state is ServerState.RECOVERING:
            if h.successes >= self.config.recover_threshold:
                self._transition(h, ServerState.HEALTHY)
                return h.addr
        return None

    # passive signals from request outcomes ----------------------------
    def report_failure(self, addr: str) -> None:
        dead: Optional[str] = None
        with self._lock:
            h = self._servers.get(addr)
            if h is not None:
                dead = self._apply_failure(h)
        if dead and self.on_dead:
            self.on_dead(dead)

    def report_success(self, addr: str) -> None:
        recovered: Optional[str] = None
        with self._lock:
            h = self._servers.get(addr)
            if h is not None:
                recovered = self._apply_success(h)
        if recovered and self.on_recover:
            self.on_recover(recovered)

    def drain(self, addr: str) -> bool:
        with self._lock:
            h = self._servers.get(addr)
            if h is None:
                return False
            self._transition(h, ServerState.DRAINING)
            return True

    def record_failover(self, migrated: bool) -> None:
        """One request hopped servers; migrated = it carried accumulated
        tokens (a resumed suffix), not a fresh start."""
        with self._lock:
            self.failovers_total += 1
            if migrated:
                self.requests_migrated_total += 1

    # ------------------------------------------------------------------
    # Probing
    # ------------------------------------------------------------------
    def probe_once(self) -> None:
        """One probe sweep. DEAD servers are only probed once per
        half-open window; everyone else is probed every sweep."""
        now = self._time()
        with self._lock:
            due = [
                h.addr for h in self._servers.values()
                if not (
                    h.state is ServerState.DEAD
                    and now - h.last_probe
                    < self.config.halfopen_interval_s
                )
            ]
        for addr in due:
            status, latency = self._probe_fn(addr)
            dead: Optional[str] = None
            recovered: Optional[str] = None
            with self._lock:
                h = self._servers.get(addr)
                if h is None:  # left the fleet mid-sweep
                    continue
                h.last_probe = self._time()
                h.probe_latency_s = latency
                self.probes_total += 1
                if status == "ok":
                    recovered = self._apply_success(h, from_probe=True)
                elif status == "draining":
                    # server-initiated drain: out of rotation, no circuit
                    self._transition(h, ServerState.DRAINING)
                else:
                    self.probe_failures_total += 1
                    dead = self._apply_failure(h)
            if dead and self.on_dead:
                self.on_dead(dead)
            if recovered and self.on_recover:
                self.on_recover(recovered)

    # ------------------------------------------------------------------
    # Background loop
    # ------------------------------------------------------------------
    def start(self) -> "FleetMonitor":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="fleet-monitor"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)

    def _loop(self) -> None:
        interval = max(0.05, self.config.probe_interval_s)
        while not self._stop.wait(interval):
            try:
                self.probe_once()
                if (
                    self.membership_key
                    and self.config.watch_membership
                    and self._time() - self._last_membership_poll
                    >= self.config.membership_poll_s
                ):
                    self._last_membership_poll = self._time()
                    self.poll_membership()
            except Exception as e:  # the monitor must never die
                logger.error(f"fleet monitor sweep failed: {e}")

    # ------------------------------------------------------------------
    # Metrics
    # ------------------------------------------------------------------
    def state_metrics(self) -> Dict[str, float]:
        """Fleet-shape gauges only (owners with their own failover
        counters merge these; see RouterState.metrics)."""
        with self._lock:
            states = [h.state for h in self._servers.values()]
            return {
                "fleet_servers": float(len(states)),
                "fleet_healthy_servers": float(
                    sum(s is ServerState.HEALTHY for s in states)
                ),
                "fleet_suspect_servers": float(
                    sum(s is ServerState.SUSPECT for s in states)
                ),
                "fleet_dead_servers": float(
                    sum(s is ServerState.DEAD for s in states)
                ),
                "fleet_recovering_servers": float(
                    sum(s is ServerState.RECOVERING for s in states)
                ),
                "fleet_draining_servers": float(
                    sum(s is ServerState.DRAINING for s in states)
                ),
                # open circuits = DEAD; half-open = RECOVERING
                "fleet_circuit_open": float(
                    sum(s is ServerState.DEAD for s in states)
                ),
                "fleet_circuit_half_open": float(
                    sum(s is ServerState.RECOVERING for s in states)
                ),
                "fleet_probes_total": float(self.probes_total),
                "fleet_probe_failures_total": float(
                    self.probe_failures_total
                ),
            }

    def metrics(self) -> Dict[str, float]:
        out = self.state_metrics()
        with self._lock:
            out["failovers_total"] = float(self.failovers_total)
            out["requests_migrated_total"] = float(
                self.requests_migrated_total
            )
        return out

    def per_server(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                a: {
                    "service": self.service,
                    "state": h.state.value,
                    "probe_latency_s": h.probe_latency_s,
                    "consecutive_failures": float(h.fails),
                }
                for a, h in self._servers.items()
            }
