"""Fleet resilience plane: health-aware membership over N generation servers.

The async architecture assumes a long-lived disaggregated fleet behind the
trainer; at the scale the north star names, server loss is a *when*. This
module is the piece every layer consults before trusting an address:

- **Per-server state machine** — ``HEALTHY → SUSPECT → DEAD →
  RECOVERING (→ HEALTHY)``, driven by active ``/health`` probes AND
  passive failure/success reports from clients (``engine/remote.py``
  reports every request outcome, so a crash is noticed at the first
  failed call, not the next probe tick).
- **Circuit breaker with half-open probes** — a DEAD server stops
  receiving traffic and is probed at most every
  ``halfopen_interval_s``; one success moves it to RECOVERING, where
  ``recover_threshold`` consecutive successes must land before it is
  schedulable again (a flapping server cannot re-enter the fleet on one
  lucky probe).
- **Graceful drain** — ``drain(addr)`` marks a server DRAINING
  (unschedulable, but not a failure); a server whose ``/health`` body
  says ``draining`` is classified the same way, so a server-initiated
  drain propagates without any control-plane call.
- **Dynamic membership** — when constructed with a name_resolve
  ``membership_key``, the monitor polls the gen_servers subtree and
  joins/leaves servers live (discovered entries only: explicitly seeded
  or ``/register``-ed servers are never removed by the watch).

The monitor never *chooses* servers — ``engine/remote.choose_server``
and ``inference/router.RouterState.schedule`` own policy — it answers
``is_schedulable`` and fires ``on_dead/on_join/on_leave`` callbacks so
owners can evict affinity and reclaim capacity. Scheduling semantics:
HEALTHY and SUSPECT take traffic (one failed probe must not drain a
server that is merely slow); DEAD, RECOVERING, and DRAINING do not.

Everything is injectable (``probe_fn``, ``time_fn``) so the state
machine is unit-testable without sockets or sleeps; the chaos harness
(``utils/chaos.py``) covers the integration side.
"""

import enum
import json
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from areal_tpu.api.cli_args import FleetConfig
from areal_tpu.utils import logging as logging_util
from areal_tpu.utils import name_resolve

logger = logging_util.getLogger("FleetMonitor")


class ServerState(str, enum.Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    DEAD = "dead"
    RECOVERING = "recovering"
    DRAINING = "draining"
    # r11: a cold server still compiling its shape ladder (/health
    # reports "warming"). Out of rotation — routing traffic at it buys
    # multi-second first-token stalls — but unlike DEAD it is alive and
    # MUST receive weight updates, or it would re-enter rotation stale.
    # r14: with `--precompile` + a seeded compile cache the window is
    # the AOT replay of the exact ladder (seconds of disk retrieval,
    # ladder_coverage rising to 1.0 with zero traffic) — the same state
    # machine, just fast enough that autoscaler spawns land inside the
    # spike they were launched for.
    WARMING = "warming"


# states that may receive new work
_SCHEDULABLE = (ServerState.HEALTHY, ServerState.SUSPECT)
# states that must be included in weight-update fan-outs: schedulable
# servers plus warming ones (skipping a warming server would make it
# serve stale weights the moment it finishes compiling)
_UPDATE_TARGETS = _SCHEDULABLE + (ServerState.WARMING,)


class ServerHealth:
    __slots__ = (
        "addr", "state", "fails", "successes", "probe_latency_s",
        "last_probe", "last_transition", "source",
        "running_requests", "queued_requests", "max_num_seqs",
        "warming_since", "ladder_coverage", "ready_lead_s",
    )

    def __init__(self, addr: str, source: str = "seed",
                 t: float = 0.0):
        self.addr = addr
        self.state = ServerState.HEALTHY
        self.fails = 0  # consecutive failures (probe or passive)
        self.successes = 0  # consecutive successes
        self.probe_latency_s = 0.0
        self.last_probe = -float("inf")
        self.last_transition = t
        self.source = source  # seed | registered | discovered
        # load view from the last /health probe (r10): running vs queued
        # SEPARATELY — the router's overload shed and the autoscaler
        # must tell a queue backlog (add capacity) apart from busy
        # decode (don't). -1 = not reported yet / pre-r10 server.
        self.running_requests = -1.0
        self.queued_requests = -1.0
        self.max_num_seqs = -1.0
        # cold-start accounting (r11): when this server was first seen
        # warming, its last reported shape-ladder coverage, and the
        # measured warming→serving lead once it crossed over
        self.warming_since: Optional[float] = None
        self.ladder_coverage = -1.0
        self.ready_lead_s = -1.0


def default_probe(addr: str, timeout: float) -> Tuple[str, float, Dict]:
    """GET /health → ("ok" | "warming" | "draining" | "fail",
    latency_s, load_info). ``load_info`` carries the body's
    running_requests / queued_requests / max_num_seqs /
    ladder_coverage when the server reports them (empty otherwise)."""
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(
            f"http://{addr}/health", timeout=timeout
        ) as r:
            latency = time.monotonic() - t0
            if r.status != 200:
                return "fail", latency, {}
            info: Dict = {}
            try:
                body = json.loads(r.read())
                status = body.get("status", "ok")
                for k in (
                    "running_requests", "queued_requests",
                    "max_num_seqs", "ladder_coverage",
                ):
                    if k in body:
                        info[k] = float(body[k])
            except Exception:
                status = "ok"
            if status not in ("draining", "warming"):
                status = "ok"
            return status, latency, info
    except Exception:
        return "fail", time.monotonic() - t0, {}


class FleetMonitor:
    def __init__(
        self,
        addresses: List[str],
        config: Optional[FleetConfig] = None,
        probe_fn: Optional[Callable[[str], Tuple[str, float]]] = None,
        time_fn: Callable[[], float] = time.monotonic,
        membership_key: Optional[str] = None,
        on_join: Optional[Callable[[str], None]] = None,
        on_leave: Optional[Callable[[str], None]] = None,
        on_dead: Optional[Callable[[str], None]] = None,
        on_recover: Optional[Callable[[str], None]] = None,
        seed_source: str = "seed",
        service: str = "gen",
    ):
        self.config = config or FleetConfig()
        # which plane this monitor watches ("gen" | "env" | "verifier"):
        # log lines and per_server() carry it so one process fronting
        # several fleets stays debuggable
        self.service = service
        self._probe_fn = probe_fn or (
            lambda a: default_probe(a, self.config.probe_timeout_s)
        )
        self._time = time_fn
        self.membership_key = membership_key
        self.on_join = on_join
        self.on_leave = on_leave
        self.on_dead = on_dead
        # fired when a server RE-ENTERS rotation after being out of it
        # (DEAD→RECOVERING→HEALTHY or DRAINING→HEALTHY) — owners verify
        # the server didn't miss weight updates while it was gone
        self.on_recover = on_recover
        self._lock = threading.RLock()
        now = self._time()
        # owners that DISCOVERED their fleet from name_resolve seed with
        # source="discovered", so the membership watch may remove the
        # initial servers too when their registrations vanish; explicit
        # "seed" servers are never watched away
        self._servers: Dict[str, ServerHealth] = {
            a: ServerHealth(a, source=seed_source, t=now)
            for a in addresses
        }
        # fleet-wide counters (owners feed failovers via record_failover)
        self.failovers_total = 0
        self.requests_migrated_total = 0
        self.probes_total = 0
        self.probe_failures_total = 0
        # cold-start accounting (r11): warming→serving transitions seen
        self.cold_to_serving_total = 0
        self.last_cold_to_serving_s = 0.0
        self._last_membership_poll = -float("inf")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # Membership
    # ------------------------------------------------------------------
    def addresses(self) -> List[str]:
        with self._lock:
            return list(self._servers)

    def add_server(self, addr: str, source: str = "registered") -> bool:
        """Join a server (idempotent). New members start HEALTHY — the
        next probe demotes them if they lied."""
        with self._lock:
            if addr in self._servers:
                return False
            self._servers[addr] = ServerHealth(addr, source, self._time())
        logger.info(f"{self.service} fleet join: {addr} ({source})")
        if self.on_join:
            self.on_join(addr)
        return True

    def remove_server(self, addr: str) -> bool:
        with self._lock:
            if self._servers.pop(addr, None) is None:
                return False
        logger.info(f"{self.service} fleet leave: {addr}")
        if self.on_leave:
            self.on_leave(addr)
        return True

    def poll_membership(self) -> None:
        """Diff the name_resolve gen_servers subtree against the fleet:
        new registrations join, vanished DISCOVERED entries leave."""
        if not self.membership_key:
            return
        try:
            current = set(name_resolve.get_subtree(self.membership_key))
        except Exception as e:  # rendezvous hiccup ≠ fleet change
            logger.warning(f"membership poll failed: {e}")
            return
        with self._lock:
            known = set(self._servers)
            discovered_gone = [
                a for a, h in self._servers.items()
                if h.source == "discovered" and a not in current
            ]
        for addr in current - known:
            self.add_server(addr, source="discovered")
        for addr in discovered_gone:
            self.remove_server(addr)

    # ------------------------------------------------------------------
    # State machine
    # ------------------------------------------------------------------
    def state(self, addr: str) -> Optional[ServerState]:
        with self._lock:
            h = self._servers.get(addr)
            return h.state if h else None

    def is_schedulable(self, addr: str) -> bool:
        with self._lock:
            h = self._servers.get(addr)
            return h is not None and h.state in _SCHEDULABLE

    def is_update_target(self, addr: str) -> bool:
        """Whether weight-update fan-outs must include this server:
        schedulable OR warming (a cold server skipped by an update
        would re-enter rotation serving stale weights)."""
        with self._lock:
            h = self._servers.get(addr)
            return h is not None and h.state in _UPDATE_TARGETS

    def is_continuation_target(self, addr: str) -> bool:
        """Whether an IN-FLIGHT request (rid affinity / suffix-resume)
        may stay on this server. Warming only gates NEW work: the
        server is alive and holds the continuation's cached KV, and
        rerouting it would burn a needless migration. Same alive set
        as update targets (DRAINING refuses /generate, DEAD is gone)."""
        with self._lock:
            h = self._servers.get(addr)
            return h is not None and h.state in _UPDATE_TARGETS

    def schedulable_addresses(self) -> List[str]:
        with self._lock:
            return [
                a for a, h in self._servers.items()
                if h.state in _SCHEDULABLE
            ]

    def _transition(self, h: ServerHealth, to: ServerState) -> Optional[str]:
        """Returns the addr to fire on_dead for (outside the lock)."""
        if h.state is to:
            return None
        logger.info(
            f"{self.service} fleet: {h.addr} "
            f"{h.state.value} -> {to.value}"
        )
        h.state = to
        h.last_transition = self._time()
        return h.addr if to is ServerState.DEAD else None

    def _apply_failure(self, h: ServerHealth) -> Optional[str]:
        h.fails += 1
        h.successes = 0
        cfg = self.config
        if h.state is ServerState.DRAINING:
            return None  # draining servers are already out of rotation
        if h.state is ServerState.RECOVERING:
            # a half-open failure re-opens the circuit immediately
            return self._transition(h, ServerState.DEAD)
        if (
            h.state is ServerState.WARMING
            and h.fails >= cfg.dead_threshold
        ):
            # a warming server that stops answering died mid-warmup;
            # it was never in rotation, so no SUSPECT intermediate
            return self._transition(h, ServerState.DEAD)
        if (
            h.state is ServerState.HEALTHY
            and h.fails >= cfg.suspect_threshold
        ):
            self._transition(h, ServerState.SUSPECT)
        if (
            h.state is ServerState.SUSPECT
            and h.fails >= cfg.dead_threshold
        ):
            return self._transition(h, ServerState.DEAD)
        return None

    def _apply_success(
        self, h: ServerHealth, from_probe: bool = False
    ) -> Optional[str]:
        """Returns the addr to fire on_recover for (outside the lock)
        when the server RE-ENTERED rotation from an out-of-rotation
        state; SUSPECT→HEALTHY is not a recovery (it never left)."""
        h.fails = 0
        h.successes += 1
        if h.state is ServerState.HEALTHY:
            return None
        if h.state is ServerState.DRAINING:
            # only a PROBE may undo a drain (the server's own /health no
            # longer says draining — drain cancelled or it restarted
            # admission); a passive success is just in-flight work from
            # before the drain finishing, not a rejoin signal
            if from_probe:
                self._transition(h, ServerState.HEALTHY)
                return h.addr
        elif h.state is ServerState.WARMING:
            # only the server's own /health saying "ok" ends a warmup
            # (a passive request success is pre-warming in-flight work);
            # record the cold→serving lead and fire on_recover so the
            # owner verifies it didn't miss weight updates while cold
            if from_probe:
                now = self._time()
                if h.warming_since is not None:
                    h.ready_lead_s = now - h.warming_since
                    h.warming_since = None  # a later re-warm re-stamps
                    self.cold_to_serving_total += 1
                    self.last_cold_to_serving_s = h.ready_lead_s
                    logger.info(
                        f"{self.service} fleet: {h.addr} warm after "
                        f"{h.ready_lead_s:.1f}s (coverage "
                        f"{h.ladder_coverage:.2f})"
                    )
                self._transition(h, ServerState.HEALTHY)
                return h.addr
        elif h.state is ServerState.SUSPECT:
            self._transition(h, ServerState.HEALTHY)
        elif h.state is ServerState.DEAD:
            # first half-open success: circuit half-closes
            self._transition(h, ServerState.RECOVERING)
        elif h.state is ServerState.RECOVERING:
            if h.successes >= self.config.recover_threshold:
                self._transition(h, ServerState.HEALTHY)
                return h.addr
        return None

    # passive signals from request outcomes ----------------------------
    def report_failure(self, addr: str) -> None:
        dead: Optional[str] = None
        with self._lock:
            h = self._servers.get(addr)
            if h is not None:
                dead = self._apply_failure(h)
        if dead and self.on_dead:
            self.on_dead(dead)

    def report_success(self, addr: str) -> None:
        recovered: Optional[str] = None
        with self._lock:
            h = self._servers.get(addr)
            if h is not None:
                recovered = self._apply_success(h)
        if recovered and self.on_recover:
            self.on_recover(recovered)

    def drain(self, addr: str) -> bool:
        with self._lock:
            h = self._servers.get(addr)
            if h is None:
                return False
            self._transition(h, ServerState.DRAINING)
            return True

    def record_failover(self, migrated: bool) -> None:
        """One request hopped servers; migrated = it carried accumulated
        tokens (a resumed suffix), not a fresh start."""
        with self._lock:
            self.failovers_total += 1
            if migrated:
                self.requests_migrated_total += 1

    # ------------------------------------------------------------------
    # Probing
    # ------------------------------------------------------------------
    def probe_once(self) -> None:
        """One probe sweep. DEAD servers are only probed once per
        half-open window; everyone else is probed every sweep."""
        now = self._time()
        with self._lock:
            due = [
                h.addr for h in self._servers.values()
                if not (
                    h.state is ServerState.DEAD
                    and now - h.last_probe
                    < self.config.halfopen_interval_s
                )
            ]
        for addr in due:
            # injected probe_fns may return the legacy (status, latency)
            # pair; the default adds a load-info dict
            out = self._probe_fn(addr)
            status, latency = out[0], out[1]
            load = out[2] if len(out) > 2 else {}
            dead: Optional[str] = None
            recovered: Optional[str] = None
            with self._lock:
                h = self._servers.get(addr)
                if h is None:  # left the fleet mid-sweep
                    continue
                h.last_probe = self._time()
                h.probe_latency_s = latency
                if load:
                    h.running_requests = load.get(
                        "running_requests", h.running_requests
                    )
                    h.queued_requests = load.get(
                        "queued_requests", h.queued_requests
                    )
                    h.max_num_seqs = load.get(
                        "max_num_seqs", h.max_num_seqs
                    )
                if "ladder_coverage" in load:
                    h.ladder_coverage = load["ladder_coverage"]
                self.probes_total += 1
                if status == "ok":
                    recovered = self._apply_success(h, from_probe=True)
                elif status == "draining":
                    # server-initiated drain: out of rotation, no circuit
                    self._transition(h, ServerState.DRAINING)
                elif status == "warming":
                    # cold server mid-compile-storm: out of rotation
                    # (but a weight-update target) until its own
                    # /health says ok. A DEAD server that answers
                    # "warming" is alive again — half-close through
                    # WARMING rather than RECOVERING; draining wins
                    # (the server is leaving regardless of warmth)
                    if h.state is not ServerState.DRAINING:
                        if h.warming_since is None:
                            h.warming_since = self._time()
                        h.fails = 0
                        self._transition(h, ServerState.WARMING)
                else:
                    self.probe_failures_total += 1
                    dead = self._apply_failure(h)
            if dead and self.on_dead:
                self.on_dead(dead)
            if recovered and self.on_recover:
                self.on_recover(recovered)

    # ------------------------------------------------------------------
    # Background loop
    # ------------------------------------------------------------------
    def start(self) -> "FleetMonitor":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="fleet-monitor"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)

    def _loop(self) -> None:
        interval = max(0.05, self.config.probe_interval_s)
        while not self._stop.wait(interval):
            try:
                self.probe_once()
                if (
                    self.membership_key
                    and self.config.watch_membership
                    and self._time() - self._last_membership_poll
                    >= self.config.membership_poll_s
                ):
                    self._last_membership_poll = self._time()
                    self.poll_membership()
            except Exception as e:  # the monitor must never die
                logger.error(f"fleet monitor sweep failed: {e}")

    # ------------------------------------------------------------------
    # Metrics
    # ------------------------------------------------------------------
    def state_metrics(self) -> Dict[str, float]:
        """Fleet-shape gauges only (owners with their own failover
        counters merge these; see RouterState.metrics)."""
        with self._lock:
            states = [h.state for h in self._servers.values()]
            return {
                "fleet_servers": float(len(states)),
                "fleet_healthy_servers": float(
                    sum(s is ServerState.HEALTHY for s in states)
                ),
                "fleet_suspect_servers": float(
                    sum(s is ServerState.SUSPECT for s in states)
                ),
                "fleet_dead_servers": float(
                    sum(s is ServerState.DEAD for s in states)
                ),
                "fleet_recovering_servers": float(
                    sum(s is ServerState.RECOVERING for s in states)
                ),
                "fleet_draining_servers": float(
                    sum(s is ServerState.DRAINING for s in states)
                ),
                # cold-start plane (r11): servers still compiling their
                # shape ladder, and the last measured warming→serving
                # lead (the autoscaler's reaction-time truth)
                "fleet_warming_servers": float(
                    sum(s is ServerState.WARMING for s in states)
                ),
                "fleet_cold_to_serving_last_s": float(
                    self.last_cold_to_serving_s
                ),
                "fleet_cold_to_serving_total": float(
                    self.cold_to_serving_total
                ),
                # open circuits = DEAD; half-open = RECOVERING
                "fleet_circuit_open": float(
                    sum(s is ServerState.DEAD for s in states)
                ),
                "fleet_circuit_half_open": float(
                    sum(s is ServerState.RECOVERING for s in states)
                ),
                "fleet_probes_total": float(self.probes_total),
                "fleet_probe_failures_total": float(
                    self.probe_failures_total
                ),
            }

    def metrics(self) -> Dict[str, float]:
        out = self.state_metrics()
        with self._lock:
            out["failovers_total"] = float(self.failovers_total)
            out["requests_migrated_total"] = float(
                self.requests_migrated_total
            )
        return out

    def per_server(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                a: {
                    "service": self.service,
                    "state": h.state.value,
                    "probe_latency_s": h.probe_latency_s,
                    "consecutive_failures": float(h.fails),
                    "running_requests": h.running_requests,
                    "queued_requests": h.queued_requests,
                    "ladder_coverage": h.ladder_coverage,
                    "ready_lead_s": h.ready_lead_s,
                }
                for a, h in self._servers.items()
            }

    def load_map(self) -> Dict[str, Tuple[float, float]]:
        """addr → (running, queued) from the latest /health probes —
        the router load map the overload shed and autoscaler read.
        Servers that have not reported load yet are absent."""
        with self._lock:
            return {
                a: (h.running_requests, h.queued_requests)
                for a, h in self._servers.items()
                if h.queued_requests >= 0
            }


# ==========================================================================
# Fleet autoscaler (r10): size the serving fleet from observed load
# ==========================================================================
def scrape_server_load(addr: str, timeout: float = 5.0) -> Dict[str, float]:
    """One server's load observation: running/queued from ``/health``
    plus ``kv_page_utilization`` from ``/metrics`` (the PR 2/3 gauges).
    Raises on an unreachable server — the caller decides whether a
    missing observation blocks a decision."""
    status, _, info = default_probe(addr, timeout)
    if status == "fail":
        raise ConnectionError(f"{addr} failed its load probe")
    obs = {
        "running": info.get("running_requests", 0.0),
        "queued": info.get("queued_requests", 0.0),
        "slots": info.get("max_num_seqs", 0.0),
        "draining": 1.0 if status == "draining" else 0.0,
        "warming": 1.0 if status == "warming" else 0.0,
        "ladder_coverage": info.get("ladder_coverage", -1.0),
        "kv_util": 0.0,
    }
    try:
        from areal_tpu.utils.tracing import parse_prometheus

        with urllib.request.urlopen(
            f"http://{addr}/metrics", timeout=timeout
        ) as r:
            parsed = parse_prometheus(r.read().decode(), prefix="areal_tpu_gen_")
        obs["kv_util"] = parsed.get("kv_page_utilization", 0.0)
    except Exception:
        pass  # /health sufficed; KV utilization is a soft signal
    return obs


class FleetAutoscaler:
    """FleetMonitor-driven autoscaler: a control loop that watches the
    fleet's queue backlog, KV-page utilization, and (when a telemetry
    rollup is wired — utils/telemetry.TelemetryCollector.rollup) the
    queue-wait p95, and grows or drains the serving fleet inside
    ``[min_servers, max_servers]``.

    Control discipline: every signal must hold for ``up_consecutive`` /
    ``down_consecutive`` evaluations (hysteresis — one bursty scrape
    must not flap the fleet), any action starts a ``cooldown_s`` window
    during which no further action fires (a just-launched server needs
    time to warm up and absorb load before the backlog is re-judged),
    and scale-down only ever uses the graceful path: ``drain_fn`` →
    the server finishes in-flight work → deregisters (the PR 4
    ``POST /drain`` contract), so shrinking the fleet loses zero
    rollouts by construction.

    Everything is injectable (``observe_fn``, ``rollup_fn``,
    ``time_fn``, ``launch_fn``, ``drain_fn``) so the control law is
    unit-testable without processes or sleeps; ``evaluate_once`` is the
    public single-step entry the tests (and the background loop) drive.
    """

    def __init__(
        self,
        traffic,
        launch_fn: Callable[[], None],
        drain_fn: Callable[[str], None],
        addresses_fn: Callable[[], List[str]],
        observe_fn: Optional[Callable[[str], Dict[str, float]]] = None,
        rollup_fn: Optional[Callable[[], Dict[str, float]]] = None,
        time_fn: Callable[[], float] = time.monotonic,
    ):
        self.traffic = traffic
        self._launch = launch_fn
        self._drain = drain_fn
        self._addresses = addresses_fn
        self._observe = observe_fn or scrape_server_load
        self._rollup = rollup_fn
        self._time = time_fn
        self._lock = threading.Lock()
        self._up_streak = 0
        self._down_streak = 0
        self._last_action = -float("inf")
        # the size the controller is steering toward (fleet_target_size
        # gauge); initialized lazily from the first observation
        self.target_size: Optional[int] = None
        self.ups_total = 0
        self.downs_total = 0
        self.last_decision = "init"
        # cold→serving lead accounting (r11): when a scale-up launched,
        # which addresses are observed warming, and the measured lead
        # from launch (or first-warming sight) to first ready
        # observation — THE number that says whether elasticity reacts
        # within a spike or after it
        self._pending_launch_t: Optional[float] = None
        self._warming_first: Dict[str, float] = {}
        self.last_cold_to_serving_s = 0.0
        self.cold_to_serving_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def evaluate_once(self) -> Optional[str]:
        """One control-loop step. Returns the action taken ("up",
        "down:<addr>") or None."""
        cfg = self.traffic
        now = self._time()
        obs: Dict[str, Dict[str, float]] = {}
        for addr in list(self._addresses()):
            try:
                obs[addr] = self._observe(addr)
            except Exception as e:
                logger.warning(f"autoscaler observe {addr}: {e}")
        # cold→serving lead: stamp addresses first seen warming (a
        # fresh scale-up spawn inherits the launch time, so the lead
        # covers process start + compile storm), record the lead when
        # they cross to serving
        for a, o in obs.items():
            if o.get("warming"):
                if a not in self._warming_first:
                    t0, self._pending_launch_t = (
                        self._pending_launch_t or now, None
                    )
                    self._warming_first[a] = t0
            elif a in self._warming_first:
                lead = now - self._warming_first.pop(a)
                with self._lock:
                    self.last_cold_to_serving_s = lead
                    self.cold_to_serving_total += 1
                logger.info(
                    f"autoscaler: {a} cold→serving in {lead:.1f}s"
                )
        # draining servers are capacity already leaving — they must not
        # count toward the active fleet or be drained twice; warming
        # servers are capacity still ARRIVING — they don't serve yet
        # (don't dilute queued-per-server) but a pending warmup also
        # must not trigger another launch
        warming_n = sum(1 for o in obs.values() if o.get("warming"))
        active = {
            a: o for a, o in obs.items()
            if not o.get("draining") and not o.get("warming")
        }
        n = len(active)
        with self._lock:
            if self.target_size is None:
                self.target_size = max(n, cfg.min_servers)
        if n == 0:
            self.last_decision = "no_observations"
            return None
        queued_total = sum(o.get("queued", 0.0) for o in active.values())
        kv_utils = [o.get("kv_util", 0.0) for o in active.values()]
        kv_mean = sum(kv_utils) / n
        kv_max = max(kv_utils)
        qw_p95 = 0.0
        if self._rollup is not None:
            try:
                qw_p95 = float(
                    self._rollup().get("queue_wait_p95_s", 0.0)
                )
            except Exception as e:
                logger.warning(f"autoscaler rollup failed: {e}")
        up = (
            queued_total / n > cfg.up_queued_per_server
            or kv_mean > cfg.up_kv_util
            or qw_p95 > cfg.up_queue_wait_s
        )
        down = (
            queued_total == 0
            and kv_max < cfg.down_kv_util
            and qw_p95 <= cfg.up_queue_wait_s
        )
        with self._lock:
            if now - self._last_action < cfg.cooldown_s:
                # cooldown also RESETS the hysteresis streaks: a scaling
                # action invalidates the evidence that justified it, so
                # the next decision must re-accumulate from scratch once
                # the fleet has settled
                self._up_streak = 0
                self._down_streak = 0
                self.last_decision = "cooldown"
                return None
            self._up_streak = self._up_streak + 1 if up else 0
            self._down_streak = self._down_streak + 1 if down else 0
            if up and warming_n > 0:
                # capacity is already on its way — judging the backlog
                # again before the warmup lands would double-launch
                self.last_decision = "warming_pending"
                return None
            if (
                up
                and self._up_streak >= max(1, cfg.up_consecutive)
                # warming_n is 0 here — the warming_pending guard above
                # already returned while capacity was arriving
                and n < cfg.max_servers
            ):
                self.target_size = n + 1
                self.ups_total += 1
                self._last_action = now
                self._up_streak = 0
                self.last_decision = "up"
                self._pending_launch_t = now
            elif (
                down
                and self._down_streak >= max(1, cfg.down_consecutive)
                and n > cfg.min_servers
            ):
                # graceful victim choice: least-loaded active server
                victim = min(
                    active,
                    key=lambda a: (
                        active[a].get("running", 0.0)
                        + active[a].get("queued", 0.0)
                    ),
                )
                self.target_size = n - 1
                self.downs_total += 1
                self._last_action = now
                self._down_streak = 0
                self.last_decision = f"down:{victim}"
            else:
                self.last_decision = "hold"
                return None
            decision = self.last_decision
        # actions run OUTSIDE the lock (launching/draining does I/O)
        if decision == "up":
            logger.info(
                f"autoscaler: scale up {n} -> {n + 1} "
                f"(queued={queued_total:.0f}, kv_mean={kv_mean:.2f}, "
                f"queue_wait_p95={qw_p95:.2f}s)"
            )
            self._launch()
            return "up"
        victim = decision.split(":", 1)[1]
        logger.info(
            f"autoscaler: scale down {n} -> {n - 1}, draining {victim} "
            f"(fleet quiet: queued=0, kv_max={kv_max:.2f})"
        )
        self._drain(victim)
        return decision

    # ------------------------------------------------------------------
    def metrics(self) -> Dict[str, float]:
        with self._lock:
            return {
                "fleet_target_size": float(
                    self.target_size
                    if self.target_size is not None
                    else 0
                ),
                "autoscale_up_total": float(self.ups_total),
                "autoscale_down_total": float(self.downs_total),
                # scale-up reaction time (r11): launch → first ready
                # observation of the spawned server
                "autoscale_cold_to_serving_s": float(
                    self.last_cold_to_serving_s
                ),
                "autoscale_cold_to_serving_total": float(
                    self.cold_to_serving_total
                ),
            }

    # ------------------------------------------------------------------
    def start(self) -> "FleetAutoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="fleet-autoscaler"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)

    def _loop(self) -> None:
        interval = max(0.05, self.traffic.autoscale_interval_s)
        while not self._stop.wait(interval):
            try:
                self.evaluate_once()
            except Exception as e:  # the controller must never die
                logger.error(f"autoscaler evaluation failed: {e}")
