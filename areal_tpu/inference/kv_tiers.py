"""Hierarchical KV tiers (r16): host-RAM spill store under the radix tree.

The device page pool is tier 0. When ``RadixPrefixCache`` eviction runs
with a ``KvTierManager`` attached, LRU leaves are *demoted* instead of
dropped: the page's K/V rows are gathered to host memory (one batched
device→host copy per eviction round), the device page is released, and
the radix node stays in the tree marked SPILLED (``page=None``,
``spill`` holds the host copy). A later claim descending through a
spilled node *promotes* it: a fresh device page is allocated on the
spot, the host copy is queued, and the engine flushes every queued
promotion as one batched host→device scatter BEFORE the wave that
claimed them dispatches — a spill-tier hit costs a copy, not a
re-prefill, and the restored page is bit-identical to what was demoted.

Tier 2 is optional disk: when the host tier overflows its byte budget
and ``disk_path`` is set, the LRU host entry is written to a file
instead of dropped; promotion reads it back and deletes the file. With
no disk path, overflow drops the entry outright (the node becomes a
hole — still in the tree, but a claim reaching it stops and the suffix
re-prefills).

Ownership contract (mirrors the tree's one-reference-per-node rule):

- a RESIDENT node holds exactly one PageManager reference;
- demotion moves the *content* host-side, then releases that reference
  (pages still shared by live claimants survive — their refcount stays
  positive and the host copy is a second, independent replica);
- promotion allocates a fresh page whose single reference becomes the
  tree's; until the engine flushes the pending scatter the device page
  holds garbage, so any transition that would free or snapshot it first
  CANCELS the pending promotion (``cancel_promotion`` re-files the host
  copy; the page goes back to the allocator untouched).

Cross-server shipping reuses the same canonical page form this module
defines: ``canonical_from_pool`` / ``pool_from_canonical`` convert
between a pool-layout page batch and the layout-independent
``[L, Hkv, tokens, D]`` token-major form (the r9 COW grain guarantees
token counts agree across layouts), so a prefix exported from a
token-packed pool imports cleanly into a head-merged one.
"""

import os
from collections import OrderedDict
from typing import Callable, List, Optional, Tuple

import numpy as np

from areal_tpu.utils import logging as logging_util

logger = logging_util.getLogger("kv_tiers")


def resolve_np_dtype(name: str) -> np.dtype:
    """``np.dtype`` from a dtype name, covering the ml_dtypes names
    (``bfloat16`` et al.) numpy itself does not register."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def canonical_from_pool(
    k: np.ndarray, num_kv_heads: int, head_dim: int
) -> np.ndarray:
    """Pool-layout page batch ``[L, Hp, n, rows, lane]`` → canonical
    token-major ``[L, Hkv, n*page_size, D]`` (page-order contiguous).

    Handles both pool layouts (ops/paged_attention.pool_layout): the
    token-packed lane is ``f`` consecutive tokens of one head, the
    head-merged lane is ``f'`` tokens × all heads, token-major — the
    same ordering model_runner's unpack uses."""
    nl, hp, n, rows, lane = k.shape
    merged = hp == 1 and num_kv_heads > 1
    if merged:
        f = lane // (num_kv_heads * head_dim)
        x = k.reshape(nl, n * rows * f, num_kv_heads, head_dim)
        return np.ascontiguousarray(x.transpose(0, 2, 1, 3))
    f = lane // head_dim
    return np.ascontiguousarray(k.reshape(nl, hp, n * rows * f, head_dim))


def pool_from_canonical(
    canon: np.ndarray, pool_shape: Tuple[int, ...]
) -> np.ndarray:
    """Canonical ``[L, Hkv, T, D]`` → pool-layout ``[L, Hp, n, rows,
    lane]`` for the target pool's page geometry (``pool_shape`` is the
    pool array's shape; ``n = T // page_size`` pages are produced)."""
    nl, hkv, t, d = canon.shape
    _, hp, _, rows, lane = pool_shape
    merged = hp == 1 and hkv > 1
    if merged:
        f = lane // (hkv * d)
        n = t // (rows * f)
        x = canon.transpose(0, 2, 1, 3)  # [L, T, Hkv, D] token-major
        return np.ascontiguousarray(
            x.reshape(nl, 1, n, rows, f * hkv * d)
        )
    f = lane // d
    n = t // (rows * f)
    return np.ascontiguousarray(canon.reshape(nl, hkv, n, rows, f * d))


class SpilledPage:
    """One demoted page's host copy. ``path`` set = disk-resident (k/v
    are None until loaded); ``nbytes`` is the in-memory footprint either
    way (disk files hold the same bytes)."""

    __slots__ = ("k", "v", "nbytes", "path", "shape", "dtype")

    def __init__(self, k: np.ndarray, v: np.ndarray):
        self.k = k
        self.v = v
        self.nbytes = int(k.nbytes + v.nbytes)
        self.path: Optional[str] = None
        self.shape = tuple(k.shape)
        self.dtype = k.dtype.name


class KvTierManager:
    """Host (and optional disk) spill tiers under one engine's radix
    tree. Single-threaded by contract: every method runs on the engine
    loop thread (the tree's owner); metric attributes are plain ints a
    metrics() snapshot may read racily."""

    def __init__(
        self,
        host_bytes: int,
        gather_fn: Callable[[List[int]], Tuple[np.ndarray, np.ndarray]],
        disk_path: str = "",
    ):
        self.host_capacity = int(host_bytes)
        self._gather = gather_fn
        self.disk_path = disk_path
        if disk_path:
            os.makedirs(disk_path, exist_ok=True)
        # id(node) → node, insertion order ≈ LRU of demotion
        self._host: "OrderedDict[int, object]" = OrderedDict()
        self._disk: "OrderedDict[int, object]" = OrderedDict()
        # pending promotions: id(node) → (node, device page) — queued at
        # claim time, flushed by the engine as one batched scatter
        self._pending: "OrderedDict[int, tuple]" = OrderedDict()
        self.host_bytes_used = 0
        self.disk_bytes_used = 0
        self._file_seq = 0
        self._page_nbytes = 0  # learned from the first demotion
        # lifetime counters (engine /metrics, gated on kv_spill)
        self.spilled_pages_total = 0
        self.spilled_bytes_total = 0
        self.promoted_pages_total = 0
        self.promoted_bytes_total = 0
        self.dropped_pages_total = 0
        self.dropped_bytes_total = 0
        self.disk_spilled_pages_total = 0
        self.disk_loaded_pages_total = 0
        self.claims_promoted_total = 0
        self.last_claim_promoted = 0

    # -- gauges ---------------------------------------------------------
    @property
    def host_pages(self) -> int:
        return len(self._host)

    @property
    def disk_pages(self) -> int:
        return len(self._disk)

    @property
    def pending_pages(self) -> int:
        return len(self._pending)

    # -- demotion -------------------------------------------------------
    def can_store(self) -> bool:
        """False only in the degenerate config where one page exceeds
        the whole host budget and there is no disk tier — the tree then
        falls back to drop-eviction."""
        if self.disk_path:
            return True
        if self._page_nbytes == 0:
            return self.host_capacity > 0
        return self._page_nbytes <= self.host_capacity

    def demote(self, items: List[tuple]) -> int:
        """Snapshot ``[(node, page), ...]`` host-side (one batched
        gather) and mark each node spilled. The caller releases the
        device pages afterwards — the gather is a blocking device→host
        read, so every in-flight write to those pages has landed."""
        if not items:
            return 0
        k, v = self._gather([page for _, page in items])
        for i, (node, _page) in enumerate(items):
            sp = SpilledPage(
                np.ascontiguousarray(k[:, :, i]),
                np.ascontiguousarray(v[:, :, i]),
            )
            self._page_nbytes = sp.nbytes
            node.spill = sp
            self._host[id(node)] = node
            self.host_bytes_used += sp.nbytes
            self.spilled_pages_total += 1
            self.spilled_bytes_total += sp.nbytes
        self._enforce_host_budget()
        return len(items)

    def _enforce_host_budget(self) -> None:
        while self.host_bytes_used > self.host_capacity and self._host:
            _, node = self._host.popitem(last=False)
            sp = node.spill
            self.host_bytes_used -= sp.nbytes
            if self.disk_path:
                self._to_disk(node, sp)
            else:
                node.spill = None  # hole: the claim chain ends here
                self.dropped_pages_total += 1
                self.dropped_bytes_total += sp.nbytes

    def _to_disk(self, node, sp: SpilledPage) -> None:
        self._file_seq += 1
        path = os.path.join(
            self.disk_path, f"kvpage_{self._file_seq:08d}.npz"
        )
        np.savez(
            path,
            k=sp.k.view(np.uint8).reshape(-1),
            v=sp.v.view(np.uint8).reshape(-1),
        )
        sp.path = path
        sp.k = None
        sp.v = None
        self._disk[id(node)] = node
        self.disk_bytes_used += sp.nbytes
        self.disk_spilled_pages_total += 1

    def _from_disk(self, sp: SpilledPage) -> None:
        dt = resolve_np_dtype(sp.dtype)
        with np.load(sp.path) as z:
            sp.k = z["k"].view(dt).reshape(sp.shape)
            sp.v = z["v"].view(dt).reshape(sp.shape)
        self.disk_loaded_pages_total += 1

    # -- promotion ------------------------------------------------------
    def begin_promotion(self, node, page: int) -> None:
        """Move ``node`` out of the spill store and queue its host copy
        for the engine's next batched scatter into ``page``. The node is
        resident from the caller's perspective (it set ``node.page``);
        ``node.spill`` stays set until the flush so demote-cancel and
        export can still reach the data."""
        sp = node.spill
        key = id(node)
        if key in self._disk:
            del self._disk[key]
            self.disk_bytes_used -= sp.nbytes
            self._from_disk(sp)
            if sp.path:
                try:
                    os.remove(sp.path)
                except OSError:
                    pass
                sp.path = None
        elif key in self._host:
            del self._host[key]
            self.host_bytes_used -= sp.nbytes
        self._pending[key] = (node, page)
        self.last_claim_promoted += 1

    def has_pending(self, node) -> bool:
        return id(node) in self._pending

    def cancel_promotion(self, node) -> Optional[int]:
        """Un-queue a pending promotion (the scatter never dispatched):
        the host copy goes back into the store and the device page —
        still garbage — is returned for the caller to release."""
        entry = self._pending.pop(id(node), None)
        if entry is None:
            return None
        _, page = entry
        self._host[id(node)] = node
        self.host_bytes_used += node.spill.nbytes
        self._enforce_host_budget()
        return page

    def drain_pending(self) -> List[tuple]:
        """Hand the engine every queued ``(page, SpilledPage)`` for one
        batched scatter; the nodes become plainly resident."""
        out = []
        for node, page in self._pending.values():
            sp = node.spill
            node.spill = None
            out.append((page, sp))
            self.promoted_pages_total += 1
            self.promoted_bytes_total += sp.nbytes
        self._pending.clear()
        return out

    def note_claim(self, promoted: int) -> None:
        """Per-claim accounting hook (the tree calls it as each claim
        descent finishes): claims_promoted_total counts CLAIMS that
        touched the host tier, not pages."""
        self.last_claim_promoted = promoted
        if promoted:
            self.claims_promoted_total += 1

    # -- export / removal ----------------------------------------------
    def export_data(self, node) -> Tuple[np.ndarray, np.ndarray]:
        """Read a spilled node's K/V without consuming the entry (kv
        shipping reads replicas; ownership stays put)."""
        sp = node.spill
        if sp.k is None:
            dt = resolve_np_dtype(sp.dtype)
            with np.load(sp.path) as z:
                return (
                    z["k"].view(dt).reshape(sp.shape),
                    z["v"].view(dt).reshape(sp.shape),
                )
        return sp.k, sp.v

    def forget(self, node) -> None:
        """Drop every trace of ``node`` (leaf removal / publish
        adoption): pending promotion un-queued WITHOUT re-filing (the
        caller owns the node's page and releases it), spill data and
        disk file discarded."""
        key = id(node)
        self._pending.pop(key, None)
        sp = node.spill
        if sp is None:
            return
        if key in self._host:
            del self._host[key]
            self.host_bytes_used -= sp.nbytes
        if key in self._disk:
            del self._disk[key]
            self.disk_bytes_used -= sp.nbytes
        if sp.path:
            try:
                os.remove(sp.path)
            except OSError:
                pass
        node.spill = None

    def flush(self) -> None:
        """Weight update: every tier's KV is stale. The tree walk
        releases resident pages (pending promotions included — their
        pages are ordinary tree references); this clears the host/disk
        replicas."""
        for node in list(self._host.values()):
            node.spill = None
        for node in list(self._disk.values()):
            sp = node.spill
            if sp is not None and sp.path:
                try:
                    os.remove(sp.path)
                except OSError:
                    pass
            node.spill = None
        for node, _page in self._pending.values():
            node.spill = None
        self._host.clear()
        self._disk.clear()
        self._pending.clear()
        self.host_bytes_used = 0
        self.disk_bytes_used = 0
