"""Jitted prefill / decode-step programs over the slot KV cache.

The TPU-native core of the generation engine (role of SGLang's model runner
behind the reference's HTTP API). Two compiled programs:

- ``prefill``: one request's prompt at a bucketed static length → writes
  K/V for every position into the request's cache slot, returns the logits
  of the last real token.
- ``decode_step``: ALL active slots advance one token in a single batched
  program — continuous batching is "the batch dim is the slot dim". K/V for
  the new token scatter into each slot's line; attention reads the full
  static cache line under a length mask.

Both scan over the stacked layer params (compile once per bucket, O(1) in
depth) and keep fp32 softmax/logits. Sampling (temperature / top-k / top-p /
greedy, per-slot) runs on device; stop handling is host-side.
"""

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from areal_tpu.models.config import ModelConfig
from areal_tpu.models.transformer import Params
from areal_tpu.ops.basic import apply_rope, rms_norm, rope_frequencies

NEG_INF = -2.3819763e38


def _project_qkv(cfg: ModelConfig, lp: Params, h: jnp.ndarray):
    """h [..., D] → q [..., Hq, Dh], k/v [..., Hkv, Dh] (pre-rope)."""
    q = h @ lp["wq"]
    k = h @ lp["wk"]
    v = h @ lp["wv"]
    if cfg.attention_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(h.shape[:-1] + (cfg.num_heads, cfg.head_dim))
    k = k.reshape(h.shape[:-1] + (cfg.num_kv_heads, cfg.head_dim))
    v = v.reshape(h.shape[:-1] + (cfg.num_kv_heads, cfg.head_dim))
    if cfg.use_qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    return q, k, v


def _mlp(lp: Params, h: jnp.ndarray) -> jnp.ndarray:
    return (jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])) @ lp["w_down"]


def _final_logits(params: Params, cfg: ModelConfig, x: jnp.ndarray):
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = (
        params["embedding"].T if cfg.tie_word_embeddings else params["lm_head"]
    )
    return x.astype(jnp.float32) @ head.astype(jnp.float32)


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def prefill(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [Tp] int32, padded to bucket
    true_len: jnp.ndarray,  # scalar int32
    slot: jnp.ndarray,  # scalar int32
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Run the prompt through the stack, cache K/V, return last-token logits."""
    tp = tokens.shape[0]
    pos = jnp.arange(tp, dtype=jnp.int32)
    valid = pos < true_len
    cos, sin = rope_frequencies(
        cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta
    )
    x = params["embedding"][tokens][None]  # [1, Tp, D]
    causal = (pos[None, :] <= pos[:, None]) & valid[None, :] & valid[:, None]

    def layer(carry, xs):
        x = carry
        lp, _ = xs
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q, k, v = _project_qkv(cfg, lp, h)
        q = apply_rope(q, pos[None], cos, sin)
        k = apply_rope(k, pos[None], cos, sin)
        # attention [1, Tp, Hq, Dh]
        rep = cfg.num_heads // cfg.num_kv_heads
        kk = jnp.repeat(k, rep, axis=2) if rep > 1 else k
        vv = jnp.repeat(v, rep, axis=2) if rep > 1 else v
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), kk.astype(jnp.float32)
        ) * (cfg.head_dim**-0.5)
        scores = jnp.where(causal[None, None], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, vv.astype(jnp.float32))
        attn = attn.astype(x.dtype).reshape(1, tp, cfg.q_dim)
        x = x + attn @ lp["wo"]
        h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + _mlp(lp, h2)
        return x, (k[0], v[0])  # [Tp, Hkv, Dh]

    n_layers = cfg.num_layers
    x, (ks, vs) = jax.lax.scan(
        layer, x, (params["layers"], jnp.arange(n_layers))
    )
    # write K/V into the slot: [L, Tp, Hkv, D] → cache [L, S, M, Hkv, D]
    zero = jnp.zeros((), jnp.int32)
    mask = valid[None, :, None, None]
    ks = jnp.where(mask, ks, 0.0).astype(cache["k"].dtype)
    vs = jnp.where(mask, vs, 0.0).astype(cache["v"].dtype)
    cache_k = jax.lax.dynamic_update_slice(
        cache["k"], ks[:, None], (zero, slot, zero, zero, zero)
    )
    cache_v = jax.lax.dynamic_update_slice(
        cache["v"], vs[:, None], (zero, slot, zero, zero, zero)
    )
    lens = cache["lens"].at[slot].set(true_len)
    last = x[0, jnp.maximum(true_len - 1, 0)]
    logits = _final_logits(params, cfg, last[None])[0]
    return {"k": cache_k, "v": cache_v, "lens": lens}, logits


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def decode_step(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [S] int32: current input token per slot
    active: jnp.ndarray,  # [S] bool
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """All slots advance one position; returns logits [S, V] (fp32)."""
    s, m = cache["k"].shape[1], cache["k"].shape[2]
    positions = cache["lens"]  # [S] next position per slot
    cos, sin = rope_frequencies(
        cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta
    )
    x = params["embedding"][tokens]  # [S, D]
    arange_m = jnp.arange(m)
    att_mask = arange_m[None, :] <= positions[:, None]  # [S, M] incl. new tok

    def layer(carry, xs):
        x = carry  # [S, D]
        lp, k_l, v_l = xs  # cache line [S, M, Hkv, D]
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q, k, v = _project_qkv(cfg, lp, h)  # q [S, Hq, Dh], k/v [S, Hkv, Dh]
        q = apply_rope(q[:, None], positions[:, None], cos, sin)[:, 0]
        k = apply_rope(k[:, None], positions[:, None], cos, sin)[:, 0]
        # scatter new k/v at each slot's position
        k_l = _scatter_token(k_l, k, positions)
        v_l = _scatter_token(v_l, v, positions)
        rep = cfg.num_heads // cfg.num_kv_heads
        kk = jnp.repeat(k_l, rep, axis=2) if rep > 1 else k_l
        vv = jnp.repeat(v_l, rep, axis=2) if rep > 1 else v_l
        scores = jnp.einsum(
            "shd,smhd->shm", q.astype(jnp.float32), kk.astype(jnp.float32)
        ) * (cfg.head_dim**-0.5)
        scores = jnp.where(att_mask[:, None, :], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("shm,smhd->shd", probs, vv.astype(jnp.float32))
        attn = attn.astype(x.dtype).reshape(s, cfg.q_dim)
        x = x + attn @ lp["wo"]
        h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + _mlp(lp, h2)
        return x, (k_l, v_l)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"])
    )
    logits = _final_logits(params, cfg, x)  # [S, V]
    lens = jnp.where(active, positions + 1, positions)
    return {"k": new_k, "v": new_v, "lens": lens}, logits


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def prefill_batch(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [N, Tp] int32 (N admissions, same bucket)
    true_lens: jnp.ndarray,  # [N] int32 (0 = empty row, skipped)
    slots: jnp.ndarray,  # [N] int32 (duplicate slot 0 for empty rows ok:
    # they write 0 tokens because their mask is empty)
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Prefill N requests in ONE dispatch via lax.scan over rows.

    Rows run sequentially on device (each is itself a big batched matmul
    program) but the host pays a single dispatch+fetch round-trip for the
    whole admission wave instead of one per request.
    """

    def row(cache, xs):
        toks, tl, slot = xs

        def do(c):
            return prefill(params, cfg, c, toks, tl, slot)

        def skip(c):
            # padding row of a partial admission wave: touch nothing
            return c, jnp.zeros((cfg.vocab_size,), jnp.float32)

        return jax.lax.cond(tl > 0, do, skip, cache)

    cache, logits = jax.lax.scan(row, cache, (tokens, true_lens, slots))
    return cache, logits  # logits [N, V]


@functools.partial(
    jax.jit, static_argnames=("cfg", "steps"), donate_argnames=("cache",)
)
def decode_multi(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [S] current input token per slot
    active: jnp.ndarray,  # [S] bool
    remaining: jnp.ndarray,  # [S] int32 tokens still allowed per slot
    no_stop_before: jnp.ndarray,  # [S] int32 (min_new_tokens countdown)
    stop_tokens: jnp.ndarray,  # [S, K] int32, -1 padded
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    top_k: jnp.ndarray,
    greedy: jnp.ndarray,
    steps: int,
):
    """`steps` fused decode+sample iterations in ONE dispatch, with stop
    handling on device — the host round-trip (which dominates serving
    latency, especially over a driver link) is amortized over `steps`
    tokens. A slot deactivates in-device when it emits a stop token (past
    its min_new_tokens window) or exhausts its budget; inactive slots stop
    advancing their cache line.

    Returns (cache, toks [steps,S], logps [steps,S], emitted [steps,S] bool,
    active_after [S], remaining_after, no_stop_after).
    """

    def step(carry, step_key):
        cache, tokens, active, remaining, no_stop = carry
        cache, toks, logps = decode_and_sample(
            params, cfg, cache, tokens, active, step_key,
            temperature, top_p, top_k, greedy,
        )
        emitted = active
        # a stop token may end the slot once it would have emitted
        # >= min_new_tokens INCLUDING this one (no_stop holds min - emitted)
        hit_stop = jnp.any(
            toks[:, None] == stop_tokens, axis=1
        ) & (no_stop <= 1)
        remaining = jnp.where(active, remaining - 1, remaining)
        no_stop = jnp.where(active, no_stop - 1, no_stop)
        active = active & ~hit_stop & (remaining > 0)
        tokens = toks
        return (cache, tokens, active, remaining, no_stop), (
            toks, logps, emitted,
        )

    keys = jax.random.split(key, steps)
    (cache, tokens, active, remaining, no_stop), (toks, logps, emitted) = (
        jax.lax.scan(
            step, (cache, tokens, active, remaining, no_stop_before), keys
        )
    )
    return cache, toks, logps, emitted, active, remaining, no_stop


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def decode_and_sample(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [S]
    active: jnp.ndarray,  # [S] bool
    key: jax.Array,
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S]
    greedy: jnp.ndarray,  # [S] bool
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Fused decode step + sampling: ONE dispatch and one host fetch per
    generation step (the per-step host round-trip is the latency floor of the
    serving loop, so everything between two steps stays on device)."""
    cache, logits = decode_step(params, cfg, cache, tokens, active)
    toks, logps = sample_tokens(
        logits, key, temperature, top_p, top_k, greedy
    )
    return cache, toks, logps


def _scatter_token(
    cache_line: jnp.ndarray,  # [S, M, Hkv, D]
    new: jnp.ndarray,  # [S, Hkv, D]
    positions: jnp.ndarray,  # [S]
) -> jnp.ndarray:
    new = new.astype(cache_line.dtype)

    def one(line, tok, pos):
        return jax.lax.dynamic_update_slice(
            line, tok[None], (pos, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
        )

    return jax.vmap(one)(cache_line, new, positions)


# ---------------------------------------------------------------------------
# Sampling
# ---------------------------------------------------------------------------
@jax.jit
def sample_tokens(
    logits: jnp.ndarray,  # [S, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S] int32 (0 = disabled)
    greedy: jnp.ndarray,  # [S] bool
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-slot sampling; returns (tokens [S], logprobs [S]).

    The returned logprob is under the temperature-scaled (untruncated)
    distribution — the behavior-policy logprob the trainer consumes
    (reference ModelResponse.output_logprobs semantics). Greedy slots are
    the exception: they pick argmax over the raw logits, so their logprob
    is reported under the *unscaled* distribution (temperature never enters
    their behavior policy).
    """
    s, v = logits.shape
    temp = jnp.maximum(temperature, 1e-5)[:, None]
    scaled = logits / temp
    logp_full = jax.nn.log_softmax(scaled, axis=-1)

    # top-k / top-p truncation for the *sampling* distribution
    sort_idx = jnp.argsort(-scaled, axis=-1)
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumprobs = jnp.cumsum(sorted_probs, axis=-1)
    rank = jnp.arange(v)[None, :]
    keep = jnp.ones((s, v), bool)
    keep &= jnp.where(top_k[:, None] > 0, rank < top_k[:, None], True)
    # keep tokens while cumulative prob (exclusive) < top_p
    keep &= (cumprev := cumprobs - sorted_probs) < top_p[:, None]
    keep = keep.at[:, 0].set(True)  # always keep the argmax token
    trunc_sorted = jnp.where(keep, sorted_logits, NEG_INF)
    trunc = jnp.full_like(scaled, NEG_INF).at[
        jnp.arange(s)[:, None], sort_idx
    ].set(trunc_sorted)
    sampled = jax.random.categorical(key, trunc, axis=-1)
    argmax = jnp.argmax(logits, axis=-1)
    tokens = jnp.where(greedy, argmax, sampled).astype(jnp.int32)
    # Greedy slots ignore temperature when picking the token, so report the
    # logprob under the *unscaled* distribution — mixing argmax(logits) with
    # the temperature-scaled softmax would hand the trainer importance
    # ratios from a distribution that was never sampled.
    lp_sampled = jnp.take_along_axis(
        logp_full, tokens[:, None], axis=-1
    ).squeeze(-1)
    lp_greedy = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), tokens[:, None], axis=-1
    ).squeeze(-1)
    logprobs = jnp.where(greedy, lp_greedy, lp_sampled)
    return tokens, logprobs
