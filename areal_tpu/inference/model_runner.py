"""Jitted prefill / decode programs over the paged KV-block pool.

The TPU-native core of the generation engine (role of SGLang's model runner
behind the reference's HTTP API, driven at areal/engine/sglang_remote.py).
Compiled programs over the page pool (inference/cache.py layout):

- ``prefill_batch``: N prompt suffixes as ONE batched [N, Tp] forward.
  Each row resumes from ``offset`` tokens already cached in its pages
  (prefix reuse — the radix-cache analog): attention = gathered page
  window [0, offset) ++ in-flight suffix (causal), and the suffix K/V for
  all layers lands in the pool with one donated scatter after the layer
  scan — the pool itself never rides the scan (a mutated multi-GB scan
  carry costs a full copy per step on TPU; measured, not folklore).
- ``decode_multi``: `steps` fused decode+sample iterations in ONE dispatch
  with device-side stop handling. The pool is READ-ONLY inside the step
  loop; new tokens' K/V accumulate in a small [L, S, T] chunk buffer that
  the paged-attention kernel folds into the same online softmax, and one
  bulk scatter merges the chunk into the pool at the end.
- ``decode_step``: single step without sampling (tests / TP fallback).
- ``copy_pages``: page-granular pool copy (GRPO sibling partial-tail pages;
  full prompt pages are *shared* host-side, no copy).

Attention backend is static per call: "kernel" (Pallas manual-DMA flash,
TPU) or "jnp" (gather fallback — CPU tests and tensor-parallel serving).
Sampling (temperature / top-k / top-p / greedy, per slot) runs on device
with a static ``topk_bound``; fp32 softmax/logits throughout.
"""

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from areal_tpu.models.config import ModelConfig
from areal_tpu.models.transformer import Params
from areal_tpu.ops.basic import (
    apply_rope,
    hidden_act_fn,
    rms_norm,
    rope_frequencies,
)
from areal_tpu.ops.paged_attention import (
    layout_from_pool,
    paged_decode_attention,
    paged_decode_attention_jnp,
    unpacked_view,
)

NEG_INF = -2.3819763e38


def _project_qkv(cfg: ModelConfig, lp: Params, h: jnp.ndarray):
    """h [..., D] → q [..., Hq, Dh], k/v [..., Hkv, Dh] (pre-rope)."""
    q = h @ lp["wq"]
    k = h @ lp["wk"]
    v = h @ lp["wv"]
    if cfg.attention_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(h.shape[:-1] + (cfg.num_heads, cfg.head_dim))
    k = k.reshape(h.shape[:-1] + (cfg.num_kv_heads, cfg.head_dim))
    v = v.reshape(h.shape[:-1] + (cfg.num_kv_heads, cfg.head_dim))
    if cfg.use_qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    return q, k, v


def _mlp(
    cfg: ModelConfig,
    lp: Params,
    h: jnp.ndarray,
    valid: Optional[jnp.ndarray] = None,  # [...] matching h[..., 0]
) -> jnp.ndarray:
    if cfg.is_moe:
        from areal_tpu.ops.moe import (
            moe_ffn_from_params,
            shared_expert_from_params,
        )

        flat = h.reshape(1, -1, h.shape[-1])
        # padding / inactive-slot tokens must not consume expert capacity
        vflat = None if valid is None else valid.reshape(1, -1)
        out, _ = moe_ffn_from_params(cfg, lp, flat, valid=vflat)
        out = out.reshape(h.shape)
        if cfg.shared_expert_size:
            out = out + shared_expert_from_params(cfg, lp, h)
        return out
    act = hidden_act_fn(cfg.hidden_act)
    return (act(h @ lp["w_gate"]) * (h @ lp["w_up"])) @ lp["w_down"]


def _layer_pre(cfg: ModelConfig, lp: Params, x: jnp.ndarray):
    """Input norm + QKV projection — the front half of the per-layer
    sandwich every forward path (prefill, decode, spec-verify) shares.
    One copy with `_layer_post` so an architecture change (a new norm
    variant, QK-norm tweak, ...) cannot silently drift between the
    three forwards and break their bit-exactness contract; everything
    between the halves (rope positions, KV staging, the attention core)
    is genuinely path-specific."""
    h = rms_norm(
        x, lp["input_norm"], cfg.rms_norm_eps,
        add_unit_offset=cfg.norm_add_unit_offset,
    )
    return _project_qkv(cfg, lp, h)


def _layer_post(
    cfg: ModelConfig,
    lp: Params,
    x: jnp.ndarray,
    attn: jnp.ndarray,  # [..., q_dim] already in x.dtype
    valid: Optional[jnp.ndarray],
) -> jnp.ndarray:
    """Output-projection residual + post-attn norm + MLP residual — the
    back half of the shared per-layer sandwich (see `_layer_pre`)."""
    x = x + attn @ lp["wo"]
    h2 = rms_norm(
        x, lp["post_attn_norm"], cfg.rms_norm_eps,
        add_unit_offset=cfg.norm_add_unit_offset,
    )
    return x + _mlp(cfg, lp, h2, valid=valid)


def _final_logits(params: Params, cfg: ModelConfig, x: jnp.ndarray):
    x = rms_norm(
        x, params["final_norm"], cfg.rms_norm_eps,
        add_unit_offset=cfg.norm_add_unit_offset,
    )
    head = (
        params["embedding"].T if cfg.tie_word_embeddings else params["lm_head"]
    )
    return x.astype(jnp.float32) @ head.astype(jnp.float32)


def _row_flat(
    tables: jnp.ndarray,  # [N, PPS] int32 logical page ids
    row_positions: jnp.ndarray,  # [N, R] int32 TOKEN position of row start
    page_size: int,
    pack: int,
    num_pages: int,
    valid: jnp.ndarray,  # [N, R] bool
) -> jnp.ndarray:
    """Pool-row index for row-granular access. The pool's unit of access
    is one 128-lane row = ``pack`` consecutive tokens (any view with a
    trailing dim < 128 forces a full relaid copy of the pool on TPU —
    measured as a 2x HBM blowup — so every jnp read/write goes through
    [*, pack*D] rows). Invalid rows map past the pool (scatter drop)."""
    prow = page_size // pack
    page = jnp.take_along_axis(
        tables,
        jnp.clip(row_positions // page_size, 0, tables.shape[1] - 1),
        axis=1,
    )
    flat = page * prow + (row_positions % page_size) // pack
    return jnp.where(valid, flat, num_pages * prow)


def _rows_view(pool: jnp.ndarray) -> jnp.ndarray:
    """[L, Hkv, NP, BS//f, f*D] → [L, Hkv, NP*(BS//f), f*D] (free)."""
    nl, hkv, np_, prow, fd = pool.shape
    return pool.reshape(nl, hkv, np_ * prow, fd)


def init_last_rows(
    num_layers: int, num_slots: int, num_kv_heads: int, fd: int, dtype
) -> Dict[str, jnp.ndarray]:
    """Per-slot copy of the last (possibly partial) pool row each sequence
    wrote. Merges consult it instead of READING the pool: on this backend
    any computation that both reads and writes a buffer pays a full copy
    of it, and gathers/scatters with index arrays serialize per index —
    write-only DUS chains are the only fast pool mutation."""
    shape = (num_layers, num_slots, num_kv_heads, fd)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


@functools.partial(
    jax.jit, static_argnames=("num_pages", "prow", "pack", "merge")
)
def assemble_rows(
    tables: jnp.ndarray,  # [N, PPS]
    pos0: jnp.ndarray,  # [N] absolute start position of kv[…, 0]
    counts: jnp.ndarray,  # [N] valid tokens per row
    kbuf: jnp.ndarray,  # [L, N, T, Hkv, D] token-order new K
    vbuf: jnp.ndarray,
    last_rows: Dict[str, jnp.ndarray],  # [L, S?, Hkv, FD] (rows N used)
    slot_ids: jnp.ndarray,  # [N] engine slot of each row (last_rows index)
    num_pages: int,
    prow: int,
    pack: int,
    merge: bool = False,
):
    """Pack token-order K/V into full 128-lane pool rows.

    ``merge``: the head-merged pool view — K/V arrive [L, N, T, Hkv, D]
    and are reshaped to [L, N, T, 1, Hkv*D] INSIDE this jit (token-major,
    a free view; doing it eagerly in merge_tokens cost one stray
    eager-op compile per shape that dodged the dispatch-scope compile
    attribution).

    Returns (dest [N*NR] flat row ids with row 0 of the pool as the drop
    target for invalid rows, kvals/vvals [N*NR, L, Hkv, FD], new
    last_rows {k,v} [L, N, Hkv, FD]). Pure compute — the pool itself is
    neither read nor written here (see init_last_rows)."""
    if merge:
        nl_, n_, t_, hkv_, d_ = kbuf.shape
        kbuf = kbuf.reshape(nl_, n_, t_, 1, hkv_ * d_)
        vbuf = vbuf.reshape(nl_, n_, t_, 1, hkv_ * d_)
    nl, n, t, hkv, d = kbuf.shape
    f = pack
    fd = f * d
    bs = prow * f
    kv_dtype = kbuf.dtype
    nr = t // f + 2  # worst-case rows touched (alignment + remainder)
    a = pos0 % f  # [N] first-row misalignment
    j = jnp.arange(nr, dtype=jnp.int32)[None, :]  # [1, NR]

    def shifted_stride(buf, start: int):
        """buf[:, :, start::f] padded/truncated to NR rows along axis 2.
        ``start`` may be negative (leading zero row). Pure strided slices
        + pads — a generic gather here was measured ~150x slower."""
        if start < 0:
            sl = buf[:, :, f + start :: f]
            sl = jnp.pad(sl, ((0, 0), (0, 0), (1, 0), (0, 0), (0, 0)))
        else:
            sl = buf[:, :, start :: f]
        rows = sl.shape[2]
        if rows < nr:
            sl = jnp.pad(
                sl, ((0, 0), (0, 0), (0, nr - rows), (0, 0), (0, 0))
            )
        return sl[:, :, :nr]

    def assemble(buf, last):  # buf [L,N,T,Hkv,D], last [L,N,Hkv,FD]
        halves = []
        for g in range(f):
            tg = j * f + g - a[:, None]  # [N, NR]
            valid = (tg >= 0) & (tg < counts[:, None])
            gathered = shifted_stride(buf, g)  # a == 0
            for aa in range(1, f):
                cand = shifted_stride(buf, g - aa)
                pick = (a == aa)[None, :, None, None, None]
                gathered = jnp.where(pick, cand, gathered)
            # first-row halves before pos0 come from the slot's remembered
            # last partial row (NOT a pool read)
            keep_old = (j == 0) & (g < a[:, None]) & (counts[:, None] > 0)
            old = last[:, :, None, :, g * d : (g + 1) * d]  # [L,N,1,Hkv,D]
            val = jnp.where(
                valid[None, :, :, None, None],
                gathered,
                jnp.where(
                    keep_old[None, :, :, None, None],
                    jnp.broadcast_to(old, gathered.shape),
                    jnp.zeros((), kv_dtype),
                ),
            )
            halves.append(val.astype(kv_dtype))
        # [L, N, NR, Hkv, f*D] — lane order g*D:(g+1)*D = token row*f+g
        return jnp.concatenate(halves, axis=-1)

    last_k = jnp.take(last_rows["k"], slot_ids, axis=1)  # [L, N, Hkv, FD]
    last_v = jnp.take(last_rows["v"], slot_ids, axis=1)
    kvals = assemble(kbuf, last_k)
    vvals = assemble(vbuf, last_v)
    row_pos = (pos0 - a)[:, None] + j * f  # [N, NR]
    any_valid = (
        ((j + 1) * f - a[:, None] > 0)
        & (j * f - a[:, None] < counts[:, None])
        & (counts[:, None] > 0)
    )
    dest = _row_flat(tables, row_pos, bs, f, num_pages, any_valid)
    # invalid rows are redirected to row 0 — the engine RESERVES page 0 as
    # a trash page (DUS clamps out-of-range starts, which would corrupt a
    # real page)
    dest = jnp.where(any_valid, dest, 0).reshape(-1)
    kw = kvals.transpose(1, 2, 0, 3, 4).reshape(n * nr, nl, hkv, fd)
    vw = vvals.transpose(1, 2, 0, 3, 4).reshape(n * nr, nl, hkv, fd)
    # new last-row per sequence: the row containing token pos0+counts-1
    # (selected by one-hot reduce — index gathers serialize on TPU)
    last_j = jnp.clip((a + counts - 1) // f, 0, nr - 1)  # [N]
    onehot = (j == last_j[:, None]).astype(kvals.dtype)  # [N, NR]
    sel_k = jnp.einsum("lnrhf,nr->lnhf", kvals, onehot)
    sel_v = jnp.einsum("lnrhf,nr->lnhf", vvals, onehot)
    wrote = (counts > 0)[None, :, None, None]
    new_last = {
        "k": jnp.where(wrote, sel_k, last_k).astype(kv_dtype),
        "v": jnp.where(wrote, sel_v, last_v).astype(kv_dtype),
    }
    return dest, kw, vw, new_last


@functools.partial(jax.jit, donate_argnames=("cache",))
def write_rows(
    cache: Dict[str, jnp.ndarray],
    dest: jnp.ndarray,  # [M] flat row ids (0 = engine trash page)
    kvals: jnp.ndarray,  # [M, L, Hkv, FD]
    vvals: jnp.ndarray,
) -> Dict[str, jnp.ndarray]:
    """WRITE-ONLY pool update: a scan of per-row dynamic_update_slice ops
    on the donated pool — the only pool-mutation shape this backend runs
    in place (index-array scatters serialize per index; any read of the
    pool in the same dispatch forces a full copy)."""
    krows = _rows_view(cache["k"])
    vrows = _rows_view(cache["v"])

    def body(carry, xs):
        kr, vr = carry
        d_, kv_, vv_ = xs  # kv_ [L, Hkv, FD]
        kr = jax.lax.dynamic_update_slice(
            kr, kv_[:, :, None, :], (0, 0, d_, 0)
        )
        vr = jax.lax.dynamic_update_slice(
            vr, vv_[:, :, None, :], (0, 0, d_, 0)
        )
        return (kr, vr), None

    (krows, vrows), _ = jax.lax.scan(body, (krows, vrows), (dest, kvals, vvals))
    return {
        "k": krows.reshape(cache["k"].shape),
        "v": vrows.reshape(cache["v"].shape),
    }


def merge_tokens(
    cache: Dict[str, jnp.ndarray],
    tables: jnp.ndarray,
    pos0: jnp.ndarray,
    counts: jnp.ndarray,
    kbuf: jnp.ndarray,  # [L, N, T, Hkv, D]
    vbuf: jnp.ndarray,
    last_rows: Optional[Dict[str, jnp.ndarray]] = None,
    slot_ids: Optional[jnp.ndarray] = None,
):
    """Two-dispatch merge: assemble rows (pure), then write-only DUS scan.
    Returns (cache, new_last_rows [L, N, Hkv_pool, LANE]).

    A head-merged pool (hkv dim 1, all heads per 128-lane row) reuses the
    same assembly machinery on kbuf viewed as [L, N, T, 1, Hkv*D] — a
    free reshape, since [T, Hkv, D] is token-major — with the pack factor
    counted in tokens-per-row."""
    nl, n, t, hkv, d = kbuf.shape
    _, hkv_pool, num_pages, prow, fd = cache["k"].shape
    merged, f = layout_from_pool(cache["k"].shape, hkv, d)
    if merged:
        hkv = 1
    if last_rows is None:
        last_rows = init_last_rows(nl, n, hkv, fd, kbuf.dtype)
    if slot_ids is None:
        slot_ids = jnp.arange(n, dtype=jnp.int32)
    # the merged-layout buffer reshape happens INSIDE assemble_rows
    # (static `merge`): an eager reshape here would compile one stray
    # program per buffer shape outside the dispatch-scope attribution
    dest, kw, vw, new_last = assemble_rows(
        tables, pos0, counts, kbuf, vbuf, last_rows, slot_ids,
        num_pages=num_pages, prow=prow, pack=f, merge=merged,
    )
    cache = write_rows(cache, dest, kw, vw)
    return cache, new_last


# ---------------------------------------------------------------------------
# Prefill (batched, prefix-aware)
# ---------------------------------------------------------------------------
@functools.partial(
    jax.jit,
    static_argnames=("cfg", "prefix_bound"),
)
def prefill_forward(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [N, Tp] int32 suffix tokens, padded to bucket
    offsets: jnp.ndarray,  # [N] int32 tokens already cached (prefix reuse)
    true_lens: jnp.ndarray,  # [N] int32 suffix lengths (0 = padding row)
    tables: jnp.ndarray,  # [N, PPS] logical pages covering offset+Tp
    prefix_bound: int = 0,  # static: gathered window >= max(offsets), 0 = none
    embeds: Optional[jnp.ndarray] = None,  # [N, Tp, D] input embeddings
    pos3: Optional[jnp.ndarray] = None,  # [N, Tp, 3] mrope positions
):
    """One batched READ-ONLY forward over N prompt suffixes; returns
    (logits [N, V] fp32, k_sfx, v_sfx [L, N, Tp, Hkv, D]) — the caller
    merges the suffix K/V with the separate write-only dispatch
    (merge_tokens), keeping this dispatch free of pool writes (a
    read+write dispatch pays a full pool copy on this backend).

    Host contract: tables cover ceil((offset+Tp)/BS) pages per real row;
    ``prefix_bound`` >= every row's offset; offsets are POOL-ROW-aligned
    (page-aligned for full-page prefix claims; mid-page for the radix
    cache's COW claims — the per-token window masks below are exact for
    any offset, and row alignment is what the MERGE needs, since
    assemble_rows consults last_rows only for mid-row starts).

    This one entry point is ALSO the chunk-resume prefill (r15 chunked
    prefill): a continuation chunk is dispatched with ``offsets`` = the
    committed page-aligned prefix the engine re-claimed from the cache
    and ``true_lens`` = the chunk width — identical in shape and
    numerics to a radix-claim resume, which is what makes chunked
    greedy streams bit-identical to unchunked ones. Chunk-capped rows
    ride a wave slotless (slot id = max_num_seqs): their last_rows
    gather clips harmlessly because page-aligned ends mean the first
    row of the NEXT chunk is never mid-row.
    """
    n, tp = tokens.shape
    d = cfg.head_dim
    nl, hkv_pool, num_pages, prow, fd = cache["k"].shape
    hkv = cfg.num_kv_heads
    merged, f = layout_from_pool(cache["k"].shape, hkv, d)
    page_size = prow * f
    mb0 = prefix_bound
    sidx = jnp.arange(tp, dtype=jnp.int32)[None, :]
    pos = offsets[:, None] + sidx  # [N, Tp] absolute positions
    valid_q = sidx < true_lens[:, None]
    cos, sin = rope_frequencies(
        cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta
    )
    if embeds is not None:
        # VLM path: image-token embeddings were spliced at admission
        # (mm_prompt_embeds applies any embedding scaling itself; scaling
        # here would double-scale text rows and wrongly scale vision rows)
        x = embeds.astype(params["embedding"].dtype)
    else:
        x = params["embedding"][tokens]  # [N, Tp, D]
        if cfg.scale_embeddings:  # gemma: sqrt(d)-scaled embeddings
            x = x * jnp.asarray(cfg.hidden_size**0.5, x.dtype)

    def _rope(t):  # [N, Tp, H, D]
        if pos3 is not None and cfg.mrope_sections:
            from areal_tpu.ops.basic import apply_mrope

            return apply_mrope(t, pos3, cos, sin, cfg.mrope_sections)
        return apply_rope(t, pos, cos, sin)

    scale = cfg.head_dim**-0.5
    g, rep = cfg.num_kv_heads, cfg.num_heads // cfg.num_kv_heads

    krows_all = _rows_view(cache["k"])  # [L, Hkv_pool, NP*prow, FD]
    vrows_all = _rows_view(cache["v"])

    if mb0 > 0:
        npg = -(-mb0 // page_size)  # window pages (covers every offset)
        wr = npg * prow  # window rows
        # page-run gather: one dynamic_slice per (row, page) — index-array
        # gathers serialize per index on TPU, DS runs at copy speed
        page_starts = (
            jnp.clip(tables[:, :npg], 0, num_pages - 1) * prow
        ).reshape(-1)  # [N*npg]

        def fetch(carry, st):
            win_k = jax.lax.dynamic_slice(
                krows_all, (0, 0, st, 0), (nl, hkv_pool, prow, fd)
            )
            win_v = jax.lax.dynamic_slice(
                vrows_all, (0, 0, st, 0), (nl, hkv_pool, prow, fd)
            )
            return carry, (win_k, win_v)

        _, (wk_pages, wv_pages) = jax.lax.scan(fetch, 0, page_starts)
        # [N*npg, L, Hkv_pool, prow, FD] → [L, Hkv_pool, N, WR, FD]
        def arrange(w):
            w = w.reshape(n, npg, nl, hkv_pool, prow, fd)
            return w.transpose(2, 3, 0, 1, 4, 5).reshape(
                nl, hkv_pool, n, wr, fd
            )

        win_k_all = arrange(wk_pages)
        win_v_all = arrange(wv_pages)
        fw = f  # lane halves per window row (token stride)
        if merged:
            # unpack the merged rows into per-head single-token rows ONCE
            # (prefix windows are an admission-time path, not decode-hot):
            # [L, 1, N, WR, tpr*Hkv*D] -> [L, Hkv, N, WR*tpr, D]
            def unmerge(w):
                y = w.reshape(nl, n, wr, f, hkv, d)
                return y.transpose(0, 4, 1, 2, 3, 5).reshape(
                    nl, hkv, n, wr * f, d
                )

            win_k_all = unmerge(win_k_all)
            win_v_all = unmerge(win_v_all)
            wr = wr * f
            fw = 1
        rpos = jnp.arange(wr, dtype=jnp.int32)[None, :] * fw  # [1, WR]
        # per-half key masks: token at (row r, half h) has position r*fw+h
        half_masks = [
            (rpos + h < offsets[:, None])[:, None, None, None]  # [N,1,1,1,WR]
            for h in range(fw)
        ]

    # causal within the in-flight suffix
    suffix_mask = (sidx[:, :, None] >= sidx[:, None, :]) & valid_q[:, None, :]

    def layer(carry, xs):
        x = carry
        lp, li = xs
        q, k, v = _layer_pre(cfg, lp, x)  # [N, Tp, H*, Dh]
        q = _rope(q)
        k = _rope(k)
        kz = jnp.where(valid_q[..., None, None], k, 0)
        vz = jnp.where(valid_q[..., None, None], v, 0)
        qg = q.reshape(n, tp, g, rep, cfg.head_dim)
        # suffix-vs-suffix scores (causal)
        sc_sfx = (
            jnp.einsum(
                "nqgrd,nkgd->ngrqk", qg, kz,
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        sc_sfx = jnp.where(suffix_mask[:, None, None], sc_sfx, NEG_INF)
        if mb0 > 0:
            # pre-gathered page windows (full 128-lane rows), lane-half
            # slices — key order is [half0 rows..., half1 rows...,
            # suffix], which softmax doesn't care about
            win_k = jax.lax.dynamic_index_in_dim(
                win_k_all, li, 0, keepdims=False
            )  # [Hkv, N, WR, FD]
            win_v = jax.lax.dynamic_index_in_dim(
                win_v_all, li, 0, keepdims=False
            )
            scs = []
            vhs = []
            for hh in range(fw):
                wk = win_k[..., hh * d : (hh + 1) * d]  # [Hkv, N, WR, D]
                vhs.append(win_v[..., hh * d : (hh + 1) * d])
                sc_h = (
                    jnp.einsum(
                        "nqgrd,gnkd->ngrqk", qg, wk,
                        preferred_element_type=jnp.float32,
                    )
                    * scale
                )
                scs.append(jnp.where(half_masks[hh], sc_h, NEG_INF))
            # segment layout [half0 .. halfN, suffix] — the probs slicing
            # below depends on this order
            sc = jnp.concatenate(scs + [sc_sfx], axis=-1)
        else:
            sc = sc_sfx
        probs = jax.nn.softmax(sc, axis=-1)
        if mb0 > 0:
            wr_n = vhs[0].shape[2]
            attn = jnp.einsum(
                "ngrqk,nkgd->nqgrd",
                probs[..., fw * wr_n :].astype(vz.dtype), vz,
                preferred_element_type=jnp.float32,
            )
            for hh in range(fw):
                attn = attn + jnp.einsum(
                    "ngrqk,gnkd->nqgrd",
                    probs[..., hh * wr_n : (hh + 1) * wr_n].astype(
                        vhs[hh].dtype
                    ),
                    vhs[hh],
                    preferred_element_type=jnp.float32,
                )
        else:
            attn = jnp.einsum(
                "ngrqk,nkgd->nqgrd", probs.astype(vz.dtype), vz,
                preferred_element_type=jnp.float32,
            )
        attn = attn.astype(x.dtype).reshape(n, tp, cfg.q_dim)
        x = _layer_post(cfg, lp, x, attn, valid_q)
        kv_dtype = cache["k"].dtype
        return x, (kz.astype(kv_dtype), vz.astype(kv_dtype))

    x, (k_sfx, v_sfx) = jax.lax.scan(
        layer, x, (params["layers"], jnp.arange(nl, dtype=jnp.int32))
    )
    last = x[jnp.arange(n), jnp.maximum(true_lens - 1, 0)]  # [N, D]
    logits = _final_logits(params, cfg, last)  # [N, V] fp32
    return logits, k_sfx, v_sfx


def prefill_batch(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,
    offsets: jnp.ndarray,
    true_lens: jnp.ndarray,
    tables: jnp.ndarray,
    prefix_bound: int = 0,
    last_rows: Optional[Dict[str, jnp.ndarray]] = None,
    slot_ids: Optional[jnp.ndarray] = None,
    embeds: Optional[jnp.ndarray] = None,
    pos3: Optional[jnp.ndarray] = None,
):
    """Read-only forward + write-only merge (two dispatches).
    Returns (cache, logits, new_last_rows [L, N, Hkv, FD])."""
    logits, k_sfx, v_sfx = prefill_forward(
        params, cfg, cache, tokens, offsets, true_lens, tables,
        prefix_bound=prefix_bound, embeds=embeds, pos3=pos3,
    )
    cache, new_last = merge_tokens(
        cache, tables, offsets, true_lens, k_sfx, v_sfx,
        last_rows=last_rows, slot_ids=slot_ids,
    )
    return cache, logits, new_last


@functools.partial(jax.jit, static_argnames=("cfg",))
def mm_prompt_embeds(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [N, Tp] int32 prompt tokens (padded)
    pixels: jnp.ndarray,  # [N, P, patch_dim]
    vis_seg: jnp.ndarray,  # [N, P]
    vis_pos_h: jnp.ndarray,  # [N, P]
    vis_pos_w: jnp.ndarray,  # [N, P]
    ordinals: jnp.ndarray,  # [N, Tp] merged-patch ordinal; -1 = text
) -> jnp.ndarray:
    """Prompt embeddings with vision embeds spliced at image-pad tokens —
    computed ONCE at admission; prefill consumes the result instead of a
    token lookup (the serving analog of models/forward.packed_forward's
    training-side splice)."""
    from areal_tpu.models import vision as vision_lib

    x = params["embedding"][tokens]
    if cfg.scale_embeddings:
        x = x * jnp.asarray(cfg.hidden_size**0.5, x.dtype)
    emb = vision_lib.vision_apply(
        params["vision"], cfg.vision, pixels, vis_seg, vis_pos_h,
        vis_pos_w, remat=False,
    )  # [N, Pm, D]
    gathered = jnp.take_along_axis(
        emb, jnp.clip(ordinals, 0)[..., None].astype(jnp.int32), axis=1
    ).astype(x.dtype)
    return jnp.where(ordinals[..., None] >= 0, gathered, x)


@functools.partial(jax.jit, donate_argnames=("cache",))
def copy_pages(
    cache: Dict[str, jnp.ndarray],
    src: jnp.ndarray,  # [P] int32 source page per copy
    dst: jnp.ndarray,  # [P] int32 destination (>= num_pages rows dropped)
) -> Dict[str, jnp.ndarray]:
    """Duplicate pool pages src→dst (GRPO sibling partial-tail pages after
    one shared prompt prefill; full pages are shared host-side instead).
    Padding rows use dst >= num_pages."""
    k = cache["k"].at[:, :, dst].set(cache["k"][:, :, src], mode="drop")
    v = cache["v"].at[:, :, dst].set(cache["v"][:, :, src], mode="drop")
    return {"k": k, "v": v}


@jax.jit
def gather_pages(
    cache: Dict[str, jnp.ndarray],
    pages: jnp.ndarray,  # [P] int32 (padding rows use page 0: trash)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Read pool pages for a host-side demotion snapshot (r16 KV spill
    tier) or a kv-shipping export: [L, Hp, P, rows, lane] per tensor.
    Non-donating — the pool stays live; the caller's device_get blocks
    until every in-flight write to those pages has landed."""
    return cache["k"][:, :, pages], cache["v"][:, :, pages]


@functools.partial(jax.jit, donate_argnames=("cache",))
def scatter_pages(
    cache: Dict[str, jnp.ndarray],
    dst: jnp.ndarray,  # [P] int32 (>= num_pages rows dropped)
    k_new: jnp.ndarray,  # [L, Hp, P, rows, lane]
    v_new: jnp.ndarray,
) -> Dict[str, jnp.ndarray]:
    """Write host-restored pages back into the pool (spill-tier
    promotion flush / kv-shipping import). Padding rows use
    dst >= num_pages, same drop contract as copy_pages."""
    k = cache["k"].at[:, :, dst].set(k_new, mode="drop")
    v = cache["v"].at[:, :, dst].set(v_new, mode="drop")
    return {"k": k, "v": v}


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------
def _gather_recent_kv(
    cache: Dict[str, jnp.ndarray],
    tables: jnp.ndarray,  # [S, PPS]
    pos0: jnp.ndarray,  # [S] cached tokens per slot
    rl: jnp.ndarray,  # [S] replay length (tokens since the boundary)
    replay: int,  # static buffer width (max replay = chunk quantum - 1)
    num_kv_heads: int,
    head_dim: int,
):
    """K/V of positions [pos0-rl, pos0) per slot, gathered from the POOL
    into chunk-buffer form ([L, S, replay, Hkv, D] ×2; entry j holds
    position pos0-rl+j, valid for j < rl, zeros elsewhere).

    This is the canonical-alignment replay prefix for speculative
    serving: a dispatch that starts mid-chunk (a partial draft accept
    left the slot between ``decode_chunk`` boundaries) re-presents the
    boundary-to-now K/V as in-window chunk entries, so every position's
    attention sees EXACTLY the pool-window/chunk-buffer split a
    non-speculative run would give it — the split changes softmax
    summation order, so matching it is what keeps greedy streams
    bit-identical. Pool bytes are the very bytes the sequential path
    had in its buffer (merges are exact copies), so no recompute and no
    numerics bet. Spec-off engines never call this."""
    nl = cache["k"].shape[0]
    num_pages = cache["k"].shape[2]
    merged, tpr = layout_from_pool(
        cache["k"].shape, num_kv_heads, head_dim
    )
    bs = cache["k"].shape[3] * tpr  # page size in tokens
    j = jnp.arange(replay, dtype=jnp.int32)[None, :]
    valid = j < rl[:, None]  # [S, R]
    positions = jnp.where(valid, (pos0 - rl)[:, None] + j, 0)
    page = jnp.take_along_axis(
        tables, jnp.clip(positions // bs, 0, tables.shape[1] - 1), axis=1
    )
    flat = jnp.clip(page, 0, num_pages - 1) * bs + positions % bs  # [S, R]

    def gather(pool):
        view = unpacked_view(pool, head_dim, num_kv_heads)
        view = view.reshape(nl, view.shape[1], -1, head_dim)
        g = view[:, :, flat]  # [L, Hkv, S, R, D]
        g = g.transpose(0, 2, 3, 1, 4)  # [L, S, R, Hkv, D]
        return jnp.where(valid[None, :, :, None, None], g, 0)

    return gather(cache["k"]), gather(cache["v"])


def _attend(
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    li: jnp.ndarray,
    q: jnp.ndarray,  # [S, Hq, D]
    pos0: jnp.ndarray,  # [S] cached lengths
    tables: jnp.ndarray,  # [S, PPS]
    ck: jnp.ndarray,  # [S, Hkv, T, D]
    cv: jnp.ndarray,
    counts: jnp.ndarray,  # [S]
    attn_impl: str,
    ppcb: int,
    spb: int,
):
    if attn_impl == "kernel":
        return paged_decode_attention(
            q, cache["k"], cache["v"], li, pos0, tables, ck, cv, counts,
            pages_per_compute_block=ppcb, slots_per_block=spb,
            num_kv_heads=cfg.num_kv_heads,
        )
    return paged_decode_attention_jnp(
        q, cache["k"], cache["v"], li, pos0, tables, ck, cv, counts,
        num_kv_heads=cfg.num_kv_heads,
    )


def _decode_core(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tables: jnp.ndarray,  # [S, PPS]
    pos0: jnp.ndarray,  # [S] cached tokens per slot (fixed this chunk)
    tokens0: jnp.ndarray,  # [S] current input token per slot
    active0: jnp.ndarray,  # [S] bool
    key: Optional[jax.Array],
    sample_args: Optional[tuple],
    stop_args: Optional[tuple],
    steps: int,
    attn_impl: str,
    ppcb: int,
    spb: int,
    topk_bound: int,
    rope_delta: Optional[jnp.ndarray] = None,  # [S] mrope text-position shift
    slot_ids: Optional[jnp.ndarray] = None,  # [S] engine slot per row
    align_base: Optional[jnp.ndarray] = None,  # [S] admission cache length
    replay: int = 0,  # static: canonical chunk quantum - 1 (0 = off)
):
    """Shared body of decode_multi / decode_step. When sample_args is None,
    runs exactly one step and returns the logits instead of sampling.

    ``rope_delta`` shifts ROPE positions only (VLM mrope compresses image
    blocks, so a text token's rotary position lags its cache index by a
    per-request constant); attention windows still use cache lengths.

    ``slot_ids`` keys each row's sampling RNG by its engine slot — under
    decode tail compaction rows are a gathered subset of slots, and the
    stream a slot produces must not depend on its row position.

    ``align_base``/``replay`` (replay MUST equal steps - 1 when used)
    enable canonical-alignment replay for speculative serving (see
    _gather_recent_kv): a slot sitting ``rl = (pos0 - align_base) %
    steps`` tokens past its last canonical chunk boundary gets the
    boundary-to-now K/V gathered from the pool into the leading chunk
    buffer entries, starts the scan at within-chunk count rl, and stops
    emitting at the boundary (dormant rows stay alive and resume next
    dispatch realigned). The buffer stays EXACTLY ``steps`` wide and
    every position lands at within-chunk column (p - base) with the
    pool window ending at its boundary — the same SHAPES and the same
    inputs as the non-speculative run, which is what bit-exactness
    actually requires (merely masking extra buffer columns changes
    reduce codegen and drifts ulps; measured on the head-merged
    layout). Spec-off engines pass replay = 0 and run the unchanged
    program; with replay the sample path returns a trailing
    ``next_tokens`` [S] (a dormant row's next input is its LAST emitted
    token, not step steps-1's sample)."""
    s = tables.shape[0]
    d = cfg.head_dim
    nl, hkv_pool, num_pages, prow, fd = cache["k"].shape
    hkv = cfg.num_kv_heads
    # tokens per pool row differ by layout (head-merged packs every head
    # into the lane dim); page_size = rows * tokens-per-row either way
    _, tpr = layout_from_pool(cache["k"].shape, hkv, d)
    page_size = prow * tpr
    cos, sin = rope_frequencies(
        cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta
    )
    srange = jnp.arange(s)
    kv_dtype = cache["k"].dtype
    use_replay = replay > 0 and align_base is not None
    if use_replay and replay != steps - 1:
        raise ValueError(
            f"replay ({replay}) must be steps - 1 ({steps - 1}): the "
            "canonical chunk quantum IS the dispatch step count"
        )
    if use_replay:
        rl = jnp.where(active0, jnp.mod(pos0 - align_base, steps), 0)
    else:
        rl = jnp.zeros(s, jnp.int32)
    base = pos0 - rl  # pool window ends at the canonical boundary

    def model_step(kbuf, vbuf, tokens, clen, active):
        """One forward pass for all slots; new K/V appended to the chunk
        buffers (inactive slots drop). Returns (kbuf, vbuf, logits).

        The 50MB-class chunk buffers are READ-ONLY inside the layer scan
        (a scatter on a nested scan carry costs a full buffer copy per
        layer — measured at ~25ms/step): each layer overlays only its own
        small [S, T] slice for the self-token, the per-layer K/V stack
        out as scan ys, and ONE bulk scatter per step appends them."""
        x = params["embedding"][tokens]  # [S, D]
        if cfg.scale_embeddings:  # gemma
            x = x * jnp.asarray(cfg.hidden_size**0.5, x.dtype)
        # clen is the ABSOLUTE within-chunk count (starts at rl under
        # replay — the replayed entries occupy buffer cols [0, rl)); the
        # just-written self token is visible
        pos = base + clen
        if rope_delta is not None:
            pos = jnp.maximum(pos + rope_delta, 0)
        counts = clen + 1
        ci = jnp.where(active, clen, steps)

        def layer(x, xs):
            lp, li = xs
            q, k, v = _layer_pre(cfg, lp, x)  # q [S,Hq,D] k/v [S,Hkv,D]
            q = apply_rope(q[:, None], pos[:, None], cos, sin)[:, 0]
            k = apply_rope(k[:, None], pos[:, None], cos, sin)[:, 0]
            kb = jax.lax.dynamic_index_in_dim(kbuf, li, 0, keepdims=False)
            vb = jax.lax.dynamic_index_in_dim(vbuf, li, 0, keepdims=False)
            kb = kb.at[srange, ci].set(k.astype(kv_dtype), mode="drop")
            vb = vb.at[srange, ci].set(v.astype(kv_dtype), mode="drop")
            attn = _attend(
                cfg, cache, li, q, base, tables,
                kb.transpose(0, 2, 1, 3), vb.transpose(0, 2, 1, 3),
                counts, attn_impl, ppcb, spb,
            )
            x = _layer_post(
                cfg, lp, x, attn.reshape(s, cfg.q_dim).astype(x.dtype),
                active,
            )
            return x, (k.astype(kv_dtype), v.astype(kv_dtype))

        x, (knew, vnew) = jax.lax.scan(
            layer, x, (params["layers"], jnp.arange(nl, dtype=jnp.int32))
        )
        # ONE bulk append per step: [L, S, Hkv, D] at (slot, ci)
        kbuf = kbuf.at[:, srange, ci].set(knew, mode="drop")
        vbuf = vbuf.at[:, srange, ci].set(vnew, mode="drop")
        return kbuf, vbuf, _final_logits(params, cfg, x)

    # inactive slots scatter at index `steps` (out of range → dropped)
    kbuf0 = jnp.zeros((nl, s, steps, hkv, d), kv_dtype)
    vbuf0 = jnp.zeros_like(kbuf0)
    if use_replay:
        seed_k, seed_v = _gather_recent_kv(
            cache, tables, pos0, rl, replay, hkv, d
        )
        kbuf0 = kbuf0.at[:, :, :replay].set(seed_k)
        vbuf0 = vbuf0.at[:, :, :replay].set(seed_v)

    def merge_view(buf):
        """This chunk's OWN entries (cols [rl, rl+emitted) per row) —
        the replay prefix is already in the pool and must not re-merge
        (its first row may predate last_rows' remembered partial row).
        Clipped out-of-range cols land beyond the merge counts and
        drop."""
        if not use_replay:
            return buf
        idx = jnp.clip(
            rl[:, None] + jnp.arange(steps, dtype=jnp.int32)[None, :],
            0, steps - 1,
        )
        return jnp.take_along_axis(
            buf, idx[None, :, :, None, None], axis=2
        )

    if sample_args is None:
        kbuf, vbuf, logits = model_step(kbuf0, vbuf0, tokens0, rl, active0)
        clen_final = active0.astype(jnp.int32)
        return logits, merge_view(kbuf), merge_view(vbuf), clen_final

    temperature, top_p, top_k, greedy = sample_args
    remaining0, no_stop0, stop_tokens = stop_args

    def step(carry, step_key):
        kbuf, vbuf, tokens, clen, active, remaining, no_stop = carry
        # boundary cap: a row whose within-chunk count reached `steps`
        # goes DORMANT for the rest of this dispatch (still alive — it
        # resumes realigned next dispatch). Only ever binds under
        # replay, where clen starts at rl > 0
        on = active & (clen < steps)
        kbuf, vbuf, logits = model_step(kbuf, vbuf, tokens, clen, on)
        toks, logps = _sample_impl(
            logits, step_key, temperature, top_p, top_k, greedy,
            topk_bound, slot_ids=slot_ids,
        )
        emitted = on
        hit_stop = jnp.any(
            toks[:, None] == stop_tokens, axis=1
        ) & (no_stop <= 1)
        clen = clen + on
        remaining = jnp.where(on, remaining - 1, remaining)
        no_stop = jnp.where(on, no_stop - 1, no_stop)
        active = jnp.where(on, active & ~hit_stop & (remaining > 0), active)
        # dormant rows keep their last emitted token — it is the next
        # dispatch's input
        tokens = jnp.where(on, toks, tokens)
        return (kbuf, vbuf, tokens, clen, active, remaining, no_stop), (
            toks, logps, emitted,
        )

    keys = jax.random.split(key, steps)
    (kbuf, vbuf, next_tokens, clen, active, remaining, no_stop), (
        toks, logps, emitted,
    ) = jax.lax.scan(
        step,
        (kbuf0, vbuf0, tokens0, rl, active0, remaining0, no_stop0),
        keys,
    )
    return (
        toks, logps, emitted, active, remaining, no_stop, base + clen,
        merge_view(kbuf), merge_view(vbuf), clen - rl, next_tokens,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "steps", "topk_bound", "attn_impl", "ppcb", "spb", "replay",
    ),
)
def _decode_multi_forward(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tables: jnp.ndarray,  # [S, PPS] int32 (bucketed page window)
    pos0: jnp.ndarray,  # [S] int32 cached tokens per slot
    tokens: jnp.ndarray,  # [S] current input token per slot
    active: jnp.ndarray,  # [S] bool
    remaining: jnp.ndarray,  # [S] int32 tokens still allowed per slot
    no_stop_before: jnp.ndarray,  # [S] int32 (min_new_tokens countdown)
    stop_tokens: jnp.ndarray,  # [S, K] int32, -1 padded
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    top_k: jnp.ndarray,
    greedy: jnp.ndarray,
    steps: int,
    topk_bound: int = 0,
    attn_impl: str = "jnp",
    ppcb: int = 4,
    spb: int = 8,
    rope_delta: Optional[jnp.ndarray] = None,
    slot_ids: Optional[jnp.ndarray] = None,
    align_base: Optional[jnp.ndarray] = None,
    replay: int = 0,
):
    """`steps` fused decode+sample iterations in ONE dispatch with stop
    handling on device (see module doc). Host contract: tables cover
    ceil((pos0[s]+steps)/page_size) pages for every active slot.

    READ-ONLY forward chunk (the merge is a separate dispatch in
    decode_multi)."""
    return _decode_core(
        params, cfg, cache, tables, pos0, tokens, active, key,
        (temperature, top_p, top_k, greedy),
        (remaining, no_stop_before, stop_tokens),
        steps, attn_impl, ppcb, spb, topk_bound, rope_delta=rope_delta,
        slot_ids=slot_ids, align_base=align_base, replay=replay,
    )


def decode_multi(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tables: jnp.ndarray,
    pos0: jnp.ndarray,
    tokens: jnp.ndarray,
    active: jnp.ndarray,
    remaining: jnp.ndarray,
    no_stop_before: jnp.ndarray,
    stop_tokens: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    top_k: jnp.ndarray,
    greedy: jnp.ndarray,
    steps: int,
    topk_bound: int = 0,
    attn_impl: str = "jnp",
    ppcb: int = 1,
    spb: int = 16,
    last_rows: Optional[Dict[str, jnp.ndarray]] = None,
    rope_delta: Optional[jnp.ndarray] = None,
    slot_ids: Optional[jnp.ndarray] = None,
    align_base: Optional[jnp.ndarray] = None,
    replay: int = 0,
):
    """`steps` fused decode+sample iterations: one READ-ONLY forward
    dispatch + one WRITE-ONLY merge dispatch (reading and writing the
    pool in one computation costs a full pool copy on this backend).
    Host contract: tables cover ceil((pos0[s]+steps)/page_size) pages for
    every active slot.

    ``slot_ids`` maps each ROW to its engine slot (default: identity).
    Under decode tail compaction the engine dispatches a gathered subset
    of slots; slot_ids keys the per-row sampling RNG and indexes
    ``last_rows`` (which may then keep its full [L, max_num_seqs, ...]
    shape), and the returned ``new_last_rows`` is in ROW space for the
    caller to scatter back. Padding rows may carry an out-of-range slot
    id — gathers clip, and the caller drops their scatter.

    Returns (cache, toks [steps,S], logps [steps,S], emitted [steps,S],
    active_after [S], remaining_after, no_stop_after, lens_after [S],
    new_last_rows, next_tokens [S]). ``lens_after`` keeps the per-slot
    cached length device-resident so the host can dispatch chunk N+1
    before fetching chunk N's results (the serving loop pipelines
    dispatch against result processing).

    ``next_tokens`` is each row's next decode input: under
    canonical-alignment replay (``align_base`` given, ``replay`` =
    steps - 1 — speculative engines, see _decode_core) a row that hit
    its chunk boundary mid-dispatch goes dormant and resumes from its
    LAST emitted token; without replay it equals toks[-1] for every row
    still active at chunk end."""
    if slot_ids is None:
        slot_ids = jnp.arange(tables.shape[0], dtype=jnp.int32)
    (
        toks, logps, emitted, active_a, remaining_a, no_stop_a, lens_a,
        kbuf, vbuf, clen, next_tokens,
    ) = _decode_multi_forward(
        params, cfg, cache, tables, pos0, tokens, active, remaining,
        no_stop_before, stop_tokens, key, temperature, top_p, top_k,
        greedy, steps, topk_bound, attn_impl, ppcb, spb,
        rope_delta=rope_delta, slot_ids=slot_ids, align_base=align_base,
        replay=replay,
    )
    cache, new_last = merge_tokens(
        cache, tables, pos0, clen, kbuf, vbuf, last_rows=last_rows,
        slot_ids=slot_ids,
    )
    # next_tokens always rides along (r14): for replay == 0 it equals
    # toks[-1] for every row still active at chunk end (the scan carry
    # updates while `on`), and inactive rows' inputs are masked and
    # row-independent — returning it saves the caller an eager [-1]
    # slice per chunk that would dodge dispatch-scope attribution
    return (
        cache, toks, logps, emitted, active_a, remaining_a, no_stop_a,
        lens_a, new_last, next_tokens,
    )


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "attn_impl", "ppcb", "spb"),
)
def _decode_step_forward(
    params, cfg, cache, tables, pos0, tokens, active,
    attn_impl="jnp", ppcb=1, spb=16, rope_delta=None,
):
    return _decode_core(
        params, cfg, cache, tables, pos0, tokens, active, None, None, None,
        1, attn_impl, ppcb, spb, 0, rope_delta=rope_delta,
    )


def decode_step(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tables: jnp.ndarray,  # [S, PPS]
    pos0: jnp.ndarray,  # [S]
    tokens: jnp.ndarray,  # [S]
    active: jnp.ndarray,  # [S] bool
    attn_impl: str = "jnp",
    ppcb: int = 1,
    spb: int = 16,
    last_rows: Optional[Dict[str, jnp.ndarray]] = None,
    rope_delta: Optional[jnp.ndarray] = None,
):
    """Single decode step for all slots (read-only forward + write-only
    merge); returns (cache, logits [S, V], new_last_rows). Callers MUST
    thread last_rows between sequential calls (it preserves the partial
    first row when pos0 isn't row-aligned)."""
    logits, kbuf, vbuf, clen = _decode_step_forward(
        params, cfg, cache, tables, pos0, tokens, active, attn_impl,
        ppcb, spb, rope_delta=rope_delta,
    )
    cache, new_last = merge_tokens(
        cache, tables, pos0, clen, kbuf, vbuf, last_rows=last_rows
    )
    return cache, logits, new_last


# ---------------------------------------------------------------------------
# Speculative verify (draft-free multi-token decode)
# ---------------------------------------------------------------------------
@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "k", "topk_bound", "attn_impl", "ppcb", "spb", "replay",
    ),
)
def _spec_verify_forward(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tables: jnp.ndarray,  # [S, PPS]
    pos0: jnp.ndarray,  # [S] cached tokens per slot
    tokens: jnp.ndarray,  # [S] current input token per slot
    draft: jnp.ndarray,  # [S, K-1] proposed continuation tokens
    draft_len: jnp.ndarray,  # [S] valid drafts per slot (0..K-1)
    active: jnp.ndarray,  # [S] bool
    remaining: jnp.ndarray,  # [S]
    no_stop_before: jnp.ndarray,  # [S]
    stop_tokens: jnp.ndarray,  # [S, 8]
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    top_k: jnp.ndarray,
    greedy: jnp.ndarray,
    k: int,  # static verify window: 1 current token + K-1 draft positions
    topk_bound: int = 0,
    attn_impl: str = "jnp",
    ppcb: int = 4,
    spb: int = 8,
    rope_delta: Optional[jnp.ndarray] = None,
    slot_ids: Optional[jnp.ndarray] = None,
    align_base: Optional[jnp.ndarray] = None,
    replay: int = 0,
):
    """Score ``k`` positions per slot in ONE forward and accept the
    longest prefix the model itself would have produced.

    Position i's input is ``[tokens, draft[0], ..., draft[i-1]][i]``; its
    logits predict the NEXT token, sampled through the exact
    ``_sample_impl`` the sequential decode scan uses (greedy slots:
    argmax; sampled slots: an independent key per position — every kept
    token is drawn from the true conditional, so the output distribution
    is exactly the non-speculative one). Acceptance is EXACT MATCH: the
    sampled token at position i must equal the draft token that was fed
    as position i+1's input, otherwise positions > i were computed on a
    wrong prefix and emission stops. Greedy streams are therefore
    bit-identical with speculation on or off.

    Numerics contract (what makes that bit-exactness hold): every op is
    row/position-independent against the sequential ``_decode_core``
    path — batched matmuls ([S, K, D] vs [S, D]) are row-stable, rope /
    norms are elementwise, and each position's attention is the SAME
    ``_attend`` call the scan makes (q [S, Hq, D], chunk counts i+1;
    masked chunk/window tails contribute exact zeros regardless of
    buffer size — the same shape-invariance the kv_bucket ladder and
    decode compaction already rely on).

    Stop/budget semantics mirror the scan step-for-step: a stop-token
    hit or exhausted budget ends emission exactly where the sequential
    path would.

    Returns the ``_decode_multi_forward`` tuple plus nothing new: (toks
    [K, S], logps, emitted, active_after, remaining_after, no_stop_after,
    lens_after, kbuf, vbuf, clen) where ``clen`` is the per-slot count of
    chunk-buffer positions whose K/V is VALID (inputs on the accepted
    path) — the merge writes only those, which IS the KV rollback:
    rejected positions never reach the pool, and cache-length accounting
    (``lens_after = pos0 + clen``) matches a non-speculative run that
    emitted the same tokens.
    """
    s = tables.shape[0]
    d = cfg.head_dim
    nl = cache["k"].shape[0]
    hkv = cfg.num_kv_heads
    cos, sin = rope_frequencies(
        cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta
    )
    kv_dtype = cache["k"].dtype
    if slot_ids is None:
        slot_ids = jnp.arange(s, dtype=jnp.int32)
    # canonical-alignment replay (see _gather_recent_kv / _decode_core):
    # window position i is scored with the EXACT shapes the sequential
    # engine gives that position — a width-cq chunk buffer holding its
    # canonical chunk's entries at within-chunk columns, pool window
    # ending at that chunk's boundary. cq = replay + 1 is the engine's
    # decode_chunk; windows may cross boundaries (every position gets
    # its own buffer/window). Without align_base (standalone use) the
    # window itself plays the chunk-buffer role at width k.
    use_replay = replay > 0 and align_base is not None
    cq = replay + 1
    if use_replay:
        rl = jnp.where(active, jnp.mod(pos0 - align_base, cq), 0)
        seed_k, seed_v = _gather_recent_kv(
            cache, tables, pos0, rl, replay, hkv, d
        )
    else:
        rl = jnp.zeros(s, jnp.int32)
    base = pos0 - rl

    # [S, K] input token matrix: current token then the draft guesses
    tokens_mat = jnp.concatenate([tokens[:, None], draft], axis=1)
    x = params["embedding"][tokens_mat]  # [S, K, D]
    if cfg.scale_embeddings:  # gemma
        x = x * jnp.asarray(cfg.hidden_size**0.5, x.dtype)
    pos = pos0[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]  # [S, K]
    if rope_delta is not None:
        pos = jnp.maximum(pos + rope_delta[:, None], 0)
    valid_q = jnp.broadcast_to(active[:, None], (s, k))

    def layer(x, xs):
        lp, li = xs
        q, kk, vv = _layer_pre(cfg, lp, x)  # [S, K, H*, D]
        q = apply_rope(q, pos, cos, sin)
        kk = apply_rope(kk, pos, cos, sin)
        kwin = kk.astype(kv_dtype)  # [S, K, Hkv, D]
        vwin = vv.astype(kv_dtype)
        attns = []
        if use_replay:
            # canonical chunk buffer: col c ↔ cache position base + c —
            # replayed boundary-to-now prefix at cols [0, rl), this
            # window scattered at per-row cols [rl, rl+K). Width is
            # EXACTLY cq (the sequential engine's chunk shape); window
            # positions at or past the next boundary are never emitted
            # (the acceptance loop caps there — their canonical chunk
            # would need [pos0, boundary) as POOL entries, which are not
            # merged yet), so slicing to cq loses nothing emittable.
            sk = jax.lax.dynamic_index_in_dim(seed_k, li, 0, keepdims=False)
            sv = jax.lax.dynamic_index_in_dim(seed_v, li, 0, keepdims=False)
            widx = jnp.clip(
                rl[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :],
                0, replay + k - 1,
            )
            srows = jnp.arange(s)[:, None]
            kb = jnp.concatenate(
                [sk, jnp.zeros((s, k, hkv, d), kv_dtype)], axis=1
            ).at[srows, widx].set(kwin)[:, :cq]
            vb = jnp.concatenate(
                [sv, jnp.zeros((s, k, hkv, d), kv_dtype)], axis=1
            ).at[srows, widx].set(vwin)[:, :cq]
            ck = kb.transpose(0, 2, 1, 3)  # [S, Hkv, cq, D]
            cv = vb.transpose(0, 2, 1, 3)
            for i in range(k):  # static unroll; K is small
                attns.append(
                    _attend(
                        cfg, cache, li, q[:, i], base, tables, ck, cv,
                        rl + i + 1, attn_impl, ppcb, spb,
                    )
                )
        else:
            # standalone (no alignment contract): the K in-window
            # positions play the chunk buffer's role; position i sees
            # entries [0, i] via counts
            ck = kwin.transpose(0, 2, 1, 3)  # [S, Hkv, K, D]
            cv = vwin.transpose(0, 2, 1, 3)
            for i in range(k):
                counts_i = jnp.full((s,), i + 1, jnp.int32)
                attns.append(
                    _attend(
                        cfg, cache, li, q[:, i], pos0, tables, ck, cv,
                        counts_i, attn_impl, ppcb, spb,
                    )
                )
        attn = jnp.stack(attns, axis=1)  # [S, K, Hq, D]
        x = _layer_post(
            cfg, lp, x, attn.reshape(s, k, cfg.q_dim).astype(x.dtype),
            valid_q,
        )
        return x, (kk.astype(kv_dtype), vv.astype(kv_dtype))

    x, (knew, vnew) = jax.lax.scan(
        layer, x, (params["layers"], jnp.arange(nl, dtype=jnp.int32))
    )
    # knew/vnew [L, S, K, Hkv, D] — already the decode chunk-buffer layout
    logits = _final_logits(params, cfg, x)  # [S, K, V] fp32

    keys = jax.random.split(key, k)
    # ``on`` gates EMISSION (dies on stop/budget like the scan's active,
    # and ALSO on a draft mismatch — later positions were computed on a
    # wrong prefix); ``alive`` is the request's continued-existence flag
    # the engine gets back: a rejected draft ends emission but NOT the
    # request (it simply continues un-speculated next chunk)
    on = active
    alive = active
    rem = remaining
    nsb = no_stop_before
    clen = jnp.zeros(s, jnp.int32)
    toks_list, logps_list, emitted_list = [], [], []
    for i in range(k):
        toks_i, logps_i = _sample_impl(
            logits[:, i], keys[i], temperature, top_p, top_k, greedy,
            topk_bound, slot_ids=slot_ids,
        )
        emitted_i = on
        emitted_list.append(emitted_i)
        hit_stop = jnp.any(
            toks_i[:, None] == stop_tokens, axis=1
        ) & (nsb <= 1)
        clen = clen + on
        rem = jnp.where(on, rem - 1, rem)
        nsb = jnp.where(on, nsb - 1, nsb)
        # exactly the scan's continue condition for this emitted token
        cont = ~hit_stop & (rem > 0)
        alive = jnp.where(emitted_i, cont, alive)
        on = emitted_i & cont
        if i + 1 < k:
            # continue into position i+1 only if the draft supplied it
            # AND the model just produced exactly that token (the
            # verified-prefix rule)
            on = on & (draft_len >= i + 1) & (toks_i == tokens_mat[:, i + 1])
            if use_replay:
                # canonical-boundary cap: a position in the NEXT chunk
                # would need this window's pre-boundary tokens as pool
                # entries (not merged yet) — the row stops here and
                # resumes realigned next dispatch
                on = on & (rl + (i + 1) < cq)
        toks_list.append(toks_i)
        logps_list.append(logps_i)
    toks = jnp.stack(toks_list)  # [K, S]
    logps = jnp.stack(logps_list)
    emitted = jnp.stack(emitted_list)
    # next decode input per row = its LAST EMITTED token (a row that
    # rejected its draft at position j resumes from token j, not from
    # position k-1's wrong-prefix sample — unlike the sequential scan,
    # toks[-1] is NOT the next input for every live row here)
    last_idx = jnp.clip(clen - 1, 0, k - 1)[None, :]
    next_tokens = jnp.take_along_axis(toks, last_idx, axis=0)[0]
    return (
        toks, logps, emitted, alive, rem, nsb, pos0 + clen, knew, vnew,
        clen, next_tokens,
    )


def spec_verify(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tables: jnp.ndarray,
    pos0: jnp.ndarray,
    tokens: jnp.ndarray,
    draft: jnp.ndarray,  # [S, K-1]
    draft_len: jnp.ndarray,  # [S]
    active: jnp.ndarray,
    remaining: jnp.ndarray,
    no_stop_before: jnp.ndarray,
    stop_tokens: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    top_k: jnp.ndarray,
    greedy: jnp.ndarray,
    k: int,
    topk_bound: int = 0,
    attn_impl: str = "jnp",
    ppcb: int = 1,
    spb: int = 16,
    last_rows: Optional[Dict[str, jnp.ndarray]] = None,
    rope_delta: Optional[jnp.ndarray] = None,
    slot_ids: Optional[jnp.ndarray] = None,
    align_base: Optional[jnp.ndarray] = None,
    replay: int = 0,
):
    """Multi-token verify with KV rollback: one READ-ONLY k-position
    forward + the standard WRITE-ONLY merge, where the merge count is the
    ACCEPTED prefix length — rejected positions' K/V never reach the
    pool, so pool state after a verify equals a non-speculative run that
    emitted the same tokens (pinned by tests/test_spec_decode.py).

    Same return contract as ``decode_multi`` (the engine's dispatch /
    fetch / process machinery treats both identically, with steps = k),
    plus a trailing ``next_tokens`` [S]: each row's last EMITTED token —
    the next decode input (``toks[-1]`` would be a wrong-prefix sample
    for rows that rejected their draft early).
    """
    if slot_ids is None:
        slot_ids = jnp.arange(tables.shape[0], dtype=jnp.int32)
    (
        toks, logps, emitted, active_a, remaining_a, no_stop_a, lens_a,
        kbuf, vbuf, clen, next_tokens,
    ) = _spec_verify_forward(
        params, cfg, cache, tables, pos0, tokens, draft, draft_len,
        active, remaining, no_stop_before, stop_tokens, key, temperature,
        top_p, top_k, greedy, k, topk_bound, attn_impl, ppcb, spb,
        rope_delta=rope_delta, slot_ids=slot_ids, align_base=align_base,
        replay=replay,
    )
    cache, new_last = merge_tokens(
        cache, tables, pos0, clen, kbuf, vbuf, last_rows=last_rows,
        slot_ids=slot_ids,
    )
    return (
        cache, toks, logps, emitted, active_a, remaining_a, no_stop_a,
        lens_a, new_last, next_tokens,
    )


# ---------------------------------------------------------------------------
# Sampling
# ---------------------------------------------------------------------------
def _sample_impl(
    logits: jnp.ndarray,  # [S, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S] int32 (0 = disabled)
    greedy: jnp.ndarray,  # [S] bool
    topk_bound: int,
    slot_ids: Optional[jnp.ndarray] = None,  # [S] engine slot per row
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-slot sampling; returns (tokens [S], logprobs [S]).

    Each row draws under a key folded from ``slot_ids[row]`` (defaulting
    to the row index), NOT from its position in the batch — so a
    request's stream is invariant to which row it occupies. This is what
    makes decode tail compaction (engine rows = active-slot bucket)
    token-exact against the full-slot dispatch.

    ``topk_bound`` picks the truncation strategy (static):
      -1  no truncation anywhere (all slots top_p>=1, top_k=0) — a single
          ``categorical`` over the scaled logits; no sort at all.
       0  exact full-vocab sort (argsort) — the always-correct fallback.
      K>0 ``lax.top_k(K)`` candidates, top-k/top-p masks applied within
          them — the fast serving path. Slots that request NO truncation
          (top_k=0, top_p>=1) sample full-vocab categorical instead, so
          their behavior logprob matches their true sampling distribution
          (advisor round-2 finding).

    The returned logprob is under the temperature-scaled (untruncated)
    distribution — the behavior-policy logprob the trainer consumes
    (reference ModelResponse.output_logprobs semantics). Greedy slots
    report the logprob under the *unscaled* distribution (temperature
    never enters their behavior policy).
    """
    s, v = logits.shape
    temp = jnp.maximum(temperature, 1e-5)[:, None]
    scaled = logits / temp
    logp_full = jax.nn.log_softmax(scaled, axis=-1)
    if slot_ids is None:
        slot_ids = jnp.arange(s, dtype=jnp.int32)
    row_keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        key, slot_ids
    )
    _categorical = jax.vmap(lambda k_, l_: jax.random.categorical(k_, l_))

    if topk_bound < 0:
        sampled = _categorical(row_keys, scaled)
    elif topk_bound > 0:
        kb = min(topk_bound, v)
        vals, idx = jax.lax.top_k(scaled, kb)  # [S, kb]
        # top_p cutoffs are defined against the FULL-vocab distribution,
        # not renormalized over the kb candidates (matching the exact path)
        cand_probs = jnp.exp(jnp.take_along_axis(logp_full, idx, axis=-1))
        cumprev = jnp.cumsum(cand_probs, axis=-1) - cand_probs
        rank = jnp.arange(kb)[None, :]
        keep = jnp.where(top_k[:, None] > 0, rank < top_k[:, None], True)
        keep &= cumprev < top_p[:, None]
        keep = keep.at[:, 0].set(True)  # always keep the argmax token
        trunc = jnp.where(keep, vals, NEG_INF)
        choice = _categorical(row_keys, trunc)
        truncated_pick = jnp.take_along_axis(
            idx, choice[:, None], axis=-1
        )[:, 0]
        # untruncated slots keep the exact full-vocab distribution
        untruncated = (top_k <= 0) & (top_p >= 1.0)
        full_pick = _categorical(row_keys, scaled)
        sampled = jnp.where(untruncated, full_pick, truncated_pick)
    else:
        # exact path: full sort (slow; tests / host-side calls)
        sort_idx = jnp.argsort(-scaled, axis=-1)
        sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumprobs = jnp.cumsum(sorted_probs, axis=-1)
        rank = jnp.arange(v)[None, :]
        keep = jnp.ones((s, v), bool)
        keep &= jnp.where(top_k[:, None] > 0, rank < top_k[:, None], True)
        keep &= (cumprobs - sorted_probs) < top_p[:, None]
        keep = keep.at[:, 0].set(True)
        trunc_sorted = jnp.where(keep, sorted_logits, NEG_INF)
        trunc = jnp.full_like(scaled, NEG_INF).at[
            jnp.arange(s)[:, None], sort_idx
        ].set(trunc_sorted)
        sampled = _categorical(row_keys, trunc)

    argmax = jnp.argmax(logits, axis=-1)
    tokens = jnp.where(greedy, argmax, sampled).astype(jnp.int32)
    # Greedy slots ignore temperature when picking the token, so report the
    # logprob under the *unscaled* distribution.
    lp_sampled = jnp.take_along_axis(
        logp_full, tokens[:, None], axis=-1
    ).squeeze(-1)
    lp_greedy = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), tokens[:, None], axis=-1
    ).squeeze(-1)
    logprobs = jnp.where(greedy, lp_greedy, lp_sampled)
    return tokens, logprobs


@jax.jit
def pack_host(*arrays) -> jnp.ndarray:
    """Flatten+concat device arrays into ONE float32 blob so the host pays
    a single fetch round-trip (over a driver tunnel each array fetch is a
    full RPC; int32 token ids are exact in f32 below 2^24)."""
    return jnp.concatenate(
        [a.reshape(-1).astype(jnp.float32) for a in arrays]
    )


@functools.partial(jax.jit, static_argnames=("topk_bound",))
def sample_tokens(
    logits: jnp.ndarray,  # [S, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S] int32 (0 = disabled)
    greedy: jnp.ndarray,  # [S] bool
    topk_bound: int = 0,
    slot_ids: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    if slot_ids is None:
        slot_ids = jnp.arange(logits.shape[0], dtype=jnp.int32)
    return _sample_impl(
        logits, key, temperature, top_p, top_k, greedy, topk_bound,
        slot_ids=slot_ids,
    )
