"""Jitted prefill / decode programs over the paged KV-block pool.

The TPU-native core of the generation engine (role of SGLang's model runner
behind the reference's HTTP API, driven at areal/engine/sglang_remote.py).
Compiled programs over the page pool (inference/cache.py layout):

- ``prefill_batch``: N prompt suffixes as ONE batched [N, Tp] forward.
  Each row resumes from ``offset`` tokens already cached in its pages
  (prefix reuse — the radix-cache analog): attention = gathered page
  window [0, offset) ++ in-flight suffix (causal), and the suffix K/V for
  all layers lands in the pool with one donated scatter after the layer
  scan — the pool itself never rides the scan (a mutated multi-GB scan
  carry costs a full copy per step on TPU; measured, not folklore).
- ``decode_multi``: `steps` fused decode+sample iterations in ONE dispatch
  with device-side stop handling. The pool is READ-ONLY inside the step
  loop; new tokens' K/V accumulate in a small [L, S, T] chunk buffer that
  the paged-attention kernel folds into the same online softmax, and one
  bulk scatter merges the chunk into the pool at the end.
- ``decode_step``: single step without sampling (tests / TP fallback).
- ``copy_pages``: page-granular pool copy (GRPO sibling partial-tail pages;
  full prompt pages are *shared* host-side, no copy).

Attention backend is static per call: "kernel" (Pallas manual-DMA flash,
TPU) or "jnp" (gather fallback — CPU tests and tensor-parallel serving).
Sampling (temperature / top-k / top-p / greedy, per slot) runs on device
with a static ``topk_bound``; fp32 softmax/logits throughout.
"""

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from areal_tpu.models.config import ModelConfig
from areal_tpu.models.transformer import Params
from areal_tpu.ops.basic import apply_rope, rms_norm, rope_frequencies
from areal_tpu.ops.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_jnp,
    unpacked_view,
)

NEG_INF = -2.3819763e38


def _project_qkv(cfg: ModelConfig, lp: Params, h: jnp.ndarray):
    """h [..., D] → q [..., Hq, Dh], k/v [..., Hkv, Dh] (pre-rope)."""
    q = h @ lp["wq"]
    k = h @ lp["wk"]
    v = h @ lp["wv"]
    if cfg.attention_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(h.shape[:-1] + (cfg.num_heads, cfg.head_dim))
    k = k.reshape(h.shape[:-1] + (cfg.num_kv_heads, cfg.head_dim))
    v = v.reshape(h.shape[:-1] + (cfg.num_kv_heads, cfg.head_dim))
    if cfg.use_qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    return q, k, v


def _mlp(
    cfg: ModelConfig,
    lp: Params,
    h: jnp.ndarray,
    valid: Optional[jnp.ndarray] = None,  # [...] matching h[..., 0]
) -> jnp.ndarray:
    if cfg.is_moe:
        from areal_tpu.ops.moe import moe_ffn_from_params

        flat = h.reshape(1, -1, h.shape[-1])
        # padding / inactive-slot tokens must not consume expert capacity
        vflat = None if valid is None else valid.reshape(1, -1)
        out, _ = moe_ffn_from_params(cfg, lp, flat, valid=vflat)
        return out.reshape(h.shape)
    return (jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])) @ lp["w_down"]


def _final_logits(params: Params, cfg: ModelConfig, x: jnp.ndarray):
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = (
        params["embedding"].T if cfg.tie_word_embeddings else params["lm_head"]
    )
    return x.astype(jnp.float32) @ head.astype(jnp.float32)


def flat_positions(
    tables: jnp.ndarray,  # [N, PPS] int32 logical page ids
    positions: jnp.ndarray,  # [N, T] int32 token positions
    page_size: int,
    num_pages: int,
    valid: jnp.ndarray,  # [N, T] bool
) -> jnp.ndarray:
    """Token position → flat pool index (page*BS + off); invalid rows map
    to num_pages*BS (dropped by scatter mode='drop')."""
    page = jnp.take_along_axis(
        tables, jnp.clip(positions // page_size, 0, tables.shape[1] - 1),
        axis=1,
    )
    flat = page * page_size + positions % page_size
    return jnp.where(valid, flat, num_pages * page_size)


# ---------------------------------------------------------------------------
# Prefill (batched, prefix-aware)
# ---------------------------------------------------------------------------
@functools.partial(
    jax.jit,
    static_argnames=("cfg", "prefix_bound"),
    donate_argnames=("cache",),
)
def prefill_batch(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [N, Tp] int32 suffix tokens, padded to bucket
    offsets: jnp.ndarray,  # [N] int32 tokens already cached (prefix reuse)
    true_lens: jnp.ndarray,  # [N] int32 suffix lengths (0 = padding row)
    tables: jnp.ndarray,  # [N, PPS] logical pages covering offset+Tp
    prefix_bound: int = 0,  # static: gathered window >= max(offsets), 0 = none
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """One batched forward over N prompt suffixes; writes each row's suffix
    K/V into its pages; returns last-real-token logits [N, V] (fp32).

    Host contract: tables cover ceil((offset+Tp)/BS) pages per real row;
    ``prefix_bound`` >= every row's offset; offsets are page-aligned.
    """
    n, tp = tokens.shape
    d = cfg.head_dim
    nl, hkv, num_pages, prow, fd = cache["k"].shape
    page_size = prow * fd // d
    mb0 = prefix_bound
    sidx = jnp.arange(tp, dtype=jnp.int32)[None, :]
    pos = offsets[:, None] + sidx  # [N, Tp] absolute positions
    valid_q = sidx < true_lens[:, None]
    cos, sin = rope_frequencies(
        cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta
    )
    x = params["embedding"][tokens]  # [N, Tp, D]
    scale = cfg.head_dim**-0.5
    g, rep = cfg.num_kv_heads, cfg.num_heads // cfg.num_kv_heads

    kpool = unpacked_view(cache["k"], d)  # [L, Hkv, NP*BS..] view
    vpool = unpacked_view(cache["v"], d)
    kflat = kpool.reshape(nl, hkv, num_pages * page_size, d)
    vflat = vpool.reshape(nl, hkv, num_pages * page_size, d)

    if mb0 > 0:
        widx = flat_positions(
            tables,
            jnp.broadcast_to(jnp.arange(mb0, dtype=jnp.int32)[None], (n, mb0)),
            page_size,
            num_pages,
            jnp.broadcast_to(
                jnp.arange(mb0, dtype=jnp.int32)[None] < offsets[:, None],
                (n, mb0),
            ),
        )
        widx = jnp.minimum(widx, num_pages * page_size - 1)  # clamp pads
        prefix_mask = (
            jnp.arange(mb0, dtype=jnp.int32)[None, None, :] < pos[:, :, None]
        ) & (jnp.arange(mb0, dtype=jnp.int32)[None, None, :]
             < offsets[:, None, None])  # [N, Tp, mb0]

    # causal within the in-flight suffix
    suffix_mask = (sidx[:, :, None] >= sidx[:, None, :]) & valid_q[:, None, :]

    def layer(carry, xs):
        x = carry
        lp, li = xs
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q, k, v = _project_qkv(cfg, lp, h)  # [N, Tp, H*, Dh]
        q = apply_rope(q, pos, cos, sin)
        k = apply_rope(k, pos, cos, sin)
        kz = jnp.where(valid_q[..., None, None], k, 0)
        vz = jnp.where(valid_q[..., None, None], v, 0)
        qg = q.reshape(n, tp, g, rep, cfg.head_dim)
        # suffix-vs-suffix scores (causal)
        sc_sfx = (
            jnp.einsum(
                "nqgrd,nkgd->ngrqk", qg, kz,
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        sc_sfx = jnp.where(suffix_mask[:, None, None], sc_sfx, NEG_INF)
        if mb0 > 0:
            kl = jax.lax.dynamic_index_in_dim(kflat, li, 0, keepdims=False)
            vl = jax.lax.dynamic_index_in_dim(vflat, li, 0, keepdims=False)
            win_k = jnp.take(kl, widx, axis=1)  # [Hkv, N, mb0, D]
            win_v = jnp.take(vl, widx, axis=1)
            sc_pre = (
                jnp.einsum(
                    "nqgrd,gnkd->ngrqk", qg, win_k,
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            sc_pre = jnp.where(
                prefix_mask[:, None, None], sc_pre, NEG_INF
            )
            sc = jnp.concatenate([sc_pre, sc_sfx], axis=-1)
        else:
            sc = sc_sfx
        probs = jax.nn.softmax(sc, axis=-1)
        if mb0 > 0:
            attn = jnp.einsum(
                "ngrqk,gnkd->nqgrd",
                probs[..., :mb0].astype(win_v.dtype), win_v,
                preferred_element_type=jnp.float32,
            ) + jnp.einsum(
                "ngrqk,nkgd->nqgrd",
                probs[..., mb0:].astype(vz.dtype), vz,
                preferred_element_type=jnp.float32,
            )
        else:
            attn = jnp.einsum(
                "ngrqk,nkgd->nqgrd", probs.astype(vz.dtype), vz,
                preferred_element_type=jnp.float32,
            )
        attn = attn.astype(x.dtype).reshape(n, tp, cfg.q_dim)
        x = x + attn @ lp["wo"]
        h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + _mlp(cfg, lp, h2, valid=valid_q)
        kv_dtype = cache["k"].dtype
        return x, (kz.astype(kv_dtype), vz.astype(kv_dtype))

    x, (k_sfx, v_sfx) = jax.lax.scan(
        layer, x, (params["layers"], jnp.arange(nl, dtype=jnp.int32))
    )
    # ONE donated scatter of every layer's suffix K/V into the pool
    dest = flat_positions(tables, pos, page_size, num_pages, valid_q)  # [N,Tp]
    kw = k_sfx.transpose(0, 3, 1, 2, 4).reshape(nl, hkv, n * tp, d)
    vw = v_sfx.transpose(0, 3, 1, 2, 4).reshape(nl, hkv, n * tp, d)
    kflat = kflat.at[:, :, dest.reshape(-1)].set(kw, mode="drop")
    vflat = vflat.at[:, :, dest.reshape(-1)].set(vw, mode="drop")
    new_cache = {
        "k": kflat.reshape(cache["k"].shape),
        "v": vflat.reshape(cache["v"].shape),
    }
    last = x[jnp.arange(n), jnp.maximum(true_lens - 1, 0)]  # [N, D]
    logits = _final_logits(params, cfg, last)  # [N, V] fp32
    return new_cache, logits


@functools.partial(jax.jit, donate_argnames=("cache",))
def copy_pages(
    cache: Dict[str, jnp.ndarray],
    src: jnp.ndarray,  # [P] int32 source page per copy
    dst: jnp.ndarray,  # [P] int32 destination (>= num_pages rows dropped)
) -> Dict[str, jnp.ndarray]:
    """Duplicate pool pages src→dst (GRPO sibling partial-tail pages after
    one shared prompt prefill; full pages are shared host-side instead).
    Padding rows use dst >= num_pages."""
    k = cache["k"].at[:, :, dst].set(cache["k"][:, :, src], mode="drop")
    v = cache["v"].at[:, :, dst].set(cache["v"][:, :, src], mode="drop")
    return {"k": k, "v": v}


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------
def _attend(
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    li: jnp.ndarray,
    q: jnp.ndarray,  # [S, Hq, D]
    pos0: jnp.ndarray,  # [S] cached lengths
    tables: jnp.ndarray,  # [S, PPS]
    ck: jnp.ndarray,  # [S, Hkv, T, D]
    cv: jnp.ndarray,
    counts: jnp.ndarray,  # [S]
    attn_impl: str,
    ppcb: int,
    spb: int,
):
    if attn_impl == "kernel":
        return paged_decode_attention(
            q, cache["k"], cache["v"], li, pos0, tables, ck, cv, counts,
            pages_per_compute_block=ppcb, slots_per_block=spb,
        )
    return paged_decode_attention_jnp(
        q, cache["k"], cache["v"], li, pos0, tables, ck, cv, counts
    )


def _decode_core(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tables: jnp.ndarray,  # [S, PPS]
    pos0: jnp.ndarray,  # [S] cached tokens per slot (fixed this chunk)
    tokens0: jnp.ndarray,  # [S] current input token per slot
    active0: jnp.ndarray,  # [S] bool
    key: Optional[jax.Array],
    sample_args: Optional[tuple],
    stop_args: Optional[tuple],
    steps: int,
    attn_impl: str,
    ppcb: int,
    spb: int,
    topk_bound: int,
):
    """Shared body of decode_multi / decode_step. When sample_args is None,
    runs exactly one step and returns the logits instead of sampling."""
    s = tables.shape[0]
    d = cfg.head_dim
    nl, hkv, num_pages, prow, fd = cache["k"].shape
    page_size = prow * fd // d
    cos, sin = rope_frequencies(
        cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta
    )
    srange = jnp.arange(s)
    kv_dtype = cache["k"].dtype

    def model_step(kbuf, vbuf, tokens, clen, active):
        """One forward pass for all slots; new K/V appended to the chunk
        buffers (inactive slots drop). Returns (kbuf, vbuf, logits)."""
        x = params["embedding"][tokens]  # [S, D]
        pos = pos0 + clen
        counts = clen + 1  # the just-written self token is visible

        def layer(xc, xs):
            x, kbuf, vbuf = xc
            lp, li = xs
            h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
            q, k, v = _project_qkv(cfg, lp, h)  # q [S,Hq,D] k/v [S,Hkv,D]
            q = apply_rope(q[:, None], pos[:, None], cos, sin)[:, 0]
            k = apply_rope(k[:, None], pos[:, None], cos, sin)[:, 0]
            ci = jnp.where(active, clen, steps)
            kbuf = kbuf.at[li, srange, ci].set(
                k.astype(kv_dtype), mode="drop"
            )
            vbuf = vbuf.at[li, srange, ci].set(
                v.astype(kv_dtype), mode="drop"
            )
            kb = jax.lax.dynamic_index_in_dim(kbuf, li, 0, keepdims=False)
            vb = jax.lax.dynamic_index_in_dim(vbuf, li, 0, keepdims=False)
            attn = _attend(
                cfg, cache, li, q, pos0, tables,
                kb.transpose(0, 2, 1, 3), vb.transpose(0, 2, 1, 3),
                counts, attn_impl, ppcb, spb,
            )
            x = x + attn.reshape(s, cfg.q_dim).astype(x.dtype) @ lp["wo"]
            h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
            x = x + _mlp(cfg, lp, h2, valid=active)
            return (x, kbuf, vbuf), None

        (x, kbuf, vbuf), _ = jax.lax.scan(
            layer, (x, kbuf, vbuf),
            (params["layers"], jnp.arange(nl, dtype=jnp.int32)),
        )
        return kbuf, vbuf, _final_logits(params, cfg, x)

    # inactive slots scatter at index `steps` (out of range → dropped)
    kbuf0 = jnp.zeros((nl, s, steps, hkv, d), kv_dtype)
    vbuf0 = jnp.zeros_like(kbuf0)

    if sample_args is None:
        kbuf, vbuf, logits = model_step(
            kbuf0, vbuf0, tokens0, jnp.zeros(s, jnp.int32), active0
        )
        clen_final = active0.astype(jnp.int32)
        cache = _merge_chunk(
            cache, kbuf, vbuf, tables, pos0, clen_final, page_size, num_pages
        )
        return cache, logits

    temperature, top_p, top_k, greedy = sample_args
    remaining0, no_stop0, stop_tokens = stop_args

    def step(carry, step_key):
        kbuf, vbuf, tokens, clen, active, remaining, no_stop = carry
        kbuf, vbuf, logits = model_step(kbuf, vbuf, tokens, clen, active)
        toks, logps = _sample_impl(
            logits, step_key, temperature, top_p, top_k, greedy, topk_bound
        )
        emitted = active
        hit_stop = jnp.any(
            toks[:, None] == stop_tokens, axis=1
        ) & (no_stop <= 1)
        clen = clen + active
        remaining = jnp.where(active, remaining - 1, remaining)
        no_stop = jnp.where(active, no_stop - 1, no_stop)
        active = active & ~hit_stop & (remaining > 0)
        return (kbuf, vbuf, toks, clen, active, remaining, no_stop), (
            toks, logps, emitted,
        )

    keys = jax.random.split(key, steps)
    (kbuf, vbuf, tokens, clen, active, remaining, no_stop), (
        toks, logps, emitted,
    ) = jax.lax.scan(
        step,
        (kbuf0, vbuf0, tokens0, jnp.zeros(s, jnp.int32),
         active0, remaining0, no_stop0),
        keys,
    )
    cache = _merge_chunk(
        cache, kbuf, vbuf, tables, pos0, clen, page_size, num_pages
    )
    return cache, toks, logps, emitted, active, remaining, no_stop


def _merge_chunk(
    cache, kbuf, vbuf, tables, pos0, clen, page_size, num_pages
):
    """Bulk scatter: chunk buffers [L, S, T, Hkv, D] → pool at absolute
    positions pos0..pos0+clen (one donated scatter per tensor)."""
    nl, s, t, hkv, d = kbuf.shape
    tgrid = jnp.arange(t, dtype=jnp.int32)[None, :]
    dest = flat_positions(
        tables, pos0[:, None] + tgrid, page_size, num_pages,
        tgrid < clen[:, None],
    ).reshape(-1)  # [S*T]
    kw = kbuf.transpose(0, 3, 1, 2, 4).reshape(nl, hkv, s * t, d)
    vw = vbuf.transpose(0, 3, 1, 2, 4).reshape(nl, hkv, s * t, d)
    kflat = unpacked_view(cache["k"], d).reshape(
        nl, hkv, num_pages * page_size, d
    )
    vflat = unpacked_view(cache["v"], d).reshape(
        nl, hkv, num_pages * page_size, d
    )
    kflat = kflat.at[:, :, dest].set(kw, mode="drop")
    vflat = vflat.at[:, :, dest].set(vw, mode="drop")
    return {
        "k": kflat.reshape(cache["k"].shape),
        "v": vflat.reshape(cache["v"].shape),
    }


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "steps", "topk_bound", "attn_impl", "ppcb", "spb"),
    donate_argnames=("cache",),
)
def decode_multi(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tables: jnp.ndarray,  # [S, PPS] int32 (bucketed page window)
    pos0: jnp.ndarray,  # [S] int32 cached tokens per slot
    tokens: jnp.ndarray,  # [S] current input token per slot
    active: jnp.ndarray,  # [S] bool
    remaining: jnp.ndarray,  # [S] int32 tokens still allowed per slot
    no_stop_before: jnp.ndarray,  # [S] int32 (min_new_tokens countdown)
    stop_tokens: jnp.ndarray,  # [S, K] int32, -1 padded
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    top_k: jnp.ndarray,
    greedy: jnp.ndarray,
    steps: int,
    topk_bound: int = 0,
    attn_impl: str = "jnp",
    ppcb: int = 4,
    spb: int = 8,
):
    """`steps` fused decode+sample iterations in ONE dispatch with stop
    handling on device (see module doc). Host contract: tables cover
    ceil((pos0[s]+steps)/page_size) pages for every active slot.

    Returns (cache, toks [steps,S], logps [steps,S], emitted [steps,S],
    active_after [S], remaining_after, no_stop_after)."""
    return _decode_core(
        params, cfg, cache, tables, pos0, tokens, active, key,
        (temperature, top_p, top_k, greedy),
        (remaining, no_stop_before, stop_tokens),
        steps, attn_impl, ppcb, spb, topk_bound,
    )


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "attn_impl", "ppcb", "spb"),
    donate_argnames=("cache",),
)
def decode_step(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tables: jnp.ndarray,  # [S, PPS]
    pos0: jnp.ndarray,  # [S]
    tokens: jnp.ndarray,  # [S]
    active: jnp.ndarray,  # [S] bool
    attn_impl: str = "jnp",
    ppcb: int = 4,
    spb: int = 8,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Single decode step for all slots; returns (cache, logits [S, V])."""
    return _decode_core(
        params, cfg, cache, tables, pos0, tokens, active, None, None, None,
        1, attn_impl, ppcb, spb, 0,
    )


# ---------------------------------------------------------------------------
# Sampling
# ---------------------------------------------------------------------------
def _sample_impl(
    logits: jnp.ndarray,  # [S, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S] int32 (0 = disabled)
    greedy: jnp.ndarray,  # [S] bool
    topk_bound: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-slot sampling; returns (tokens [S], logprobs [S]).

    ``topk_bound`` picks the truncation strategy (static):
      -1  no truncation anywhere (all slots top_p>=1, top_k=0) — a single
          ``categorical`` over the scaled logits; no sort at all.
       0  exact full-vocab sort (argsort) — the always-correct fallback.
      K>0 ``lax.top_k(K)`` candidates, top-k/top-p masks applied within
          them — the fast serving path. Slots that request NO truncation
          (top_k=0, top_p>=1) sample full-vocab categorical instead, so
          their behavior logprob matches their true sampling distribution
          (advisor round-2 finding).

    The returned logprob is under the temperature-scaled (untruncated)
    distribution — the behavior-policy logprob the trainer consumes
    (reference ModelResponse.output_logprobs semantics). Greedy slots
    report the logprob under the *unscaled* distribution (temperature
    never enters their behavior policy).
    """
    s, v = logits.shape
    temp = jnp.maximum(temperature, 1e-5)[:, None]
    scaled = logits / temp
    logp_full = jax.nn.log_softmax(scaled, axis=-1)

    if topk_bound < 0:
        sampled = jax.random.categorical(key, scaled, axis=-1)
    elif topk_bound > 0:
        kb = min(topk_bound, v)
        vals, idx = jax.lax.top_k(scaled, kb)  # [S, kb]
        # top_p cutoffs are defined against the FULL-vocab distribution,
        # not renormalized over the kb candidates (matching the exact path)
        cand_probs = jnp.exp(jnp.take_along_axis(logp_full, idx, axis=-1))
        cumprev = jnp.cumsum(cand_probs, axis=-1) - cand_probs
        rank = jnp.arange(kb)[None, :]
        keep = jnp.where(top_k[:, None] > 0, rank < top_k[:, None], True)
        keep &= cumprev < top_p[:, None]
        keep = keep.at[:, 0].set(True)  # always keep the argmax token
        trunc = jnp.where(keep, vals, NEG_INF)
        choice = jax.random.categorical(key, trunc, axis=-1)
        truncated_pick = jnp.take_along_axis(
            idx, choice[:, None], axis=-1
        )[:, 0]
        # untruncated slots keep the exact full-vocab distribution
        untruncated = (top_k <= 0) & (top_p >= 1.0)
        full_pick = jax.random.categorical(key, scaled, axis=-1)
        sampled = jnp.where(untruncated, full_pick, truncated_pick)
    else:
        # exact path: full sort (slow; tests / host-side calls)
        sort_idx = jnp.argsort(-scaled, axis=-1)
        sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumprobs = jnp.cumsum(sorted_probs, axis=-1)
        rank = jnp.arange(v)[None, :]
        keep = jnp.ones((s, v), bool)
        keep &= jnp.where(top_k[:, None] > 0, rank < top_k[:, None], True)
        keep &= (cumprobs - sorted_probs) < top_p[:, None]
        keep = keep.at[:, 0].set(True)
        trunc_sorted = jnp.where(keep, sorted_logits, NEG_INF)
        trunc = jnp.full_like(scaled, NEG_INF).at[
            jnp.arange(s)[:, None], sort_idx
        ].set(trunc_sorted)
        sampled = jax.random.categorical(key, trunc, axis=-1)

    argmax = jnp.argmax(logits, axis=-1)
    tokens = jnp.where(greedy, argmax, sampled).astype(jnp.int32)
    # Greedy slots ignore temperature when picking the token, so report the
    # logprob under the *unscaled* distribution.
    lp_sampled = jnp.take_along_axis(
        logp_full, tokens[:, None], axis=-1
    ).squeeze(-1)
    lp_greedy = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), tokens[:, None], axis=-1
    ).squeeze(-1)
    logprobs = jnp.where(greedy, lp_greedy, lp_sampled)
    return tokens, logprobs


@jax.jit
def pack_host(*arrays) -> jnp.ndarray:
    """Flatten+concat device arrays into ONE float32 blob so the host pays
    a single fetch round-trip (over a driver tunnel each array fetch is a
    full RPC; int32 token ids are exact in f32 below 2^24)."""
    return jnp.concatenate(
        [a.reshape(-1).astype(jnp.float32) for a in arrays]
    )


@functools.partial(jax.jit, static_argnames=("topk_bound",))
def sample_tokens(
    logits: jnp.ndarray,  # [S, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S] int32 (0 = disabled)
    greedy: jnp.ndarray,  # [S] bool
    topk_bound: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    return _sample_impl(
        logits, key, temperature, top_p, top_k, greedy, topk_bound
    )
