"""Jitted prefill / decode-step programs over the slot KV cache.

The TPU-native core of the generation engine (role of SGLang's model runner
behind the reference's HTTP API). Compiled programs:

- ``prefill_batch``: N requests' prompt suffixes as ONE batched [N, Tp]
  forward — the whole admission wave is a single large matmul program
  instead of N serial prompt passes. Each row carries a per-row ``offset``:
  the number of tokens already cached in its slot (prefix reuse — the
  radix-cache analog, reference areal/engine/sglang_remote.py:158-168).
  K/V for the suffix land at [offset, offset+len) in the slot's line.
- ``decode_step``: ALL active slots advance one token in a single batched
  program — continuous batching is "the batch dim is the slot dim". K/V for
  the new token scatter into each slot's line; attention reads the cache
  line up to a static ``kv_bound`` (host-bucketed to the longest active
  sequence) under a length mask, so short sequences don't pay
  max_model_len HBM traffic.
- ``copy_slots``: duplicate cache lines across slots — GRPO's group_size
  identical prompts prefill once and fan out by an HBM copy.

All programs scan over the stacked layer params (compile once per bucket,
O(1) in depth), keep fp32 softmax/logits, and use
``preferred_element_type=f32`` einsums so bf16 stays on the MXU. Sampling
(temperature / top-k / top-p / greedy, per-slot) runs on device with a
static ``topk_bound`` (lax.top_k instead of a full-vocab sort); stop
handling is on device in ``decode_multi``, host backstopped.
"""

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from areal_tpu.models.config import ModelConfig
from areal_tpu.models.transformer import Params
from areal_tpu.ops.basic import apply_rope, rms_norm, rope_frequencies

NEG_INF = -2.3819763e38


def _project_qkv(cfg: ModelConfig, lp: Params, h: jnp.ndarray):
    """h [..., D] → q [..., Hq, Dh], k/v [..., Hkv, Dh] (pre-rope)."""
    q = h @ lp["wq"]
    k = h @ lp["wk"]
    v = h @ lp["wv"]
    if cfg.attention_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(h.shape[:-1] + (cfg.num_heads, cfg.head_dim))
    k = k.reshape(h.shape[:-1] + (cfg.num_kv_heads, cfg.head_dim))
    v = v.reshape(h.shape[:-1] + (cfg.num_kv_heads, cfg.head_dim))
    if cfg.use_qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    return q, k, v


def _mlp(
    cfg: ModelConfig,
    lp: Params,
    h: jnp.ndarray,
    valid: Optional[jnp.ndarray] = None,  # [...] matching h[..., 0]
) -> jnp.ndarray:
    if cfg.is_moe:
        from areal_tpu.ops.moe import moe_ffn_from_params

        flat = h.reshape(1, -1, h.shape[-1])
        # padding / inactive-slot tokens must not consume expert capacity
        # (their identical embeddings would all route to the same experts
        # and displace real tokens)
        vflat = None if valid is None else valid.reshape(1, -1)
        out, _ = moe_ffn_from_params(cfg, lp, flat, valid=vflat)
        return out.reshape(h.shape)
    return (jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])) @ lp["w_down"]


def _final_logits(params: Params, cfg: ModelConfig, x: jnp.ndarray):
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = (
        params["embedding"].T if cfg.tie_word_embeddings else params["lm_head"]
    )
    return x.astype(jnp.float32) @ head.astype(jnp.float32)


# ---------------------------------------------------------------------------
# Prefill (batched, prefix-aware)
# ---------------------------------------------------------------------------
def _prefill_impl(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [N, Tp] int32 suffix tokens, padded to bucket
    offsets: jnp.ndarray,  # [N] int32 tokens already cached (prefix reuse)
    true_lens: jnp.ndarray,  # [N] int32 suffix lengths (0 = padding row)
    slots: jnp.ndarray,  # [N] int32 target slot per row
    kv_bound: Optional[int],
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """One batched forward over N prompt suffixes; writes K/V into each
    row's slot at its offset; returns last-real-token logits [N, V].

    Host contract: ``offsets[i] + Tp <= kv_bound <= max_model_len`` for
    every real row, so the dynamic_update_slice never clamps.
    """
    n, tp = tokens.shape
    num_slots, m = cache["k"].shape[1], cache["k"].shape[2]
    mb = m if kv_bound is None else min(kv_bound, m)
    # padding rows scatter out-of-range → dropped
    slots = jnp.where(true_lens > 0, slots, num_slots)
    sidx = jnp.arange(tp, dtype=jnp.int32)[None, :]
    pos = offsets[:, None] + sidx  # [N, Tp] absolute positions
    valid_q = sidx < true_lens[:, None]
    cos, sin = rope_frequencies(
        cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta
    )
    x = params["embedding"][tokens]  # [N, Tp, D]
    # key j visible to row-i query at suffix index s iff j <= offset_i + s
    att_mask = jnp.arange(mb)[None, None, :] <= pos[:, :, None]  # [N, Tp, mb]
    scale = cfg.head_dim**-0.5
    g, rep = cfg.num_kv_heads, cfg.num_heads // cfg.num_kv_heads

    k_all = cache["k"][:, :, :mb]
    v_all = cache["v"][:, :, :mb]

    def upd(line, new, off):
        zero = jnp.zeros((), jnp.int32)
        return jax.lax.dynamic_update_slice(line, new, (off, zero, zero))

    def layer(x, xs):
        lp, k_lines, v_lines = xs  # lines [S, mb, Hkv, Dh]
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q, k, v = _project_qkv(cfg, lp, h)
        q = apply_rope(q, pos, cos, sin)
        k = apply_rope(k, pos, cos, sin)
        kz = jnp.where(valid_q[..., None, None], k, 0).astype(k_lines.dtype)
        vz = jnp.where(valid_q[..., None, None], v, 0).astype(v_lines.dtype)
        rows_k = jax.vmap(upd)(k_lines[slots], kz, offsets)  # [N, mb, Hkv, Dh]
        rows_v = jax.vmap(upd)(v_lines[slots], vz, offsets)
        # GQA without materializing repeated KV: queries grouped by their
        # shared kv head (head h uses group h // rep — HF layout); bf16
        # stays on the MXU, accumulation fp32
        qg = q.reshape(n, tp, g, rep, cfg.head_dim)
        scores = (
            jnp.einsum(
                "nqgrd,nkgd->ngrqk", qg, rows_k,
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        scores = jnp.where(att_mask[:, None, None], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum(
            "ngrqk,nkgd->nqgrd", probs.astype(rows_v.dtype), rows_v,
            preferred_element_type=jnp.float32,
        )
        attn = attn.astype(x.dtype).reshape(n, tp, cfg.q_dim)
        x = x + attn @ lp["wo"]
        h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + _mlp(cfg, lp, h2, valid=valid_q)
        k_lines = k_lines.at[slots].set(rows_k, mode="drop")
        v_lines = v_lines.at[slots].set(rows_v, mode="drop")
        return x, (k_lines, v_lines)

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], k_all, v_all))
    if mb < m:
        cache_k = cache["k"].at[:, :, :mb].set(new_k)
        cache_v = cache["v"].at[:, :, :mb].set(new_v)
    else:
        cache_k, cache_v = new_k, new_v
    lens = cache["lens"].at[slots].set(offsets + true_lens, mode="drop")
    last = x[jnp.arange(n), jnp.maximum(true_lens - 1, 0)]  # [N, D]
    logits = _final_logits(params, cfg, last)  # [N, V] fp32
    return {"k": cache_k, "v": cache_v, "lens": lens}, logits


@functools.partial(
    jax.jit, static_argnames=("cfg", "kv_bound"), donate_argnames=("cache",)
)
def prefill_batch(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [N, Tp]
    offsets: jnp.ndarray,  # [N]
    true_lens: jnp.ndarray,  # [N]
    slots: jnp.ndarray,  # [N]
    kv_bound: Optional[int] = None,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Prefill N prompt suffixes in ONE batched dispatch (see module doc)."""
    return _prefill_impl(
        params, cfg, cache, tokens, offsets, true_lens, slots, kv_bound
    )


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def prefill(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [Tp] int32, padded to bucket
    true_len: jnp.ndarray,  # scalar int32
    slot: jnp.ndarray,  # scalar int32
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Single-request prefill (batch of one; kept for tests/simple callers)."""
    cache, logits = _prefill_impl(
        params,
        cfg,
        cache,
        tokens[None],
        jnp.zeros((1,), jnp.int32),
        true_len[None],
        slot[None],
        None,
    )
    return cache, logits[0]


@functools.partial(jax.jit, donate_argnames=("cache",))
def copy_slots(
    cache: Dict[str, jnp.ndarray],
    src: jnp.ndarray,  # [P] int32 source slot per copy
    dst: jnp.ndarray,  # [P] int32 destination (>= num_slots rows are dropped)
) -> Dict[str, jnp.ndarray]:
    """Duplicate cache lines src→dst (GRPO sibling fan-out after one
    shared prompt prefill). Padding rows use dst >= num_slots."""
    k = cache["k"].at[:, dst].set(cache["k"][:, src], mode="drop")
    v = cache["v"].at[:, dst].set(cache["v"][:, src], mode="drop")
    lens = cache["lens"].at[dst].set(cache["lens"][src], mode="drop")
    return {"k": k, "v": v, "lens": lens}


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------
def _decode_impl(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [S] int32: current input token per slot
    active: jnp.ndarray,  # [S] bool
    kv_bound: Optional[int],
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """All slots advance one position; returns logits [S, V] (fp32).

    Attention reads only the first ``kv_bound`` cache positions (host
    guarantees every active length + 1 fits inside it).
    """
    s, m = cache["k"].shape[1], cache["k"].shape[2]
    mb = m if kv_bound is None else min(kv_bound, m)
    positions = cache["lens"]  # [S] next position per slot
    cos, sin = rope_frequencies(
        cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta
    )
    x = params["embedding"][tokens]  # [S, D]
    att_mask = jnp.arange(mb)[None, :] <= positions[:, None]  # [S, mb]
    scale = cfg.head_dim**-0.5
    g, rep = cfg.num_kv_heads, cfg.num_heads // cfg.num_kv_heads

    def layer(carry, xs):
        x = carry  # [S, D]
        lp, k_l, v_l = xs  # cache line [S, mb, Hkv, Dh]
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q, k, v = _project_qkv(cfg, lp, h)  # q [S, Hq, Dh], k/v [S, Hkv, Dh]
        q = apply_rope(q[:, None], positions[:, None], cos, sin)[:, 0]
        k = apply_rope(k[:, None], positions[:, None], cos, sin)[:, 0]
        # scatter new k/v at each ACTIVE slot's position; inactive slots'
        # lines (possibly freed-but-reusable prefixes longer than this
        # dispatch's kv_bound) must not be touched — dynamic_update_slice
        # clamps out-of-range starts, which would corrupt position mb-1
        k_l = _scatter_token(k_l, k, positions, active)
        v_l = _scatter_token(v_l, v, positions, active)
        # GQA without materializing repeated KV (the decode step is HBM
        # bound on exactly these cache-line reads)
        qg = q.reshape(s, g, rep, cfg.head_dim)
        scores = (
            jnp.einsum(
                "sgrd,smgd->sgrm", qg, k_l,
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        scores = jnp.where(att_mask[:, None, None, :], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum(
            "sgrm,smgd->sgrd", probs.astype(v_l.dtype), v_l,
            preferred_element_type=jnp.float32,
        )
        attn = attn.astype(x.dtype).reshape(s, cfg.q_dim)
        x = x + attn @ lp["wo"]
        h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + _mlp(cfg, lp, h2, valid=active)
        return x, (k_l, v_l)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], cache["k"][:, :, :mb], cache["v"][:, :, :mb])
    )
    logits = _final_logits(params, cfg, x)  # [S, V]
    lens = jnp.where(active, positions + 1, positions)
    if mb < m:
        cache_k = cache["k"].at[:, :, :mb].set(new_k)
        cache_v = cache["v"].at[:, :, :mb].set(new_v)
    else:
        cache_k, cache_v = new_k, new_v
    return {"k": cache_k, "v": cache_v, "lens": lens}, logits


@functools.partial(
    jax.jit, static_argnames=("cfg", "kv_bound"), donate_argnames=("cache",)
)
def decode_step(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [S] int32
    active: jnp.ndarray,  # [S] bool
    kv_bound: Optional[int] = None,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    return _decode_impl(params, cfg, cache, tokens, active, kv_bound)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "steps", "kv_bound", "topk_bound"),
    donate_argnames=("cache",),
)
def decode_multi(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [S] current input token per slot
    active: jnp.ndarray,  # [S] bool
    remaining: jnp.ndarray,  # [S] int32 tokens still allowed per slot
    no_stop_before: jnp.ndarray,  # [S] int32 (min_new_tokens countdown)
    stop_tokens: jnp.ndarray,  # [S, K] int32, -1 padded
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    top_k: jnp.ndarray,
    greedy: jnp.ndarray,
    steps: int,
    kv_bound: Optional[int] = None,
    topk_bound: int = 0,
):
    """`steps` fused decode+sample iterations in ONE dispatch, with stop
    handling on device — the host round-trip (which dominates serving
    latency, especially over a driver link) is amortized over `steps`
    tokens. A slot deactivates in-device when it emits a stop token (past
    its min_new_tokens window) or exhausts its budget; inactive slots stop
    advancing their cache line.

    The big KV cache is READ-ONLY inside the step loop — mutating a
    multi-hundred-MB loop carry costs a full copy per step on TPU. New
    tokens' K/V accumulate in a small ``[L, S, steps]`` chunk buffer;
    attention covers the (bounded) cached window plus the chunk window;
    one bulk scatter merges the chunk into the cache at the end.

    Host contract: ``max(lens) <= kv_bound`` (the chunk window carries the
    in-flight tokens, so the bound needn't cover ``+ steps``).

    Returns (cache, toks [steps,S], logps [steps,S], emitted [steps,S] bool,
    active_after [S], remaining_after, no_stop_after).
    """
    s, m = cache["k"].shape[1], cache["k"].shape[2]
    mb = m if kv_bound is None else min(kv_bound, m)
    g, rep = cfg.num_kv_heads, cfg.num_heads // cfg.num_kv_heads
    nl = cfg.num_layers
    pos0 = cache["lens"]  # [S] cached tokens per slot (fixed this chunk)
    cos, sin = rope_frequencies(
        cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta
    )
    srange = jnp.arange(s)
    cache_mask = jnp.arange(mb)[None, :] < pos0[:, None]  # [S, mb] static
    k_ro = cache["k"][:, :, :mb]  # read-only views
    v_ro = cache["v"][:, :, :mb]
    scale = cfg.head_dim**-0.5

    def step(carry, step_key):
        kbuf, vbuf, tokens, clen, active, remaining, no_stop = carry
        x = params["embedding"][tokens]  # [S, D]
        pos = pos0 + clen

        def layer(xc, xs):
            x, kbuf, vbuf = xc
            lp, li = xs
            h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
            q, k, v = _project_qkv(cfg, lp, h)
            q = apply_rope(q[:, None], pos[:, None], cos, sin)[:, 0]
            k = apply_rope(k[:, None], pos[:, None], cos, sin)[:, 0]
            # new token K/V → chunk buffer (inactive slots drop)
            ci = jnp.where(active, clen, steps)
            kbuf = kbuf.at[li, srange, ci].set(
                k.astype(kbuf.dtype), mode="drop"
            )
            vbuf = vbuf.at[li, srange, ci].set(
                v.astype(vbuf.dtype), mode="drop"
            )
            k_l = jax.lax.dynamic_index_in_dim(k_ro, li, 0, keepdims=False)
            v_l = jax.lax.dynamic_index_in_dim(v_ro, li, 0, keepdims=False)
            kb = jax.lax.dynamic_index_in_dim(kbuf, li, 0, keepdims=False)
            vb = jax.lax.dynamic_index_in_dim(vbuf, li, 0, keepdims=False)
            # GQA grouped attention over cached ++ chunk windows
            qg = q.reshape(s, g, rep, cfg.head_dim)
            sc = (
                jnp.einsum(
                    "sgrd,smgd->sgrm", qg, k_l,
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            sc = jnp.where(cache_mask[:, None, None, :], sc, NEG_INF)
            sb = (
                jnp.einsum(
                    "sgrd,stgd->sgrt", qg, kb,
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            chunk_mask = jnp.arange(steps)[None, :] <= clen[:, None]
            sb = jnp.where(chunk_mask[:, None, None, :], sb, NEG_INF)
            probs = jax.nn.softmax(
                jnp.concatenate([sc, sb], axis=-1), axis=-1
            )
            pc, pb = probs[..., :mb], probs[..., mb:]
            attn = jnp.einsum(
                "sgrm,smgd->sgrd", pc.astype(v_l.dtype), v_l,
                preferred_element_type=jnp.float32,
            ) + jnp.einsum(
                "sgrt,stgd->sgrd", pb.astype(vb.dtype), vb,
                preferred_element_type=jnp.float32,
            )
            x = x + attn.astype(x.dtype).reshape(s, cfg.q_dim) @ lp["wo"]
            h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
            x = x + _mlp(cfg, lp, h2, valid=active)
            return (x, kbuf, vbuf), None

        (x, kbuf, vbuf), _ = jax.lax.scan(
            layer, (x, kbuf, vbuf), (params["layers"], jnp.arange(nl))
        )
        logits = _final_logits(params, cfg, x)
        toks, logps = _sample_impl(
            logits, step_key, temperature, top_p, top_k, greedy, topk_bound
        )
        emitted = active
        # a stop token may end the slot once it would have emitted
        # >= min_new_tokens INCLUDING this one (no_stop holds min - emitted)
        hit_stop = jnp.any(
            toks[:, None] == stop_tokens, axis=1
        ) & (no_stop <= 1)
        clen = clen + active
        remaining = jnp.where(active, remaining - 1, remaining)
        no_stop = jnp.where(active, no_stop - 1, no_stop)
        active = active & ~hit_stop & (remaining > 0)
        return (kbuf, vbuf, toks, clen, active, remaining, no_stop), (
            toks, logps, emitted,
        )

    kbuf0 = jnp.zeros(
        (nl, s, steps, g, cfg.head_dim), cache["k"].dtype
    )
    vbuf0 = jnp.zeros_like(kbuf0)
    keys = jax.random.split(key, steps)
    (kbuf, vbuf, tokens, clen, active, remaining, no_stop), (
        toks, logps, emitted,
    ) = jax.lax.scan(
        step,
        (kbuf0, vbuf0, tokens, jnp.zeros(s, jnp.int32), active,
         remaining, no_stop_before),
        keys,
    )
    # bulk merge: chunk buffer → cache at absolute positions (one scatter)
    tgrid = jnp.arange(steps)[None, :]
    tgt = jnp.where(tgrid < clen[:, None], pos0[:, None] + tgrid, m)  # [S, T]
    cache_k = cache["k"].at[:, srange[:, None], tgt].set(kbuf, mode="drop")
    cache_v = cache["v"].at[:, srange[:, None], tgt].set(vbuf, mode="drop")
    lens = pos0 + clen
    cache = {"k": cache_k, "v": cache_v, "lens": lens}
    return cache, toks, logps, emitted, active, remaining, no_stop


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "kv_bound", "topk_bound"),
    donate_argnames=("cache",),
)
def decode_and_sample(
    params: Params,
    cfg: ModelConfig,
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [S]
    active: jnp.ndarray,  # [S] bool
    key: jax.Array,
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S]
    greedy: jnp.ndarray,  # [S] bool
    kv_bound: Optional[int] = None,
    topk_bound: int = 0,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Fused decode step + sampling: ONE dispatch and one host fetch per
    generation step (the per-step host round-trip is the latency floor of the
    serving loop, so everything between two steps stays on device)."""
    cache, logits = _decode_impl(params, cfg, cache, tokens, active, kv_bound)
    toks, logps = _sample_impl(
        logits, key, temperature, top_p, top_k, greedy, topk_bound
    )
    return cache, toks, logps


def _scatter_token(
    cache_line: jnp.ndarray,  # [S, M, Hkv, D]
    new: jnp.ndarray,  # [S, Hkv, D]
    positions: jnp.ndarray,  # [S]
    active: jnp.ndarray,  # [S] bool — inactive rows are left untouched
) -> jnp.ndarray:
    new = new.astype(cache_line.dtype)

    def one(line, tok, pos, act):
        zero = jnp.zeros((), jnp.int32)
        cur = jax.lax.dynamic_slice(
            line, (pos, zero, zero), (1,) + line.shape[1:]
        )
        tok = jnp.where(act, tok[None], cur)
        return jax.lax.dynamic_update_slice(line, tok, (pos, zero, zero))

    return jax.vmap(one)(cache_line, new, positions, active)


# ---------------------------------------------------------------------------
# Sampling
# ---------------------------------------------------------------------------
def _sample_impl(
    logits: jnp.ndarray,  # [S, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S] int32 (0 = disabled)
    greedy: jnp.ndarray,  # [S] bool
    topk_bound: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-slot sampling; returns (tokens [S], logprobs [S]).

    ``topk_bound`` picks the truncation strategy (static):
      -1  no truncation anywhere (all slots top_p>=1, top_k=0) — a single
          ``categorical`` over the scaled logits; no sort at all.
       0  exact full-vocab sort (argsort) — the always-correct fallback.
      K>0 ``lax.top_k(K)`` candidates, top-k/top-p masks applied within
          them — the fast serving path (host picks K >= every slot's
          top_k; top_p truncation beyond K candidates is approximated,
          standard practice on accelerator serving stacks).

    The returned logprob is under the temperature-scaled (untruncated)
    distribution — the behavior-policy logprob the trainer consumes
    (reference ModelResponse.output_logprobs semantics). Greedy slots are
    the exception: they pick argmax over the raw logits, so their logprob
    is reported under the *unscaled* distribution (temperature never enters
    their behavior policy).
    """
    s, v = logits.shape
    temp = jnp.maximum(temperature, 1e-5)[:, None]
    scaled = logits / temp
    logp_full = jax.nn.log_softmax(scaled, axis=-1)

    if topk_bound < 0:
        sampled = jax.random.categorical(key, scaled, axis=-1)
    elif topk_bound > 0:
        kb = min(topk_bound, v)
        vals, idx = jax.lax.top_k(scaled, kb)  # [S, kb]
        # top_p cutoffs are defined against the FULL-vocab distribution, not
        # renormalized over the kb candidates (matching the exact path)
        cand_probs = jnp.exp(jnp.take_along_axis(logp_full, idx, axis=-1))
        cumprev = jnp.cumsum(cand_probs, axis=-1) - cand_probs
        rank = jnp.arange(kb)[None, :]
        keep = jnp.where(top_k[:, None] > 0, rank < top_k[:, None], True)
        keep &= cumprev < top_p[:, None]
        keep = keep.at[:, 0].set(True)  # always keep the argmax token
        trunc = jnp.where(keep, vals, NEG_INF)
        choice = jax.random.categorical(key, trunc, axis=-1)
        sampled = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
    else:
        # exact path: full sort (slow; tests / host-side calls)
        sort_idx = jnp.argsort(-scaled, axis=-1)
        sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumprobs = jnp.cumsum(sorted_probs, axis=-1)
        rank = jnp.arange(v)[None, :]
        keep = jnp.ones((s, v), bool)
        keep &= jnp.where(top_k[:, None] > 0, rank < top_k[:, None], True)
        # keep tokens while cumulative prob (exclusive) < top_p
        keep &= (cumprobs - sorted_probs) < top_p[:, None]
        keep = keep.at[:, 0].set(True)  # always keep the argmax token
        trunc_sorted = jnp.where(keep, sorted_logits, NEG_INF)
        trunc = jnp.full_like(scaled, NEG_INF).at[
            jnp.arange(s)[:, None], sort_idx
        ].set(trunc_sorted)
        sampled = jax.random.categorical(key, trunc, axis=-1)

    argmax = jnp.argmax(logits, axis=-1)
    tokens = jnp.where(greedy, argmax, sampled).astype(jnp.int32)
    # Greedy slots ignore temperature when picking the token, so report the
    # logprob under the *unscaled* distribution — mixing argmax(logits) with
    # the temperature-scaled softmax would hand the trainer importance
    # ratios from a distribution that was never sampled.
    lp_sampled = jnp.take_along_axis(
        logp_full, tokens[:, None], axis=-1
    ).squeeze(-1)
    lp_greedy = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), tokens[:, None], axis=-1
    ).squeeze(-1)
    logprobs = jnp.where(greedy, lp_greedy, lp_sampled)
    return tokens, logprobs


@jax.jit
def pack_host(*arrays) -> jnp.ndarray:
    """Flatten+concat device arrays into ONE float32 blob so the host pays
    a single fetch round-trip (over a driver tunnel each array fetch is a
    full RPC; int32 token ids are exact in f32 below 2^24)."""
    return jnp.concatenate(
        [a.reshape(-1).astype(jnp.float32) for a in arrays]
    )


@functools.partial(jax.jit, static_argnames=("topk_bound",))
def sample_tokens(
    logits: jnp.ndarray,  # [S, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S] int32 (0 = disabled)
    greedy: jnp.ndarray,  # [S] bool
    topk_bound: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    return _sample_impl(
        logits, key, temperature, top_p, top_k, greedy, topk_bound
    )
