"""Multi-policy serving plane: the server-side `PolicyRegistry`.

Role: generalize the r13 `WeightStore` — one linear version history per
engine — to N NAMED policy handles, each with its own version line,
pins, and cohort dispatches, so one engine serves e.g. ``actor@v12``
(90%), ``actor@v13`` (10% canary), and ``opponent@v7`` concurrently.

The handle contract (stringly-typed, stamped end to end —
workflow metadata → ``remote.agenerate`` → router schedule → /generate
payload → engine admission):

- ``""`` / absent      — the DEFAULT line: the engine's own
  ``self.params`` / ``self.model_version`` served exactly as before
  this subsystem existed. The registry never touches it; with no named
  line registered the whole plane is a strict no-op (bit-identical
  greedy streams, zero new metric keys).
- ``"name"``           — the named line's deterministic stable/canary
  split (below); with no canary staged, its stable version.
- ``"name@stable"``    — the stable version explicitly.
- ``"name@canary"``    — the canary version (error if none staged).
- ``"name@v<N>"``      — version N exactly (error if N is not live).

An unknown name (or a dead version selector) raises
:class:`UnknownPolicyError` — typed, carrying ``status=400`` so the
server answers a 4xx that ``utils/http.py``'s 5xx-only retry policy
propagates immediately instead of hammering a request that can never
succeed.

Line lifecycle: ``push`` (register-on-first-push; replaces stable, or
stages a canary when a split fraction rides along) → ``promote`` (the
canary becomes stable — pure registry state, no buffer movement, no
pause span; the canary's per-(policy, version) KV namespace stays valid
because the version int didn't change) → ``retire`` (drop the line;
refused while any request pins one of its buffers).

Canary split: a per-line DETERMINISTIC error accumulator
(``err += fraction; err >= 1 → canary, err -= 1``) rather than RNG —
a 90/10 split lands within one request of exact over any window, which
is what the ±3%-over-200-requests acceptance gate measures. The router
runs the same accumulator fleet-side; the engine's copy covers
direct-to-server callers and keeps single-server tests deterministic.

HBM pressure: cold named buffers demote to host RAM (the r16 spill
pattern applied to parameter pytrees) past ``max_resident`` resident
named buffers, LRU, and reload on the next request that resolves to
them. A pinned buffer — any in-flight request decoding on it — is
never demotable, so eviction of an in-use buffer is impossible by
construction, not by timing.

Like `WeightStore`, the registry is deliberately engine-agnostic: it
never imports jax. The engine supplies ``to_host(tree)`` /
``to_device(tree)`` callables (and `WeightStore`'s ``place_leaf`` for
chunked ingest), so the registry unit-tests without a device.

NOTE: the /metrics surface (policy_lines, policy_buffers_resident,
policy_buffers_host, policy_demotions_total, policy_reloads_total,
policy_pinned_requests, policy_pushes_total, policy_promotes_total and
the per-policy ``policy_*{policy="..."}`` families) lives INLINE in
``GenerationEngine.metrics()`` — the arealint ARL003 static scan
extracts names from that literal, same as the WeightStore counters.
"""

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from areal_tpu.inference.weights import WeightStore
from areal_tpu.utils import logging as logging_util

logger = logging_util.getLogger("PolicyRegistry")


class UnknownPolicyError(Exception):
    """A request named a policy handle this server does not serve.

    Carries ``status = 400``: the request itself is wrong (a typo, a
    retired line, a dead pinned version) — retrying it verbatim can
    never succeed, so the server must answer a 4xx that the client's
    5xx-only retry policy (utils/http.py) propagates immediately. A
    500 here would burn the whole retry budget per request and then
    surface as a server-health failure, poisoning failover decisions
    for a client-side mistake."""

    status = 400

    def __init__(self, handle: str, reason: str = "unknown policy"):
        self.handle = handle
        self.reason = reason
        super().__init__(f"{reason}: {handle!r}")


def parse_handle(handle: str) -> Tuple[str, Optional[Any]]:
    """``handle`` → ``(name, selector)`` where selector is None (split),
    ``"stable"``, ``"canary"``, or an int version. Grammar errors raise
    :class:`UnknownPolicyError` (they are client mistakes, 4xx)."""
    handle = str(handle)
    if "@" not in handle:
        if not handle:
            raise UnknownPolicyError(handle, "empty policy handle")
        return handle, None
    name, _, sel = handle.partition("@")
    if not name or not sel:
        raise UnknownPolicyError(handle, "malformed policy handle")
    if sel in ("stable", "canary"):
        return name, sel
    if sel.startswith("v") and sel[1:].isdigit():
        return name, int(sel[1:])
    raise UnknownPolicyError(
        handle, "bad version selector (want @stable, @canary, or @v<N>)"
    )


class _PolicyLine:
    """One named policy's version line: stable (+ optional canary)
    buffers, per-version pins, chunked-push staging, split state."""

    __slots__ = (
        "name", "stable_version", "canary_version", "canary_fraction",
        "split_err", "buffers", "host_buffers", "pins", "last_used",
        "staging", "requests_total", "tokens_total",
    )

    def __init__(self, name: str, staging_ttl_s: float):
        self.name = name
        self.stable_version = 0
        self.canary_version: Optional[int] = None
        self.canary_fraction = 0.0
        self.split_err = 0.0
        # version -> device params (resident) / host params (demoted).
        # A version lives in exactly one of the two maps.
        self.buffers: Dict[int, Any] = {}
        self.host_buffers: Dict[int, Any] = {}
        self.pins: Dict[int, int] = {}
        self.last_used = 0.0
        # chunked streamed pushes reuse the WeightStore staging machinery
        # (re-key on (version, n_chunks), TTL sweep, staging gauges)
        self.staging = WeightStore(staging_ttl_s=staging_ttl_s)
        self.requests_total = 0
        self.tokens_total = 0

    def live_versions(self) -> List[int]:
        out = [self.stable_version]
        if self.canary_version is not None:
            out.append(self.canary_version)
        return out


class PolicyRegistry:
    """Named policy lines for one generation engine. Thread-safe:
    pushes/ingest run on HTTP handler threads, resolution runs on the
    submit (caller) thread, pins/params lookups run on the engine loop
    thread. ``active`` is a lock-free hot-loop gate — False until the
    first line registers, so the single-policy engine loop pays one
    attribute read and nothing else."""

    def __init__(
        self,
        to_host: Optional[Callable[[Any], Any]] = None,
        to_device: Optional[Callable[[Any], Any]] = None,
        max_resident: int = 0,
        staging_ttl_s: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._to_host = to_host
        self._to_device = to_device
        self.max_resident = int(max_resident)
        self.staging_ttl_s = float(staging_ttl_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._lines: Dict[str, _PolicyLine] = {}
        # (name, version) pairs whose KV namespaces became garbage (a
        # push superseded the version, or the line retired); the engine
        # loop drains this and flushes each namespace — namespace maps
        # are loop-owned, so the registry only signals.
        self._retired: List[Tuple[str, int]] = []
        self.active = False  # lock-free: engine hot-loop gate
        # lifetime counters (engine /metrics surface, inline literal)
        self.pushes_total = 0
        self.promotes_total = 0
        self.demotions_total = 0
        self.reloads_total = 0

    # ------------------------------------------------------------------
    # Lifecycle: push / promote / retire (HTTP handler threads)
    # ------------------------------------------------------------------
    def push(
        self,
        name: str,
        params: Any,
        version: Optional[int] = None,
        canary_fraction: float = 0.0,
    ) -> int:
        """Install a buffer on line ``name`` (registering the line on
        first push). With ``canary_fraction > 0`` the buffer becomes the
        line's CANARY at that split fraction; otherwise it replaces
        stable outright. Returns the installed version. Superseded
        unpinned buffers drop immediately (pinned ones drain with their
        last release); their KV namespaces queue for the engine's flush
        sweep either way."""
        if not name:
            raise ValueError("policy name must be non-empty")
        if not (0.0 <= canary_fraction < 1.0):
            raise ValueError(
                f"canary_fraction must be in [0, 1), got {canary_fraction}"
            )
        with self._lock:
            line = self._lines.get(name)
            if line is None:
                line = _PolicyLine(name, self.staging_ttl_s)
                self._lines[name] = line
                self.active = True
                fresh = True
            else:
                fresh = False
            if version is None:
                version = max(line.live_versions()) + 1 if not fresh else 1
            version = int(version)
            if not fresh and version in line.live_versions():
                raise ValueError(
                    f"policy {name!r} already serves v{version}"
                )
            line.buffers[version] = params
            if canary_fraction > 0.0 and not fresh:
                old_canary = line.canary_version
                line.canary_version = version
                line.canary_fraction = float(canary_fraction)
                line.split_err = 0.0
                if old_canary is not None:
                    self._drop_version_locked(line, old_canary)
            else:
                old_stable = None if fresh else line.stable_version
                line.stable_version = version
                if canary_fraction > 0.0:
                    # first push with a fraction: nothing to split
                    # against yet — the buffer IS the line
                    logger.warning(
                        f"policy {name!r}: canary_fraction on the first "
                        f"push ignored (no stable to split against)"
                    )
                if old_stable is not None:
                    self._drop_version_locked(line, old_stable)
            line.last_used = self._clock()
            self.pushes_total += 1
            self._maybe_demote_locked(keep=(name, version))
            logger.info(
                f"policy {name!r} ← v{version}"
                + (
                    f" (canary, split {canary_fraction:.2%})"
                    if canary_fraction > 0.0 and not fresh
                    else " (stable)"
                )
            )
            return version

    def promote(self, name: str) -> int:
        """Canary → stable. Pure registry state: no buffer moves, no
        pause span, and the canary's (policy, version) KV namespace
        stays valid because the version int is unchanged — promote is
        zero-cost for in-flight and cached work alike."""
        with self._lock:
            line = self._line_locked(name)
            if line.canary_version is None:
                raise UnknownPolicyError(
                    f"{name}@canary", "no canary staged to promote"
                )
            old_stable = line.stable_version
            line.stable_version = line.canary_version
            line.canary_version = None
            line.canary_fraction = 0.0
            line.split_err = 0.0
            self._drop_version_locked(line, old_stable)
            self.promotes_total += 1
            logger.info(
                f"policy {name!r}: promoted v{line.stable_version} "
                f"(was v{old_stable})"
            )
            return line.stable_version

    def retire(self, name: str) -> None:
        """Drop a line entirely. Refused while any request pins one of
        its buffers — retiring mid-decode would dispatch a cohort
        against a freed buffer."""
        with self._lock:
            line = self._line_locked(name)
            pinned = sum(line.pins.values())
            if pinned:
                raise RuntimeError(
                    f"policy {name!r} has {pinned} pinned request(s); "
                    f"drain before retiring"
                )
            line.staging.close()
            for v in list(line.buffers) + list(line.host_buffers):
                self._retired.append((name, v))
            self._lines.pop(name)
            self.active = bool(self._lines)
            logger.info(f"policy {name!r} retired")

    def set_split(self, name: str, canary_fraction: float) -> None:
        """Adjust a staged canary's traffic fraction in place."""
        if not (0.0 <= canary_fraction < 1.0):
            raise ValueError(
                f"canary_fraction must be in [0, 1), got {canary_fraction}"
            )
        with self._lock:
            line = self._line_locked(name)
            if line.canary_version is None:
                raise UnknownPolicyError(
                    f"{name}@canary", "no canary staged to split"
                )
            line.canary_fraction = float(canary_fraction)
            line.split_err = 0.0

    # ------------------------------------------------------------------
    # Chunked streamed push (HTTP handler threads)
    # ------------------------------------------------------------------
    def ingest_chunk(
        self,
        name: str,
        header: Dict[str, Any],
        arrays: Dict[str, Any],
        place_leaf: Callable[[str, Any], Any],
    ) -> Optional[int]:
        """Stage one FFD chunk for line ``name`` (registering the line
        lazily at completion). Returns the installed version when this
        chunk completes the set, else None. The final chunk's header may
        carry ``canary_fraction``."""
        with self._lock:
            line = self._lines.get(name)
            if line is None:
                # stage into a provisional line so parallel pushes to
                # different new names don't share a staging buffer
                line = _PolicyLine(name, self.staging_ttl_s)
                self._lines[name] = line
                self.active = True
                line.stable_version = -1  # marks "no buffer yet"
            staging = line.staging
        done = staging.ingest_chunk(header, arrays, place_leaf)
        if done is None:
            return None
        version, tree = done
        with self._lock:
            if line.stable_version == -1:
                # first completed push registers the line proper
                line.stable_version = int(version)
                line.buffers[int(version)] = tree
                line.last_used = self._clock()
                self.pushes_total += 1
                self._maybe_demote_locked(keep=(name, int(version)))
                logger.info(f"policy {name!r} ← v{version} (stable)")
                return int(version)
        return self.push(
            name, tree, version=int(version),
            canary_fraction=float(header.get("canary_fraction", 0.0)),
        )

    def sweep(self) -> None:
        """Per-line staging TTL sweep (abandoned streamed pushes)."""
        with self._lock:
            lines = list(self._lines.values())
        for line in lines:
            line.staging.sweep()

    # ------------------------------------------------------------------
    # Resolution (submit/caller threads) + admission helpers (loop)
    # ------------------------------------------------------------------
    def resolve(self, handle: str) -> Tuple[str, int]:
        """``handle`` → ``(name, version)``. A bare name runs the
        deterministic stable/canary split — mutating split state, so
        call this exactly ONCE per request (at submit). Raises
        :class:`UnknownPolicyError` for unknown names and dead
        selectors."""
        name, sel = parse_handle(handle)
        with self._lock:
            line = self._line_locked(name, handle=handle)
            if sel is None:
                if line.canary_version is None or line.canary_fraction <= 0:
                    return name, line.stable_version
                line.split_err += line.canary_fraction
                if line.split_err >= 1.0:
                    line.split_err -= 1.0
                    return name, line.canary_version
                return name, line.stable_version
            if sel == "stable":
                return name, line.stable_version
            if sel == "canary":
                if line.canary_version is None:
                    raise UnknownPolicyError(handle, "no canary staged")
                return name, line.canary_version
            if sel in line.buffers or sel in line.host_buffers:
                return name, int(sel)
            raise UnknownPolicyError(handle, "version not live")

    def effective_version(self, name: str, version: int) -> int:
        """The version a request resolved at submit, unless a push
        dropped that buffer while it queued — then the line's CURRENT
        stable (re-resolving keeps long-queued requests serveable; the
        per-token version stamps stay exact because admission stamps
        the effective version). Read-only: never advances split state."""
        with self._lock:
            line = self._lines.get(name)
            if line is None:
                raise UnknownPolicyError(name, "policy retired while queued")
            if version in line.buffers or version in line.host_buffers:
                return int(version)
            return line.stable_version

    def is_live(self, name: str, version: int) -> bool:
        """True while (name, version) still serves — the park-at-finish
        gate: a finished request's pages only enter the (policy,
        version) namespace while future claimants can exist."""
        with self._lock:
            line = self._lines.get(name)
            return line is not None and version in line.live_versions()

    # ------------------------------------------------------------------
    # Buffers + pins (engine loop thread)
    # ------------------------------------------------------------------
    def params_for(self, name: str, version: int) -> Any:
        """The buffer for (name, version), reloading a host-demoted one
        onto the device first. Raises if the pair died — the caller
        (admission/dispatch) must never run a cohort on the wrong
        weights silently."""
        with self._lock:
            line = self._lines.get(name)
            if line is None:
                raise UnknownPolicyError(name, "policy retired")
            line.last_used = self._clock()
            params = line.buffers.get(version)
            if params is not None:
                return params
            host = line.host_buffers.pop(version, None)
            if host is None:
                raise UnknownPolicyError(
                    f"{name}@v{version}", "version not live"
                )
            if self._to_device is None:
                params = host
            else:
                t0 = self._clock()
                params = self._to_device(host)
                logger.info(
                    f"policy {name!r} v{version}: reloaded from host RAM "
                    f"({(self._clock() - t0) * 1e3:.1f} ms)"
                )
            line.buffers[version] = params
            self.reloads_total += 1
            self._maybe_demote_locked(keep=(name, version))
            return params

    def retain(self, name: str, version: int) -> None:
        """One in-flight request decodes on (name, version): its buffer
        becomes undemotable (and undropppable) until the pin releases."""
        with self._lock:
            line = self._lines.get(name)
            if line is None:  # pragma: no cover - retire refuses pins
                raise UnknownPolicyError(name, "policy retired")
            line.pins[version] = line.pins.get(version, 0) + 1
            line.requests_total += 1

    def release(self, name: str, version: int) -> None:
        with self._lock:
            line = self._lines.get(name)
            if line is None:
                return  # line retired after the pin drained (shutdown)
            n = line.pins.get(version, 0) - 1
            if n > 0:
                line.pins[version] = n
                return
            line.pins.pop(version, None)
            if version not in line.live_versions():
                # a superseded buffer just drained its last pin
                self._drop_version_locked(line, version)

    def note_tokens(self, name: str, n: int) -> None:
        with self._lock:
            line = self._lines.get(name)
            if line is not None:
                line.tokens_total += n

    def pinned_requests(self) -> int:
        with self._lock:
            return sum(
                sum(line.pins.values()) for line in self._lines.values()
            )

    # ------------------------------------------------------------------
    # LRU host demotion (the PR 16 spill pattern, applied to params)
    # ------------------------------------------------------------------
    def _resident_named_locked(self) -> List[Tuple[float, str, int]]:
        out = []
        for line in self._lines.values():
            for v in line.buffers:
                out.append((line.last_used, line.name, v))
        return sorted(out)

    def _maybe_demote_locked(self, keep: Tuple[str, int]) -> None:
        """Demote cold unpinned named buffers to host RAM past the
        ``max_resident`` device budget, LRU by line. ``keep`` (the
        buffer just installed/used) and every pinned buffer are exempt
        — eviction of an in-use buffer is impossible, not just
        unlikely."""
        if self.max_resident <= 0 or self._to_host is None:
            return
        resident = self._resident_named_locked()
        excess = len(resident) - self.max_resident
        for _, name, v in resident:
            if excess <= 0:
                break
            if (name, v) == keep:
                continue
            line = self._lines[name]
            if line.pins.get(v, 0) > 0:
                continue
            params = line.buffers.pop(v)
            line.host_buffers[v] = self._to_host(params)
            self.demotions_total += 1
            excess -= 1
            logger.info(
                f"policy {name!r} v{v}: demoted to host RAM "
                f"(LRU, {self.max_resident} resident buffers kept)"
            )

    # ------------------------------------------------------------------
    # Retired-namespace drain (engine loop thread)
    # ------------------------------------------------------------------
    def _drop_version_locked(self, line: _PolicyLine, version: int) -> None:
        if line.pins.get(version, 0) > 0:
            # pinned: the buffer drains with its last release(); only
            # the KV namespace retires now (no future claimants)
            self._retired.append((line.name, version))
            return
        line.buffers.pop(version, None)
        line.host_buffers.pop(version, None)
        self._retired.append((line.name, version))

    @property
    def dirty(self) -> bool:
        return bool(self._retired)

    def drain_retired(self) -> List[Tuple[str, int]]:
        """(name, version) pairs whose KV namespaces must flush — the
        engine loop owns the namespace map, so it consumes this."""
        with self._lock:
            out, self._retired = self._retired, []
            return out

    # ------------------------------------------------------------------
    # Introspection (metrics/endpoints)
    # ------------------------------------------------------------------
    def _line_locked(
        self, name: str, handle: Optional[str] = None
    ) -> _PolicyLine:
        line = self._lines.get(name)
        if line is None or line.stable_version < 0:
            raise UnknownPolicyError(handle or name)
        return line

    def names(self) -> List[str]:
        with self._lock:
            return sorted(
                n for n, l in self._lines.items() if l.stable_version >= 0
            )

    def staging_bytes(self) -> int:
        with self._lock:
            lines = list(self._lines.values())
        return sum(line.staging.staging_bytes for line in lines)

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-line snapshot for /metrics families and GET /policy."""
        with self._lock:
            out: Dict[str, Dict[str, Any]] = {}
            for name, line in sorted(self._lines.items()):
                if line.stable_version < 0:
                    continue  # provisional (mid-first-push)
                out[name] = {
                    "stable_version": line.stable_version,
                    "canary_version": line.canary_version,
                    "canary_fraction": line.canary_fraction,
                    "buffers_resident": len(line.buffers),
                    "buffers_host": len(line.host_buffers),
                    "pinned_requests": sum(line.pins.values()),
                    "requests_total": line.requests_total,
                    "tokens_total": line.tokens_total,
                }
            return out

    def metrics(self) -> Dict[str, float]:
        """Aggregate gauges/counters. Only merged into the engine's
        /metrics dict while ``active`` — single-policy mode surfaces
        zero new keys (the off-mode discipline)."""
        with self._lock:
            resident = sum(len(l.buffers) for l in self._lines.values())
            host = sum(len(l.host_buffers) for l in self._lines.values())
            pinned = sum(
                sum(l.pins.values()) for l in self._lines.values()
            )
            n = sum(
                1 for l in self._lines.values() if l.stable_version >= 0
            )
        return {
            "policy_lines": float(n),
            "policy_buffers_resident": float(resident),
            "policy_buffers_host": float(host),
            "policy_pinned_requests": float(pinned),
            "policy_pushes_total": float(self.pushes_total),
            "policy_promotes_total": float(self.promotes_total),
            "policy_demotions_total": float(self.demotions_total),
            "policy_reloads_total": float(self.reloads_total),
            "policy_staging_bytes": float(self.staging_bytes()),
        }

    def close(self) -> None:
        with self._lock:
            for line in self._lines.values():
                line.staging.close()


class CanarySplitter:
    """Router-side deterministic stable/canary splitter for one policy
    name: the same error-accumulator arithmetic the engine registry
    runs, so a fleet-side split lands within one request of exact over
    any window. Not thread-safe — callers hold the router lock."""

    __slots__ = (
        "name", "stable_version", "canary_version", "fraction", "err",
        "stable_total", "canary_total",
    )

    def __init__(
        self,
        name: str,
        stable_version: int,
        canary_version: Optional[int] = None,
        fraction: float = 0.0,
    ):
        if not (0.0 <= fraction < 1.0):
            raise ValueError(
                f"canary fraction must be in [0, 1), got {fraction}"
            )
        self.name = name
        self.stable_version = int(stable_version)
        self.canary_version = (
            int(canary_version) if canary_version is not None else None
        )
        self.fraction = float(fraction)
        self.err = 0.0
        self.stable_total = 0
        self.canary_total = 0

    def pick(self) -> str:
        """Resolve one bare-name schedule to an exact-version handle."""
        if self.canary_version is not None and self.fraction > 0.0:
            self.err += self.fraction
            if self.err >= 1.0:
                self.err -= 1.0
                self.canary_total += 1
                return f"{self.name}@v{self.canary_version}"
        self.stable_total += 1
        return f"{self.name}@v{self.stable_version}"

    def promote(self) -> None:
        if self.canary_version is None:
            raise ValueError(f"policy {self.name!r}: no canary to promote")
        self.stable_version = self.canary_version
        self.canary_version = None
        self.fraction = 0.0
        self.err = 0.0


def parse_split_spec(spec: str) -> Dict[str, CanarySplitter]:
    """Parse the router's ``--policy-split`` grammar:
    ``name=STABLE[:CANARY:FRACTION][,name=...]`` — e.g.
    ``actor=12:13:0.1,opponent=7``. Empty string → no splits."""
    out: Dict[str, CanarySplitter] = {}
    spec = (spec or "").strip()
    if not spec:
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad --policy-split entry {part!r} "
                f"(want name=STABLE[:CANARY:FRACTION])"
            )
        name, _, rhs = part.partition("=")
        if not name:
            raise ValueError(
                f"bad --policy-split entry {part!r}: empty policy name"
            )
        fields = rhs.split(":")
        try:
            if len(fields) == 1:
                out[name] = CanarySplitter(name, int(fields[0]))
            elif len(fields) == 3:
                out[name] = CanarySplitter(
                    name, int(fields[0]), int(fields[1]), float(fields[2])
                )
            else:
                raise ValueError(rhs)
        except ValueError as e:
            raise ValueError(
                f"bad --policy-split entry {part!r}: {e} "
                f"(want name=STABLE[:CANARY:FRACTION])"
            ) from None
    return out
