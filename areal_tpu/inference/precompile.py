"""Cold-start elimination: exact shape-ladder enumeration + AOT precompile.

A cold generation server burns its first minutes compiling the engine's
program ladder shape by shape as traffic discovers it (191 backend
compiles / 378 s in the r5 bench capture) — which makes autoscaler
spawns useless against a spike and turns every supervisor
full-constellation restart into a multi-minute outage. This module
closes the loop the goodput plane (r11) opened:

1. :func:`enumerate_ladder` — walks the engine config and emits the
   EXACT set of ``phase|signature`` keys the engine's
   ``goodput.dispatch_scope`` tags can produce: prefill wave rows ×
   suffix buckets × page windows × prefix bounds (including the
   signatures only MIXED waves can produce — a wave's signature is the
   componentwise max over its rows, so multi-row rungs are the join
   closure of the per-row triple set), compacted decode row buckets ×
   page windows under every reachable pipeline margin, the spec-verify
   twins, the sampling-mode rungs, the page-copy pad buckets, and the
   untagged-helper catch-all. This replaces the r11 ``_ladder_estimate``
   heuristic, so ``shape_ladder_coverage`` has a true denominator and
   ``/health`` readiness can genuinely reach 1.0.

2. :class:`Precompiler` — drives every ladder rung AHEAD of traffic by
   AOT-compiling the same jitted ``model_runner`` entry points the
   engine dispatches, with ``jax.ShapeDtypeStruct`` inputs (the
   ``parallel/feasibility.py`` machinery: ``jit(...).lower().compile()``
   — no real KV traffic, nothing executes). Every compile lands in the
   persistent XLA compilation cache (``utils/compile_cache.py``), so the
   engine's first real dispatch per shape is a disk retrieval, not an
   XLA run; each driven rung is marked in the engine's
   ``CompileTracker`` so coverage reaches 1.0 (and readiness latches)
   with ZERO traffic — even on a seeded cache where no backend compile
   fires at all. Replay mode warms only the shapes a prior run's
   ``compile_events.jsonl`` actually hit, and REFUSES a stream whose
   header fingerprint doesn't match this engine's ladder (a mismatched
   replay would silently compile garbage).

Known exclusions (documented, incremental-compile territory): vision
(mm=1) waves — their pixel-pad buckets depend on image geometry the
config doesn't bound; per-request ``top_k`` above ``sample_topk_bound``;
the post-auto-disable replay-0 twins of a speculative engine; and VLM
``rope_delta`` decode variants. A fully-precompiled engine may still
compile those shapes later — readiness LATCHES, so that never drops a
serving engine out of rotation.
"""

import dataclasses
import hashlib
import json
import re
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from areal_tpu.utils import data as data_utils
from areal_tpu.utils import logging as logging_util
from areal_tpu.utils.goodput import jax_version

logger = logging_util.getLogger("Precompile")

# precompile modes the server CLI accepts (``replay:<path>`` rides the
# "replay" mode with PrecompileConfig.replay_path)
PRECOMPILE_MODES = ("off", "ladder", "replay")

# the untagged-helper rung: eager device ops the engine loop fires
# outside any dispatch scope (state gathers/scatters, logits selects)
# all attribute to the thread-default tracker under this one key
ENGINE_MISC_RUNG = ("engine", "")


class ReplayMismatchError(RuntimeError):
    """A compile_events stream's header does not match this engine's
    ladder fingerprint — replaying it would compile (and cache) programs
    this engine can never dispatch, or miss the ones it will."""


def resolve_chunk_budget(config) -> int:
    """Resolved per-dispatch prefill token budget for chunked prefill
    (r15); 0 = chunking off or unavailable. ONE source of truth shared
    by the engine's admission cap and this module's ladder enumerator —
    drift between the two would put unenumerated chunk shapes on the
    serving path.

    The budget is floored to a page multiple (chunk commits publish
    FULL pages so both cache modes — including the flat registry's
    full-page-only claims — resume exactly at the commit), must be at
    least ``prefix_reuse_min`` (a committed prefix the cache refuses to
    match would re-prefill from zero forever), and must leave something
    to split (below ``max_model_len``)."""
    if not bool(getattr(config, "chunked_prefill", False)):
        return 0
    reuse = int(getattr(config, "prefix_reuse_min", 0))
    if reuse <= 0:
        return 0  # no prefix cache, no chunk-resume point
    bs = int(config.page_size)
    budget = int(getattr(config, "prefill_chunk_tokens", 0))
    if budget <= 0:
        budget = 2 * int(config.prefill_chunk)  # auto
    budget = max(bs, (budget // bs) * bs)
    if budget < reuse:
        return 0  # committed chunks would never match the claim floor
    if budget >= int(config.max_model_len):
        return 0  # nothing to split
    return budget


# --------------------------------------------------------------------------
# Signature formatting — ONE source of truth shared with the engine's
# dispatch_scope tags (engine.py imports these; drift between what the
# engine stamps and what the enumerator emits would silently break
# coverage, so both sides call the same functions)
# --------------------------------------------------------------------------
def prefill_sig(rows: int, tp: int, pps: int, pfb: int, mm: int) -> str:
    return f"rows{rows}|tp{tp}|pps{pps}|pfb{pfb}|mm{mm}"


def decode_sig(rows: int, steps: int, pps: int, replay: int) -> str:
    return f"rows{rows}|steps{steps}|pps{pps}|replay{replay}"


def spec_sig(rows: int, k: int, pps: int, replay: int) -> str:
    return f"rows{rows}|k{k}|pps{pps}|replay{replay}"


def sample_sig(topk: int) -> str:
    return f"topk{topk}"


def copy_sig(pad: int) -> str:
    return f"pad{pad}"


def kv_gather_sig(pad: int) -> str:
    """KV tier demotion gather (r16). Attribution-only: demotion and
    promotion are host-driven copies off the request path, so their
    programs are NOT ladder rungs — no new precompile shapes."""
    return f"pad{pad}"


def kv_scatter_sig(pad: int) -> str:
    """KV tier promotion / shipping-import scatter (r16). Attribution-
    only, same rationale as kv_gather_sig."""
    return f"pad{pad}"


_SIG_RE = re.compile(r"([a-z]+)(-?\d+)")


def parse_signature(signature: str) -> Optional[Dict[str, int]]:
    """``rows8|steps8|pps16|replay0`` → {"rows": 8, ...}; None when the
    string doesn't parse (free-form signatures stay mark-only)."""
    out: Dict[str, int] = {}
    for part in signature.split("|"):
        m = _SIG_RE.fullmatch(part)
        if m is None:
            return None
        out[m.group(1)] = int(m.group(2))
    return out or None


@dataclasses.dataclass(frozen=True)
class Rung:
    phase: str
    signature: str

    @property
    def key(self) -> str:
        return f"{self.phase}|{self.signature}"


# --------------------------------------------------------------------------
# Derived engine geometry (mirrors GenerationEngine.__init__ exactly;
# the engine passes its own resolved values where they depend on
# runtime state such as device platform)
# --------------------------------------------------------------------------
@dataclasses.dataclass
class LadderSpace:
    """Everything the enumerator (and the precompiler's argument
    builder) needs, derived once from (JaxGenConfig, ModelConfig)."""

    m: int  # max_model_len
    q: int  # prefill bucket quantum
    kv: int  # kv_bucket
    bs: int  # page_size
    num_pages: int
    mpps: int  # max_pages_per_seq
    s: int  # max_num_seqs
    wave: int
    steps: int  # decode_chunk
    depth: int  # decode_pipeline
    compact: bool
    min_rows: int
    spec: bool
    k: int  # verify window (spec only)
    replay: int
    reuse_min: int
    grain: int  # claim offset alignment (0 = prefix reuse off)
    p_max: int  # largest admissible prompt length
    topk_values: Tuple[int, ...]
    vision: bool
    # chunked prefill (r15): resolved per-dispatch suffix token budget
    # (0 = off). With chunking on, every prefill row's suffix is capped
    # here, and page-floored chunk-end triples join the reachable set
    chunk: int = 0


def derive_space(config, model_config, single_device: bool = True) -> LadderSpace:
    m = int(config.max_model_len)
    bs = int(config.page_size)
    num_pages = int(config.num_pages)
    if num_pages <= 0:  # engine auto-provisioning formula
        num_pages = int(config.max_num_seqs) * (-(-m // bs)) + 1
    mpps = -(-m // bs)
    s = max(1, int(config.max_num_seqs))
    steps = max(1, int(config.decode_chunk))
    sc = getattr(config, "spec", None)
    spec = bool(
        sc is not None
        and sc.enabled
        and single_device
        and not model_config.is_moe
        and int(config.decode_chunk) >= 2
    )
    k = min(max(1, sc.max_draft), steps - 1) + 1 if spec else 0
    compact = bool(getattr(config, "decode_compact", True)) and single_device
    reuse_min = int(getattr(config, "prefix_reuse_min", 0))
    if reuse_min > 0:
        if getattr(config, "prefix_cache_mode", "radix") == "radix":
            from areal_tpu.ops.paged_attention import pack_factor

            grain = pack_factor(model_config.head_dim)
        else:
            grain = bs  # flat registry: full-page claims only
    else:
        grain = 0
    bound = int(config.sample_topk_bound)
    topk_values = (-1, 0 if bound <= 0 else bound)
    return LadderSpace(
        m=m,
        q=min(int(config.prefill_chunk), m),
        kv=int(config.kv_bucket),
        bs=bs,
        num_pages=num_pages,
        mpps=mpps,
        s=s,
        wave=max(1, int(config.admit_wave)),
        steps=steps,
        depth=max(0, int(config.decode_pipeline)),
        compact=compact,
        min_rows=max(1, int(config.decode_compact_min_rows)),
        spec=spec,
        k=k,
        replay=steps - 1 if spec else 0,
        reuse_min=reuse_min,
        grain=grain,
        p_max=max(1, min(m - 1, (num_pages - 1) * bs)),
        topk_values=topk_values,
        vision=model_config.vision is not None,
        chunk=resolve_chunk_budget(config),
    )


# --------------------------------------------------------------------------
# Exact enumeration
# --------------------------------------------------------------------------
def _pow2ceil(n: int) -> int:
    return 1 << (max(1, n) - 1).bit_length()


def _step_values(f, lo: int, hi: int) -> List[int]:
    """Distinct values of a NONDECREASING integer step function over
    [lo, hi], by boundary bisection — O(values × log range) instead of
    O(range), so 128k-token ladders enumerate in microseconds."""
    out: List[int] = []
    x = lo
    while x <= hi:
        v = f(x)
        out.append(v)
        # find the last x' in [x, hi] with f(x') == v
        a, b = x, hi
        while a < b:
            mid = (a + b + 1) // 2
            if f(mid) == v:
                a = mid
            else:
                b = mid - 1
        x = a + 1
    return out


def _prefill_rows(sp: LadderSpace) -> List[int]:
    top = _pow2ceil(min(sp.wave, sp.s))
    rows = [1]
    r = 2
    while r <= top:
        rows.append(r)
        r *= 2
    return rows


def _decode_rows(sp: LadderSpace) -> List[int]:
    if not sp.compact:
        return [sp.s]
    out: Set[int] = set()
    r = _pow2ceil(sp.min_rows)
    while r < sp.s:
        out.add(min(r, sp.s))
        r *= 2
    out.add(sp.s)
    return sorted(out)


def _decode_margins(sp: LadderSpace) -> List[int]:
    """Reachable page-growth margins for a REGULAR decode dispatch:
    the new chunk plus every in-flight chunk's worst case. At most one
    verify chunk can be in flight (verify dispatches only on an empty
    pipeline), regular chunks stack to ``decode_pipeline``."""
    out: Set[int] = set()
    for y in (0, 1) if sp.spec else (0,):
        for x in range(1, sp.depth + 2 - y):
            out.add(x * sp.steps + y * sp.k)
    return sorted(out)


def _pages_bound_value(sp: LadderSpace, tokens: int) -> int:
    t = min(sp.m, data_utils.next_bucket_size(tokens, sp.kv))
    return min(-(-t // sp.bs), sp.mpps)


def _decode_pps(sp: LadderSpace, margins: List[int]) -> List[int]:
    # cached length at dispatch ∈ [1, m]; margins small — the token
    # range is contiguous, so one boundary walk covers every margin
    lo = 1 + min(margins)
    hi = sp.m + max(margins)
    return _step_values(lambda t: _pages_bound_value(sp, t), lo, hi)


def _aligned_offsets(sp: LadderSpace) -> Tuple[int, int, int]:
    """(o_min, o_max, grain) of reachable nonzero claim offsets, or
    (0, -1, 0) when prefix reuse is off. Claim offsets are multiples of
    the registry grain (radix: pool row; flat: page) totalling at least
    ``prefix_reuse_min`` matched tokens, and always leave >= 1 prompt
    token uncached."""
    if sp.grain <= 0:
        return 0, -1, 0
    g = sp.grain
    o_min = -(-max(sp.reuse_min, 1) // g) * g
    o_max = ((sp.p_max - 1) // g) * g
    return o_min, o_max, g


def _prefill_triples(sp: LadderSpace) -> Set[Tuple[int, int, int]]:
    """Per-ROW (tp, pps, pfb) contribution set R: one element per
    reachable (prompt_len, claim_offset) bucket combination. A wave's
    signature is the componentwise max over its rows (all three
    components are monotone step functions of their inputs), so the
    multi-row rungs are joins over this set (see _join_* below)."""

    def tp_of(suffix: int) -> int:
        return min(data_utils.next_bucket_size(suffix, sp.q), sp.m)

    def pps_of(p: int) -> int:
        return min(
            max(1, -(-data_utils.next_bucket_size(p, sp.kv) // sp.bs)),
            sp.mpps,
        )

    def pfb_of(o: int) -> int:
        return 0 if o <= 0 else min(
            sp.m, data_utils.next_bucket_size(o, sp.kv)
        )

    triples: Set[Tuple[int, int, int]] = set()
    o_min, o_max, g = _aligned_offsets(sp)
    offsets = [0] + (
        list(range(o_min, o_max + 1, g)) if g > 0 and o_min <= o_max else []
    )
    # chunked prefill (r15): a row whose suffix exceeds the chunk
    # budget is capped at a page-floored end — its suffix never exceeds
    # the budget, and its (pps, table) window covers only the committed
    # end. The chunk-commit offsets themselves (page multiples >= the
    # budget) are already in the claim-offset grid: commits publish
    # full pages and the resolved budget is >= prefix_reuse_min, so
    # every continuation claim lands on a grain multiple the grid
    # enumerates. Documented exclusion: the stall-escape valve (a
    # continuation whose claims regressed twice admits its remainder
    # WHOLE) can dispatch an uncapped suffix under cache thrash —
    # readiness latches, so that lone incremental compile never drops
    # a serving engine out of rotation.
    cap = sp.chunk
    for o in offsets:
        pfb = pfb_of(o)
        # for fixed o both tp(p - o) and pps(p) are nondecreasing step
        # functions of p — walk their merged boundaries
        lo = o + 1
        hi = sp.p_max if cap <= 0 else min(sp.p_max, o + cap)
        if lo <= hi:
            x = lo
            while x <= hi:
                pair = (tp_of(x - o), pps_of(x))
                triples.add((pair[0], pair[1], pfb))
                a, b = x, hi
                while a < b:
                    mid = (a + b + 1) // 2
                    if (tp_of(mid - o), pps_of(mid)) == pair:
                        a = mid
                    else:
                        b = mid - 1
                x = a + 1
        if cap > 0 and o + cap < sp.p_max:
            # chunk-capped row at this offset: exactly one reachable
            # triple — end is the page-floored chunk boundary (the
            # engine's ``end = ((off + budget) // bs) * bs``)
            e = ((o + cap) // sp.bs) * sp.bs
            if e > o:
                triples.add((tp_of(e - o), pps_of(e), pfb))
    return triples


class _JoinIndex:
    """Dominance indices over the per-row triple set: answers the
    witness queries the join-reachability characterization needs.

    A wave of rows {r_i} ⊆ R produces signature T = componentwise max.
    T is a join of ≤ n elements iff each coordinate's max is witnessed
    by some row whose OTHER coordinates are dominated by T — with at
    most n distinct witnesses. n >= 3 (row buckets >= 4) reduces to the
    full closure test (one witness per coordinate); n == 2 additionally
    requires one row to witness two coordinates at once."""

    def __init__(self, triples: Set[Tuple[int, int, int]]):
        self.triples = triples
        self.by_tp: Dict[int, List[Tuple[int, int]]] = {}
        self.by_pps: Dict[int, List[Tuple[int, int]]] = {}
        self.by_pfb: Dict[int, List[Tuple[int, int]]] = {}
        self.min3: Dict[Tuple[str, int, int], int] = {}
        for a, b, c in triples:
            self.by_tp.setdefault(a, []).append((b, c))
            self.by_pps.setdefault(b, []).append((a, c))
            self.by_pfb.setdefault(c, []).append((a, b))
            for key, val in (
                (("tp_pps", a, b), c),
                (("tp_pfb", a, c), b),
                (("pps_pfb", b, c), a),
            ):
                if val < self.min3.get(key, 1 << 60):
                    self.min3[key] = val
        # Pareto frontiers (minimal pairs) for the dominated-pair tests
        for d in (self.by_tp, self.by_pps, self.by_pfb):
            for key, pairs in d.items():
                pairs.sort()
                frontier: List[Tuple[int, int]] = []
                best = 1 << 60
                for u, v in pairs:
                    if v < best:
                        frontier.append((u, v))
                        best = v
                d[key] = frontier

    @staticmethod
    def _dominated(frontier: List[Tuple[int, int]], u: int, v: int) -> bool:
        """∃ (x, y) in the indexed set with x <= u and y <= v."""
        for x, y in frontier:
            if x > u:
                return False
            if y <= v:
                return True
        return False

    def witness(self, coord: str, val: int, u: int, v: int) -> bool:
        d = {"tp": self.by_tp, "pps": self.by_pps, "pfb": self.by_pfb}[
            coord
        ]
        fr = d.get(val)
        return fr is not None and self._dominated(fr, u, v)

    def pair_witness(self, key: str, x: int, y: int, bound: int) -> bool:
        """∃ row witnessing coordinates (x, y) of `key` exactly with the
        remaining coordinate <= bound."""
        return self.min3.get((key, x, y), 1 << 60) <= bound

    def closure_member(self, a: int, b: int, c: int) -> bool:
        return (
            self.witness("tp", a, b, c)
            and self.witness("pps", b, a, c)
            and self.witness("pfb", c, a, b)
        )

    def join2_member(self, a: int, b: int, c: int) -> bool:
        if (a, b, c) in self.triples:
            return True
        return (
            (
                self.pair_witness("tp_pps", a, b, c)
                and self.witness("pfb", c, a, b)
            )
            or (
                self.pair_witness("tp_pfb", a, c, b)
                and self.witness("pps", b, a, c)
            )
            or (
                self.pair_witness("pps_pfb", b, c, a)
                and self.witness("tp", a, b, c)
            )
        )


def _copy_pads(sp: LadderSpace) -> List[int]:
    """Page-copy dispatch pad buckets. Copies exist when pages can hold
    a partial tail (sibling fan-out, needs >= 2 slots) or a mid-page COW
    claim (radix reuse with a sub-page grain)."""
    if sp.bs <= 1:
        return []
    sibling = sp.s >= 2
    cow = sp.grain > 0 and sp.grain < sp.bs
    if not (sibling or cow):
        return []
    max_copies = 1
    if sibling:
        max_copies = max(max_copies, sp.s - 1)
    if cow:
        max_copies = max(max_copies, min(sp.wave, sp.s))
    top = data_utils.next_bucket_size(max_copies, 8)
    return list(range(8, top + 1, 8))


# the enumeration is a pure function of the derived LadderSpace, and
# engines construct constantly in tests — memoize per space (a few
# hundred ms per distinct serving shape, paid once per process)
_LADDER_MEMO: Dict[Tuple, List[Rung]] = {}


def enumerate_ladder(
    config,
    model_config,
    single_device: bool = True,
) -> List[Rung]:
    """The EXACT set of (phase, signature) keys this engine's dispatch
    scopes can stamp under text traffic — the shape_ladder_coverage
    denominator AND the precompiler's work list. See the module
    docstring for the documented exclusions (vision waves, oversized
    per-request top_k, post-auto-disable spec twins)."""
    sp = derive_space(config, model_config, single_device)
    memo_key = dataclasses.astuple(sp)
    cached = _LADDER_MEMO.get(memo_key)
    if cached is not None:
        return list(cached)
    rungs: List[Rung] = []

    # --- prefill: rows × join-reachable (tp, pps, pfb) triples ---
    triples = _prefill_triples(sp)
    idx = _JoinIndex(triples)
    tp_vals = sorted(idx.by_tp)
    pps_vals = sorted(idx.by_pps)
    pfb_vals = sorted(idx.by_pfb)
    candidates = [
        (a, b, c)
        for a in tp_vals
        for b in pps_vals
        for c in pfb_vals
    ]
    closure = (
        {t for t in candidates if idx.closure_member(*t)}
        if len(_prefill_rows(sp)) > 2
        else set()
    )
    join2 = (
        {t for t in candidates if idx.join2_member(*t)}
        if len(_prefill_rows(sp)) > 1
        else set()
    )
    for rows in _prefill_rows(sp):
        if rows == 1:
            reach = triples
        elif rows == 2:
            reach = join2
        else:
            reach = closure
        for (tp, pps, pfb) in sorted(reach):
            rungs.append(
                Rung("prefill", prefill_sig(rows, tp, pps, pfb, 0))
            )

    # --- decode (+ spec verify twins) ---
    dec_rows = _decode_rows(sp)
    for pps in _decode_pps(sp, _decode_margins(sp)):
        for rows in dec_rows:
            rungs.append(
                Rung("decode", decode_sig(rows, sp.steps, pps, sp.replay))
            )
    if sp.spec:
        for pps in _decode_pps(sp, [sp.k]):
            for rows in dec_rows:
                rungs.append(
                    Rung("spec_verify", spec_sig(rows, sp.k, pps, sp.replay))
                )

    # --- sampling modes + page-copy pads + untagged helpers ---
    for topk in sorted(set(sp.topk_values)):
        rungs.append(Rung("sample", sample_sig(topk)))
    for pad in _copy_pads(sp):
        rungs.append(Rung("copy", copy_sig(pad)))
    rungs.append(Rung(*ENGINE_MISC_RUNG))
    _LADDER_MEMO[memo_key] = rungs
    return list(rungs)


def ladder_fingerprint(
    config,
    model_config,
    single_device: bool = True,
    attn_impl: Optional[str] = None,
    platform: Optional[str] = None,
) -> str:
    """Stable identity of (ladder keys × program contents): the rung
    set plus everything that changes the compiled programs under a
    fixed rung key — model geometry, dtype, resolved attention backend,
    device platform, jax version. Written into the compile_events
    header; replay refuses a mismatch. Pass the engine's RESOLVED
    ``attn_impl`` (config "auto" resolves per platform — two machines
    with the same config can run different programs)."""
    sp = derive_space(config, model_config, single_device)
    if platform is None:
        try:
            import jax

            platform = jax.devices()[0].platform
        except Exception as e:  # pragma: no cover - stub environments
            logger.warning(f"no jax backend for fingerprint: {e}")
            platform = "unknown"
    memo_key = (
        dataclasses.astuple(sp), config.dtype,
        attn_impl or config.attn_impl, platform,
        getattr(config, "pool_layout", "auto"), model_config,
    )
    cached = _FINGERPRINT_MEMO.get(memo_key)
    if cached is not None:
        return cached
    rungs = enumerate_ladder(config, model_config, single_device)
    ident = {
        "rungs": sorted(r.key for r in rungs),
        "jax": jax_version(),
        "dtype": config.dtype,
        "attn_impl": attn_impl or config.attn_impl,
        "platform": platform,
        "pool_layout": getattr(config, "pool_layout", "auto"),
        "pages": [sp.num_pages, sp.bs],
        "model": [
            model_config.family,
            model_config.num_layers,
            model_config.hidden_size,
            model_config.intermediate_size,
            model_config.num_heads,
            model_config.num_kv_heads,
            model_config.head_dim,
            model_config.vocab_size,
        ],
        "single_device": bool(single_device),
    }
    fp = hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()
    ).hexdigest()[:16]
    _FINGERPRINT_MEMO[memo_key] = fp
    return fp


_FINGERPRINT_MEMO: Dict[Tuple, str] = {}


# --------------------------------------------------------------------------
# AOT precompiler
# --------------------------------------------------------------------------
class Precompiler:
    """Drives ladder rungs through the engine's jitted entry points with
    ``jax.ShapeDtypeStruct`` inputs: ``lower().compile()`` populates the
    persistent XLA compilation cache without executing anything, and
    each driven rung is marked in the engine's CompileTracker so
    coverage (and /health readiness) reflects the warm ladder."""

    def __init__(self, engine):
        self.engine = engine
        self.sp = derive_space(
            engine.config, engine.model_config, engine.mesh is None
        )
        self._sds_ready = False

    # -- shared ShapeDtypeStructs (built lazily, shapes only) ----------
    def _build_sds(self):
        if self._sds_ready:
            return
        import jax

        eng = self.engine

        def sds_of(a):
            # single-device: a bare SDS lowers exactly like the engine's
            # committed arrays — attaching SingleDeviceSharding would
            # stamp "{replicated}" arg annotations into the HLO and
            # break cache-key identity with the real dispatches. Under
            # TP the real arrays carry NamedShardings that DO annotate,
            # so there the SDS must carry them too.
            if eng.mesh is None:
                return jax.ShapeDtypeStruct(a.shape, a.dtype)
            return jax.ShapeDtypeStruct(
                a.shape, a.dtype, sharding=getattr(a, "sharding", None)
            )

        self.params_sds = jax.tree_util.tree_map(sds_of, eng.params)
        self.cache_sds = jax.tree_util.tree_map(sds_of, eng.cache)
        self.last_rows_sds = jax.tree_util.tree_map(
            sds_of, eng._last_rows
        )
        self.key_sds = jax.ShapeDtypeStruct(
            eng._rng_key.shape, eng._rng_key.dtype
        )
        self._logits_dtype = None  # filled by the first prefill rung
        self._sds_ready = True

    def _vec(self, n, dtype):
        import jax
        import jax.numpy as jnp

        dt = {
            "i32": jnp.int32,
            "f32": jnp.float32,
            "bool": jnp.bool_,
        }[dtype]
        return jax.ShapeDtypeStruct((n,), dt)

    def _mat(self, shape, dtype):
        import jax
        import jax.numpy as jnp

        dt = {"i32": jnp.int32, "f32": jnp.float32}[dtype]
        return jax.ShapeDtypeStruct(tuple(shape), dt)

    # -- merge chain (assemble_rows + write_rows), shared by every
    # dispatch family — mirrors model_runner.merge_tokens on shapes
    def _compile_merge(self, tables, pos0, counts, kbuf, vbuf, slot_ids):
        import jax

        from areal_tpu.inference import model_runner
        from areal_tpu.ops.paged_attention import layout_from_pool

        eng = self.engine
        k_shape = eng.cache["k"].shape
        nl, n, t, hkv, d = kbuf.shape
        merged, f = layout_from_pool(k_shape, hkv, d)
        _, _, num_pages, prow, _ = k_shape
        args = (
            tables, pos0, counts, kbuf, vbuf, self.last_rows_sds,
            slot_ids,
        )
        kw = dict(num_pages=num_pages, prow=prow, pack=f, merge=merged)
        model_runner.assemble_rows.lower(*args, **kw).compile()
        # statics can't ride eval_shape's abstraction — bind them in a
        # closure and abstract only the array arguments
        dest, kw_buf, vw_buf, _ = jax.eval_shape(
            lambda *a: model_runner.assemble_rows(*a, **kw), *args
        )
        model_runner.write_rows.lower(
            self.cache_sds, dest, kw_buf, vw_buf
        ).compile()

    # -- per-family drivers --------------------------------------------
    def _drive_prefill(self, p: Dict[str, int]):
        import jax

        from areal_tpu.inference import model_runner

        eng = self.engine
        rows, tp, pps = p["rows"], p["tp"], p["pps"]
        tokens = self._mat((rows, tp), "i32")
        offsets = self._vec(rows, "i32")
        true_lens = self._vec(rows, "i32")
        tables = self._mat((rows, pps), "i32")
        slot_ids = self._vec(rows, "i32")
        mc = eng.model_config
        arrays = (
            self.params_sds, self.cache_sds, tokens, offsets,
            true_lens, tables,
        )
        kw = dict(prefix_bound=p["pfb"], embeds=None, pos3=None)
        model_runner.prefill_forward.lower(
            arrays[0], mc, *arrays[1:], **kw
        ).compile()
        logits, k_sfx, v_sfx = jax.eval_shape(
            lambda pp, cc, *a: model_runner.prefill_forward(
                pp, mc, cc, *a, **kw
            ),
            *arrays,
        )
        self._logits_dtype = logits.dtype
        self._compile_merge(
            tables, offsets, true_lens, k_sfx, v_sfx, slot_ids
        )

    def _decode_common(self, rows: int):
        st = {
            "pos0": self._vec(rows, "i32"),
            "tokens": self._vec(rows, "i32"),
            "active": self._vec(rows, "bool"),
            "remaining": self._vec(rows, "i32"),
            "no_stop": self._vec(rows, "i32"),
            "stops": self._mat((rows, 8), "i32"),
            "temp": self._vec(rows, "f32"),
            "top_p": self._vec(rows, "f32"),
            "top_k": self._vec(rows, "i32"),
            "greedy": self._vec(rows, "bool"),
            "slot_ids": self._vec(rows, "i32"),
        }
        return st

    def _drive_decode(self, p: Dict[str, int]):
        import jax

        from areal_tpu.inference import model_runner

        eng = self.engine
        rows, steps, pps, replay = (
            p["rows"], p["steps"], p["pps"], p["replay"],
        )
        st = self._decode_common(rows)
        tables = self._mat((rows, pps), "i32")
        align = self._vec(rows, "i32") if replay > 0 else None
        mc = eng.model_config
        out = None
        for topk in sorted(set(self.sp.topk_values)):
            arrays = (
                self.params_sds, self.cache_sds, tables, st["pos0"],
                st["tokens"], st["active"], st["remaining"],
                st["no_stop"], st["stops"], self.key_sds, st["temp"],
                st["top_p"], st["top_k"], st["greedy"],
            )
            kw = dict(
                steps=steps, topk_bound=topk, attn_impl=eng._attn_impl,
                ppcb=eng.config.pages_per_compute_block,
                spb=eng.config.slots_per_block, rope_delta=None,
                slot_ids=st["slot_ids"], align_base=align, replay=replay,
            )
            model_runner._decode_multi_forward.lower(
                arrays[0], mc, *arrays[1:], **kw
            ).compile()
            out = jax.eval_shape(
                lambda pp, cc, *a: model_runner._decode_multi_forward(
                    pp, mc, cc, *a, **kw
                ),
                *arrays,
            )
        (toks, logps, emitted, active_a, _, _, _, kbuf, vbuf, clen, _) = out
        self._compile_merge(
            tables, st["pos0"], clen, kbuf, vbuf, st["slot_ids"]
        )
        model_runner.pack_host.lower(
            toks, logps, emitted, active_a
        ).compile()

    def _drive_spec(self, p: Dict[str, int]):
        import jax

        from areal_tpu.inference import model_runner

        eng = self.engine
        rows, k, pps, replay = p["rows"], p["k"], p["pps"], p["replay"]
        st = self._decode_common(rows)
        tables = self._mat((rows, pps), "i32")
        draft = self._mat((rows, k - 1), "i32")
        draft_len = self._vec(rows, "i32")
        align = self._vec(rows, "i32") if replay > 0 else None
        mc = eng.model_config
        out = None
        for topk in sorted(set(self.sp.topk_values)):
            arrays = (
                self.params_sds, self.cache_sds, tables, st["pos0"],
                st["tokens"], draft, draft_len, st["active"],
                st["remaining"], st["no_stop"], st["stops"],
                self.key_sds, st["temp"], st["top_p"], st["top_k"],
                st["greedy"],
            )
            kw = dict(
                k=k, topk_bound=topk, attn_impl=eng._attn_impl,
                ppcb=eng.config.pages_per_compute_block,
                spb=eng.config.slots_per_block, rope_delta=None,
                slot_ids=st["slot_ids"], align_base=align, replay=replay,
            )
            model_runner._spec_verify_forward.lower(
                arrays[0], mc, *arrays[1:], **kw
            ).compile()
            out = jax.eval_shape(
                lambda pp, cc, *a: model_runner._spec_verify_forward(
                    pp, mc, cc, *a, **kw
                ),
                *arrays,
            )
        (toks, logps, emitted, active_a, _, _, _, kbuf, vbuf, clen, _) = out
        self._compile_merge(
            tables, st["pos0"], clen, kbuf, vbuf, st["slot_ids"]
        )
        model_runner.pack_host.lower(
            toks, logps, emitted, active_a
        ).compile()

    def _drive_sample(self, p: Dict[str, int]):
        import jax
        import jax.numpy as jnp

        from areal_tpu.inference import model_runner

        eng = self.engine
        ldt = self._logits_dtype or jnp.float32
        logits = jax.ShapeDtypeStruct(
            (self.sp.s, eng.model_config.vocab_size), ldt
        )
        st = self._decode_common(self.sp.s)
        topk = p["topk"]
        model_runner.sample_tokens.lower(
            logits, self.key_sds, st["temp"], st["top_p"], st["top_k"],
            st["greedy"], topk_bound=topk,
        ).compile()
        toks, logps = jax.eval_shape(
            lambda *a: model_runner.sample_tokens(*a, topk_bound=topk),
            logits, self.key_sds, st["temp"], st["top_p"],
            st["top_k"], st["greedy"],
        )
        model_runner.pack_host.lower(toks, logps).compile()

    def _drive_copy(self, p: Dict[str, int]):
        from areal_tpu.inference import model_runner

        pad = p["pad"]
        model_runner.copy_pages.lower(
            self.cache_sds, self._vec(pad, "i32"), self._vec(pad, "i32")
        ).compile()

    _DRIVERS = {
        "prefill": (_drive_prefill, ("rows", "tp", "pps", "pfb", "mm")),
        "decode": (_drive_decode, ("rows", "steps", "pps", "replay")),
        "spec_verify": (_drive_spec, ("rows", "k", "pps", "replay")),
        "sample": (_drive_sample, ("topk",)),
        "copy": (_drive_copy, ("pad",)),
    }

    # -- entry points ---------------------------------------------------
    def run(
        self, mode: str, replay_path: str = ""
    ) -> Dict[str, Any]:
        """Drive the full enumerated ladder (``mode="ladder"``) or a
        prior run's observed shapes (``mode="replay"``). Returns a
        summary dict; individual rung failures degrade gracefully (a
        precompile must never take serving down), a mismatched replay
        header raises :class:`ReplayMismatchError` before any work."""
        if mode not in ("ladder", "replay"):
            raise ValueError(
                f"precompile mode {mode!r}: expected ladder | replay"
            )
        from areal_tpu.utils import goodput

        eng = self.engine
        if mode == "ladder":
            rungs = list(getattr(eng, "_ladder", None) or enumerate_ladder(
                eng.config, eng.model_config, eng.mesh is None
            ))
        else:
            rungs = self.replay_rungs(replay_path)
        self._build_sds()
        # order: prefill rungs first (they discover the logits dtype the
        # sample rungs reuse), then everything else as enumerated
        rungs.sort(key=lambda r: r.phase != "prefill")
        t0 = time.monotonic()
        tr = eng.compiles
        c0, u0 = tr.compiles_total, tr.uncached_compiles_total
        driven = failed = marked = 0
        for rung in rungs:
            driver_entry = self._DRIVERS.get(rung.phase)
            params = parse_signature(rung.signature)
            if (
                driver_entry is None
                or params is None
                or (rung.phase == "prefill" and params.get("mm"))
            ):
                # untagged-helper catch-all, free-form signatures, and
                # vision waves (replayed mm=1 rungs — their pixel pads
                # aren't in the signature): coverage-mark only
                tr.mark_compiled(rung.phase, rung.signature)
                marked += 1
                continue
            driver, fields = driver_entry
            if any(f not in params for f in fields if f != "mm"):
                tr.mark_compiled(rung.phase, rung.signature)
                marked += 1
                continue
            try:
                with goodput.dispatch_scope(
                    tr, rung.phase, rung.signature
                ):
                    driver(self, params)
                driven += 1
                # covered: SUCCESSFUL rungs only. Marking failures
                # would let a systematic driver breakage latch a
                # stone-cold server ready at coverage 1.0 — failed
                # rungs instead keep coverage short and readiness
                # degrades to the r11 traffic-driven rules (quiet /
                # completed-requests), exactly like mode=off.
                tr.mark_compiled(rung.phase, rung.signature)
            except Exception as e:  # degrade: skip the rung, keep going
                failed += 1
                logger.warning(f"precompile rung {rung.key} failed: {e}")
        wall = time.monotonic() - t0
        # NOTE: deliberately NOT booked into the engine GoodputLedger —
        # the precompiler runs on its own thread, usually concurrent
        # with a serving loop that accounts its own wall; adding this
        # thread's wall on top would break the fractions-sum-to-1.0
        # invariant. The warm cost is visible in this summary, the
        # compile-events stream, and the tracker's compile seconds.
        summary = {
            "mode": mode,
            "rungs": len(rungs),
            "driven": driven,
            "marked": marked,
            "failed": failed,
            "wall_s": round(wall, 3),
            "backend_compiles": tr.compiles_total - c0,
            "uncached_compiles": tr.uncached_compiles_total - u0,
            "coverage": tr.coverage(),
        }
        tr.append_event({"kind": "precompile", **summary})
        logger.info(
            f"precompile({mode}): {driven} rungs driven, {marked} "
            f"marked, {failed} failed in {wall:.1f}s "
            f"({summary['backend_compiles']} backend compiles, "
            f"{summary['uncached_compiles']} uncached)"
        )
        return summary

    def replay_rungs(self, path: str) -> List[Rung]:
        """Parse a compile_events stream into the deduped rung list it
        recorded, refusing a missing/mismatched header fingerprint."""
        if not path:
            raise ValueError("replay precompile needs an events path")
        eng = self.engine
        want = ladder_fingerprint(
            eng.config, eng.model_config, eng.mesh is None,
            attn_impl=getattr(eng, "_attn_impl", None),
        )
        seen: Set[Tuple[str, str]] = set()
        rungs: List[Rung] = []
        header = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if header is None:
                    if rec.get("kind") != "header":
                        raise ReplayMismatchError(
                            f"{path} has no header line — refusing to "
                            f"replay an unfingerprinted compile stream"
                        )
                    header = rec
                    if rec.get("fingerprint") != want:
                        raise ReplayMismatchError(
                            f"{path} was recorded for ladder "
                            f"{rec.get('fingerprint')!r} but this engine "
                            f"is {want!r} (config/model/jax changed) — "
                            f"replaying it would compile garbage"
                        )
                    continue
                if rec.get("kind") != "compile":
                    continue
                key = (str(rec.get("phase")), str(rec.get("signature")))
                if key not in seen:
                    seen.add(key)
                    rungs.append(Rung(*key))
        if header is None:
            raise ReplayMismatchError(
                f"{path} is empty — nothing to replay"
            )
        return rungs
